"""Genuinely-asynchronous distributed trainers (host-loop + PS hub).

The mesh trainers in :mod:`distkeras_tpu.trainers` realize the reference's
async algorithms as deterministic synchronous serializations — one fused
XLA program, the right default on a TPU slice.  This module is the other
execution option from SURVEY.md §7 ("hard parts", option b): a faithful
reproduction of the reference's *actual* concurrency — N workers training
independently and racing pull/commit exchanges against a parameter-server
hub (reference call stack §3.1) — for deployments where workers are
separate host processes driving their own chips over DCN, or where true
staleness behavior is being studied.

Differences from the reference's execution (same semantics, new substrate):

- each worker's ``communication_window`` minibatches compile to ONE
  ``lax.scan`` program (no per-batch Python), so the host loop only runs
  at window boundaries — exactly where the socket exchange happens anyway;
- the PS hub may be the C++ one (``native/ps_server.cpp``) — commits then
  apply outside the GIL, so in-process worker threads genuinely overlap;
- weights travel as raw float32 frames, not pickles — through the
  zero-copy flat framing path (one preallocated frame per direction,
  ``recv_into`` scatter receives; ``networking.FlatFrameCodec``);
- the exchange is PIPELINED by default (``pipeline=True``): the pull for
  window k+1 is prefetched while window k computes and commit acks
  coalesce into later receives, so wall-per-window converges toward
  max(compute, wire) instead of their sum (staleness semantics:
  ARCHITECTURE.md "Async transport");
- co-located workers may skip sockets entirely with ``transport="inproc"``
  (same center logic under the hub's lock, identical trajectories;
  sockets stay the default for multi-host authenticity).

Worker threads in one process share the single JAX runtime; with multiple
devices visible each worker pins its compute to ``devices[i % n]``, giving
real device-parallel async training in one process (the test/CI shape).

Multi-host topology (exercised by ``tests/test_multihost.py``): a
standalone hub on the head node
(``runtime/launcher.py :: start_parameter_server`` / ``distkeras-ps``),
and on every worker host one Async* trainer constructed with
``ps_address=(head, port)`` — worker-only mode: it starts no hub, drives
its local shard against the remote one, and returns the center pulled at
finish.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import distributed as dtrace
from distkeras_tpu.data.dataset import Dataset, prefetch_to_device
from distkeras_tpu.models.base import Model
from distkeras_tpu.parallel.engine import make_minibatch_step
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    InprocPSClient,
    PSClient,
    ShardedParameterServer,
    ShardedPSClient,
    _normalize_failover,
    shard_plan,
)
from distkeras_tpu.runtime.faults import WorkerPreempted
from distkeras_tpu.trainers import Trainer
from distkeras_tpu.utils import flatten_weights


class _DrainRequested(Exception):
    """Control-flow signal: the FleetController asked this worker to
    retire; unwinds the window loop into the graceful-drain handler."""

    def __init__(self, worker: int, window: int):
        super().__init__(
            f"drain requested: worker {worker} at window {window}")
        self.worker = int(worker)
        self.window = int(window)


def _make_window_fn(trainer: "AsyncDistributedTrainer", apply_fn: Callable,
                    loss: Callable, optimizer) -> Callable:
    """Jitted ``(params, opt_state, pulled, wx, wy) -> (next_params,
    opt_state, commit, mean_loss)``: one communication window of local
    steps PLUS the algorithm's window-boundary math as a single XLA
    program.

    Folding ``device_window_start`` / ``device_commit`` into the program
    keeps the worker's params and optimizer state DEVICE-RESIDENT across
    windows (round-4 verdict weak #2: the old loop round-tripped the full
    model host<->device every window and computed the commit delta in
    single-threaded host numpy).  The only per-window host<->device
    traffic left is what the PS protocol itself moves: the pulled center
    in, the commit payload out.  ``params``/``opt_state`` are donated —
    XLA reuses their buffers for the next window's state."""
    mini = make_minibatch_step(apply_fn, loss, optimizer)

    def window(params, opt_state, pulled, wx, wy):
        start = trainer.device_window_start(pulled, params)
        (after, opt_state), losses = jax.lax.scan(mini, (start, opt_state), (wx, wy))
        commit, next_params = trainer.device_commit(pulled, after)
        return next_params, opt_state, commit, jnp.mean(losses)

    return jax.jit(window, donate_argnums=(0, 1))


class AsyncDistributedTrainer(Trainer):
    """Scaffolding shared by the async family (reference §2.4's
    ``AsynchronousDistributedTrainer``): starts the PS, spawns one worker
    thread per partition, joins, returns the PS's center model."""

    def __init__(self, model, num_workers: int = 2, communication_window: int = 5,
                 native_ps: bool = False,
                 ps_address: Optional[Tuple[str, int]] = None,
                 ps_failover: Optional[Any] = None,
                 replica_of: Optional[Tuple[str, int]] = None,
                 replica_sync_timeout: float = 60.0,
                 checkpoint_interval: float = 30.0,
                 on_worker_failure: str = "raise",
                 max_worker_restarts: int = 2,
                 fault_hook: Optional[Callable[[int, int], None]] = None,
                 compress_commits: Optional[str] = None,
                 transport: str = "socket",
                 num_shards: int = 1,
                 recv_batch_depth: int = 0,
                 pipeline: bool = True,
                 max_inflight_commits: int = 2,
                 max_reconnects: Optional[int] = None,
                 reconnect_backoff: float = 0.1,
                 heartbeat_interval: Optional[float] = None,
                 elastic: bool = False,
                 ps_idle_timeout: Optional[float] = None,
                 trace_context: Optional[str] = None,
                 health_interval_s: Optional[float] = None,
                 sparse_tables: Optional[Any] = None,
                 sparse_cache_rows: Optional[int] = None,
                 adaptive: bool = False,
                 autoscale: bool = False,
                 **kwargs):
        super().__init__(model, **kwargs)
        self.num_workers = int(num_workers)
        self.communication_window = int(communication_window)
        self.native_ps = bool(native_ps)
        # transport="socket" (default): workers speak the framed wire
        # protocol — the multi-host-authentic path, also used co-located.
        # transport="inproc": co-located workers call the hub's
        # pull_direct/commit_direct under its lock — no sockets, no
        # framing; identical training trajectories (the parity property
        # tests/test_transport.py pins).  Requires owning the hub.
        # transport="shm" (ISSUE 18): the socket path plus the opt-in
        # shared-memory attach — the hub gets an shm_dir, every worker
        # client sends the action-Z capability request, and same-host
        # frames move over mmap rings instead of the kernel socket stack.
        # Byte-identical frame payloads, so trajectories match "socket"
        # exactly; a hub that declines (or a legacy hub) degrades each
        # worker independently back to plain TCP.
        if transport not in ("socket", "inproc", "shm"):
            raise ValueError(f"transport must be 'socket', 'inproc' or "
                             f"'shm', got {transport!r}")
        if transport == "inproc" and ps_address is not None:
            raise ValueError(
                "transport='inproc' requires a co-located hub (the trainer "
                "starts its own); worker-only mode with ps_address needs "
                "transport='socket'")
        self.transport = transport
        # pipeline=True (default): the pull for window k+1 is prefetched
        # while window k computes, and commit acks coalesce into later
        # receives (at most max_inflight_commits ride unacknowledged) —
        # wall-per-window converges toward max(compute, wire).  The pull
        # for k+1 then observes the center BEFORE this worker's commit k
        # (deterministic self-staleness of 1; see ARCHITECTURE.md "Async
        # transport").  pipeline=False restores the strictly serial
        # pull -> train -> commit -> ack exchange per window.
        self.pipeline = bool(pipeline)
        self.max_inflight_commits = int(max_inflight_commits)
        # "int8": workers send action-Q commits (4x fewer wire bytes,
        # error feedback client-side — see PSClient); pulls stay f32.
        # Both hubs (Python and C++) accept either commit form.
        if compress_commits not in (None, "int8"):
            raise ValueError(f"compress_commits must be None or 'int8', "
                             f"got {compress_commits!r}")
        self.compress_commits = compress_commits
        # sharded hub (ISSUE 6): num_shards > 1 partitions the center
        # across that many hubs — deterministic size-balanced leaf->shard
        # assignment (shard_plan), one hub per shard, striped pull/commit.
        # The default 1 is byte-identical to today's single-hub wire
        self.num_shards = int(num_shards)
        if self.num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        # batched hub receive (ISSUE 18): > 0 makes every trainer-owned
        # hub drain its sockets recvmmsg-style, up to this many frames per
        # syscall (falling back to plain nonblocking recvs where the
        # batched syscall is unavailable).  0 (default) keeps the exact
        # one-recv_into-per-frame receive loop
        self.recv_batch_depth = int(recv_batch_depth)
        if self.recv_batch_depth < 0:
            raise ValueError(f"recv_batch_depth must be >= 0, got "
                             f"{recv_batch_depth}")
        # lazily-created run-scoped directory the shm transport's ring
        # files live in (under /dev/shm when present, so the "file" is
        # pure page cache); cleaned up when the trainer-owned hub stops
        self._shm_dir: Optional[str] = None
        # worker-only mode (multi-host): connect to an external hub at this
        # (host, port) — or, sharded, a SEQUENCE of per-shard (host, port)
        # pairs aligned with the shard plan (num_shards defaults to the
        # sequence length) — instead of starting one; see module docstring
        if ps_address is None:
            self.ps_address = None
            self._ps_addresses: Optional[List[Tuple[str, int]]] = None
        else:
            addr = list(ps_address)
            if addr and isinstance(addr[0], (str, bytes)):
                addrs = [(str(addr[0]), int(addr[1]))]
            else:
                addrs = [(str(h), int(p)) for h, p in addr]
            if len(addrs) > 1 and self.num_shards == 1:
                self.num_shards = len(addrs)
            if len(addrs) != self.num_shards:
                raise ValueError(
                    f"ps_address has {len(addrs)} shard addresses but "
                    f"num_shards={self.num_shards}; worker-only sharded mode "
                    f"needs one (host, port) per shard")
            self._ps_addresses = addrs
            self.ps_address = (addrs[0] if len(addrs) == 1
                               else tuple(addrs))
        # hot-standby failover (ISSUE 7): per-shard standby address(es)
        # every worker client rotates to when its primary stripe dies
        # inside the reconnect budget.  Unsharded: one (host, port) pair or
        # a list of pairs; sharded: one entry per shard, aligned with
        # ps_address (None for shards without a standby)
        if ps_failover is None:
            self._ps_failover: Optional[List[List[Tuple[str, int]]]] = None
        elif self.num_shards == 1:
            self._ps_failover = [_normalize_failover(ps_failover)]
        else:
            fo = list(ps_failover)
            if fo and isinstance(fo[0], (str, bytes)):
                # a bare (host, port) pair: its length can coincide with
                # num_shards (2 shards!) and would otherwise be sliced
                # into per-shard garbage instead of erroring
                raise ValueError(
                    f"ps_failover got a single (host, port) pair but "
                    f"num_shards={self.num_shards}; sharded failover needs "
                    f"one entry per shard (None for shards without a "
                    f"standby)")
            if len(fo) != self.num_shards:
                raise ValueError(
                    f"ps_failover has {len(fo)} entries but "
                    f"num_shards={self.num_shards}; sharded failover needs "
                    f"one entry per shard (None for shards without a "
                    f"standby)")
            self._ps_failover = [_normalize_failover(e) for e in fo]
        # replica_of=(host, port): the trainer-owned hub starts as a HOT
        # STANDBY of that primary (binds, tracks the primary's center,
        # promotes itself on feed loss or first commit) — the launcher's
        # --replica-of for in-process deployments.  Python hub only;
        # single-shard only (per-shard standbys are per-shard daemons)
        self.replica_of = (None if replica_of is None
                           else (str(replica_of[0]), int(replica_of[1])))
        # how long train() waits for the standby hub's first full sync
        # before refusing to train (see the wait_synced guard below)
        self.replica_sync_timeout = float(replica_sync_timeout)
        if self.replica_of is not None:
            if ps_address is not None:
                raise ValueError("replica_of configures the trainer-owned "
                                 "hub; worker-only mode (ps_address) starts "
                                 "no hub — point ps_failover at the standby "
                                 "instead")
            if self.num_shards > 1:
                raise ValueError("replica_of requires num_shards=1 (a "
                                 "sharded deployment runs one standby "
                                 "daemon per shard primary)")
            # both hubs serve replica_of (the C++ standby runs its feed
            # thread native-side; ISSUE 11) — no native guard needed
        self.checkpoint_interval = float(checkpoint_interval)
        # failure policy (SURVEY §5 "failure detection" — the reference had
        # none; Spark silently re-ran dead executors).  "raise" surfaces the
        # first worker error after all workers drain; "continue" lets the
        # survivors finish and returns the center anyway, recording errors
        # in self.worker_errors — the hub-keeps-serving recovery mode.
        # "restart" is Spark's re-run made explicit and bounded: a crashed
        # worker is restarted up to max_worker_restarts times from the
        # hub's CURRENT center (its progress up to the last applied commit
        # survives in the center; its local divergence does not), resuming
        # at the epoch it died in; once the budget is exhausted the error
        # is recorded and the survivors finish, as with "continue".
        if on_worker_failure not in ("raise", "continue", "restart"):
            raise ValueError(f"on_worker_failure must be 'raise', 'continue' "
                             f"or 'restart', got {on_worker_failure!r}")
        self.on_worker_failure = on_worker_failure
        self.max_worker_restarts = int(max_worker_restarts)
        # client resilience knobs, threaded into every worker's PSClient
        # (socket transport only — inproc workers share the hub's process
        # and die with it): bounded reconnect with exponential backoff +
        # jitter, and heartbeat-on-idle against the hub's idle eviction.
        # Default: worker-only mode (ps_address) gets a small budget —
        # remote workers face real networks AND the standalone hub's
        # default idle eviction, and a reconnect+re-pull is semantically
        # safe — while a trainer that owns its hub fails fast (the hub
        # dying means this process is dying with it)
        if max_reconnects is None:
            max_reconnects = 5 if ps_address is not None else 0
        self.max_reconnects = int(max_reconnects)
        self.reconnect_backoff = float(reconnect_backoff)
        self.heartbeat_interval = heartbeat_interval
        # elastic=True: the hub normalizes by LIVE membership instead of
        # the configured worker count (ADAG; see ADAGParameterServer) —
        # a permanently dead worker stops diluting the survivors
        self.elastic = bool(elastic)
        # half-open-connection eviction window on the trainer-owned hub.
        # Default OFF: a trainer-owned hub only serves same-process
        # workers, whose sockets always deliver FIN on death (true
        # half-open needs a dead remote host/NIC), and a default eviction
        # window would regress runs whose first-window compile outlasts
        # it.  Standalone hubs (distkeras-ps / start_parameter_server)
        # default to 300 s — they face real networks
        self.ps_idle_timeout = ps_idle_timeout
        # distributed tracing (ISSUE #5): the job id every worker's
        # TraceContext announces over the PS wire.  None = auto-generate a
        # fresh one per train() when telemetry is on; pass an explicit id
        # to join a multi-host run's workers under one job in the merged
        # trace (all hosts must pass the same string).  Only consulted
        # while telemetry is enabled — with obs off no context exists and
        # no T frame ever leaves (pre-T hubs interoperate)
        self.trace_context = trace_context
        # live fleet health plane (ISSUE 8): every health_interval_s
        # seconds each worker pushes one compact metric report (windows,
        # rolling window wall, reconnect/failover totals) to the hub —
        # wire action M on the pipelined FIFO (socket) or a direct
        # collector fold (inproc) — where the online detectors run over
        # the per-worker sliding windows.  Default None = OFF: no M frame
        # ever leaves, so pre-M hubs interoperate byte-identically.
        # Both hubs ingest M (the C++ hub parks reports in a ring its
        # wrapper drains into the collector; ISSUE 11)
        if health_interval_s is not None:
            health_interval_s = float(health_interval_s)
            if health_interval_s <= 0:
                raise ValueError(f"health_interval_s must be positive, "
                                 f"got {health_interval_s}")
            # both hubs ingest action-M reports (the C++ hub parks them
            # in a ring its Python wrapper drains into the collector)
        self.health_interval_s = health_interval_s
        # row-sparse embedding tables (ISSUE 9): None (default) = fully
        # off, every wire byte identical to the dense stack.  "auto"
        # resolves the model spec's declared EmbeddingTable leaves
        # (models.base.sparse_leaf_indices — e.g. the embedding_classifier
        # family); an explicit iterable names flat-leaf indices directly.
        # With sparse tables on, each worker pulls only the rows its next
        # window's batch touches (wire action S/V) and commits
        # (row_ids, row_grads) pairs (U, or X under int8) — idle rows cost
        # zero wire bytes; the hub applies them under the same staleness
        # clock and commit_scale rules as dense commits
        if sparse_tables is not None and sparse_tables != "auto":
            sparse_tables = tuple(sorted({int(i) for i in sparse_tables}))
        self.sparse_tables = sparse_tables
        # (the former sparse+inproc+native guard is gone: the C++ hub now
        # serves the sparse direct pair — dk_ps_pull_sparse /
        # dk_ps_commit_sparse, ISSUE 15 — so every transport x hub cell
        # composes with sparse_tables)
        # hot-tier client caching (ISSUE 15): each worker's per-table
        # host cache becomes a bounded LRU of sparse_cache_rows rows —
        # hits are served locally (zero wire), misses fetched over the
        # sparse pull, the window's compute consumes [k, dim] row blocks
        # scattered into a device-resident mirror.  None (default) keeps
        # the PR-9 full-cache path byte-identical
        self.sparse_cache_rows = (None if sparse_cache_rows is None
                                  else int(sparse_cache_rows))
        if self.sparse_cache_rows is not None:
            if sparse_tables is None:
                raise ValueError("sparse_cache_rows needs sparse_tables "
                                 "(there is no sparse exchange to cache)")
            if self.sparse_cache_rows < 1:
                raise ValueError(f"sparse_cache_rows must be >= 1, got "
                                 f"{self.sparse_cache_rows}")
            if self.num_shards > 1:
                raise ValueError(
                    "sparse_cache_rows requires num_shards=1: the striped "
                    "client's sparse design is row-range views of one "
                    "full-size cache (see MIGRATION.md)")
        # telemetry-driven adaptive aggregation (ISSUE 10), off by
        # default.  On: the trainer-owned hub merges queued commits
        # Adasum-style, scales each worker's commits by its live
        # staleness standing (DynSGD re-based on the fleet, driven by
        # HealthMonitor events), and sheds reconnect storms with
        # retry-after hints the workers' clients honor (wire action G/Y
        # — opt-in, every pre-existing frame unchanged).  Workers get
        # trace contexts even with telemetry off, so the hub can
        # attribute staleness per worker; pair with health_interval_s
        # for window-wall straggler detection too.  Python hub only
        self.adaptive = bool(adaptive)
        # both hubs serve adaptive=True: the C++ hub runs the Adasum
        # flat-combining merger and G/Y backpressure natively, with
        # per-worker rates pushed from the Python AdaptiveRateController
        # self-scaling fleet (ISSUE 19), off by default.  On: a
        # FleetController subscribes to the run's HealthMonitor and acts
        # on capacity — respawning a worker slot when fleet throughput
        # lags the frozen run-start baseline, retiring a worker the
        # staleness_drift detector names persistently (graceful drain →
        # BYE → elastic membership shrink), and authorizing the respawn
        # after a planned preemption (SpotPreemptionPlan / SIGTERM-with-
        # deadline) WITHOUT charging the restart budget.  Requires an
        # owned hub with the health plane on (health_interval_s); the
        # default False sends every wire byte identical to HEAD
        self.autoscale = bool(autoscale)
        if self.autoscale and ps_address is not None:
            raise ValueError(
                "autoscale=True requires a trainer-owned hub (the "
                "controller subscribes to the owned run's HealthMonitor); "
                "worker-only mode scales at the launcher instead "
                "(distkeras-ps --autoscale)")
        # test/chaos hook: called as fault_hook(worker_idx, window_idx) at
        # every window boundary; raise inside it to kill that worker
        self.fault_hook = fault_hook
        self.worker_errors: List[BaseException] = []
        self.worker_restarts = 0  # total supervisor restarts, last train()
        # planned-preemption records, last train(): one dict per drained
        # worker ({"worker", "window", "deadline_s", "drained_clean",
        # "outstanding_after_drain"}) — the recovery drill and the bench
        # tripwires read these
        self.worker_preemptions: List[Dict[str, Any]] = []
        self.fleet_controller: Optional[Any] = None  # last train()'s, if any
        # (monotonic_ts, worker) per completed window, autoscale runs only
        # — the bench derives pre/post-preemption fleet throughput from it
        self._window_log: List[Tuple[float, int]] = []
        self._window_log_lock = threading.Lock()
        self.parameter_server: Optional[Any] = None
        self._window_fn: Optional[Callable] = None  # cached per instance so a
        # second train() on the same trainer reuses the compiled program
        # (mirrors DistributedTrainer._engine)

    # -- factories (reference: allocate_worker / allocate_parameter_server) ---
    def allocate_parameter_server(self, weights: List[np.ndarray],
                                  shard_id: Optional[int] = None) -> Any:
        raise NotImplementedError  # pragma: no cover - interface

    def _hub_kwargs(self, shard_id: Optional[int] = None) -> dict:
        """Fault-tolerance + identity kwargs every trainer-owned hub
        (Python or C++) takes; subclass allocators splat this into their
        constructor.  ``shard_id`` tags a sharded hub's telemetry (None on
        the unsharded path — the exact pre-sharding series).  With sparse
        tables resolved for this run, each hub additionally learns its
        sparse leaf positions (never added otherwise — the off path
        byte-parity pins never see the kwarg)."""
        kw = {"idle_timeout": self.ps_idle_timeout, "shard_id": shard_id,
              "replica_of": self.replica_of}
        sp = getattr(self, "_hub_sparse", None)
        if sp is not None:
            kw["sparse_leaves"] = sp.get(shard_id, ())
        if self.adaptive:
            # only added when on, so the off path's zero-adaptive-
            # machinery guarantee holds for either hub implementation
            kw["adaptive"] = True
        if self.transport == "shm":
            # only added when opted in, so "socket"/"inproc" runs
            # construct hubs with byte-identical kwargs to pre-shm code
            kw["shm_dir"] = self._ensure_shm_dir()
        if self.recv_batch_depth > 0:
            kw["recv_batch_depth"] = self.recv_batch_depth
        return kw

    def _ensure_shm_dir(self) -> str:
        """The run's ring-file directory, created on first use.  Prefers
        ``/dev/shm`` (tmpfs: ring pages never touch a disk) and falls
        back to the default temp dir — mmap over any filesystem is
        correct, tmpfs is just faster under memory pressure."""
        if self._shm_dir is None:
            self._shm_dir = tempfile.mkdtemp(
                prefix="dkshm-",
                dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
        return self._shm_dir

    def _cleanup_shm_dir(self) -> None:
        """Remove the run's ring-file directory (idempotent).  The hub
        unlinks each ring file right after its attach handshake — live
        mappings keep the memory alive — so this normally removes an
        empty directory; leftovers only exist if a hub died mid-attach."""
        if self._shm_dir is not None:
            shutil.rmtree(self._shm_dir, ignore_errors=True)
            self._shm_dir = None

    def _resolve_sparse_tables(self, flat: List[np.ndarray]) -> Tuple[int, ...]:
        """The run's sparse leaf indices: () when off, the spec's declared
        EmbeddingTable leaves for "auto", or the validated explicit set."""
        declared = self.sparse_tables
        if declared is None:
            return ()
        if declared == "auto":
            from distkeras_tpu.models.base import sparse_leaf_indices

            declared = sparse_leaf_indices(self.model.spec,
                                           self.model.params)
            if not declared:
                raise ValueError(
                    f"sparse_tables='auto' but architecture "
                    f"{self.model.spec.name!r} declares no sparse embedding "
                    f"tables (sparse_param_names); name leaf indices "
                    f"explicitly or drop sparse_tables")
        # validation below covers BOTH paths: an architecture declaring
        # mismatched-vocabulary tables must fail at setup too
        for i in declared:
            if not 0 <= i < len(flat):
                raise ValueError(f"sparse_tables index {i} out of range for "
                                 f"{len(flat)} model leaves")
            if flat[i].ndim != 2:
                raise ValueError(f"sparse_tables leaf {i} must be a "
                                 f"[rows, dim] table, got {flat[i].shape}")
        # per-table vocabularies (ISSUE 15): an architecture declaring a
        # sparse_field_map gets an INDEPENDENT id set per table — each
        # table's ids come from its own feature columns and validate
        # against its own row count, so vocabularies may differ freely.
        # Without a map the PR-9 shared-vocabulary contract stands: one
        # id set per window feeds every table, so unequal row counts
        # would only surface as a mid-run ValueError on the first
        # out-of-range id — refuse at setup instead
        from distkeras_tpu.models.base import (sparse_leaf_indices,
                                               sparse_table_fields)

        fields = sparse_table_fields(self.model.spec, self.model.params)
        if fields is not None:
            by_leaf = dict(zip(sparse_leaf_indices(self.model.spec,
                                                   self.model.params),
                               fields))
            missing = [i for i in declared if i not in by_leaf]
            if missing:
                raise ValueError(
                    f"sparse_tables leaves {missing} have no "
                    f"sparse_field_map entry on architecture "
                    f"{self.model.spec.name!r} — every per-vocabulary "
                    f"table needs its column declaration")
            fields = tuple(by_leaf[i] for i in declared)
        self._sparse_fields = fields
        if fields is None:
            row_counts = {flat[i].shape[0] for i in declared}
            if len(row_counts) > 1:
                raise ValueError(
                    f"sparse_tables leaves have mismatched row counts "
                    f"{sorted(row_counts)}: tables sharing one id set must "
                    f"share one vocabulary — declare a sparse_field_map "
                    f"on the architecture for per-table vocabularies")
        return declared

    def _allocate_hub(self, weights: List[np.ndarray],
                      plan) -> Any:
        """One hub (num_shards=1) or the sharded facade — each shard built
        by the subclass's algorithm-specific allocator over its slice."""
        if plan is None:
            return self.allocate_parameter_server(weights)
        return ShardedParameterServer(
            weights, plan,
            lambda w, sid: self.allocate_parameter_server(w, shard_id=sid))

    # -- the algorithm's window-boundary math, ON DEVICE -----------------------
    # Both hooks take parameter PYTREES already resident on the worker's
    # device and trace into the jitted window program (_make_window_fn), so
    # the exchange arithmetic runs at device speed and the full model never
    # round-trips through host numpy (the commit PAYLOAD still crosses to
    # the host — that is the PS wire protocol's own traffic, not overhead).

    def device_window_start(self, pulled: Any, local: Any) -> Any:
        """What the worker trains from at window start: default = the fresh
        center (DOWNPOUR-family).  Elastic variants keep their local."""
        return pulled

    def device_commit(self, pulled: Any, local_after: Any) -> Tuple[Any, Any]:
        """Window-boundary exchange: given the center pulled at window start
        and the post-window local params (pytrees on device), return
        ``(commit_payload, params_to_continue_from)`` per the algorithm."""
        raise NotImplementedError  # pragma: no cover - interface

    # -- checkpointing ---------------------------------------------------------
    # Async runs have no synchronized epoch boundary, so the checkpoint
    # story is CENTER SNAPSHOTS: a daemon thread periodically saves the
    # hub's current center (every ``checkpoint_interval`` seconds, plus
    # once at finish), and a fresh run restores the latest center as its
    # starting weights.  Preemption loses at most one interval of commits;
    # elastic locals restart from the center (their divergence is
    # exploration state, not progress).  This was round-1 verdict weak #7
    # ("the genuinely asynchronous mode has no preemption story").

    def _maybe_restore(self, checkpointer) -> bool:
        """Load the latest center snapshot into ``self.model``; True if one
        existed."""
        step = checkpointer.latest_step()
        if step is None:
            return False
        restored = checkpointer.restore({"params": self.model.params}, step=step)
        self.model = Model(spec=self.model.spec,
                           params=jax.tree.map(jnp.asarray, restored["params"]))
        return True

    def _snapshot_loop(self, checkpointer, stop: threading.Event, get_center,
                       treedef, next_step: List[int], lock: threading.Lock) -> None:
        import warnings

        while not stop.wait(self.checkpoint_interval):
            try:
                self._snapshot(checkpointer, get_center, treedef, next_step, lock)
            except Exception as e:
                # a transient failure (hub mid-restart, disk hiccup) must
                # not silently kill the snapshot thread for the rest of
                # the run — skip this interval and try again
                warnings.warn(f"center snapshot failed (will retry): "
                              f"{type(e).__name__}: {e}")

    def _snapshot(self, checkpointer, get_center, treedef, next_step: List[int],
                  lock: threading.Lock) -> None:
        # the lock serializes the periodic loop against the final snapshot
        # (a slow save outliving the join timeout must not race the same
        # step number — Checkpointer.save rmtree's in-progress tmp dirs)
        with lock:
            weights = get_center()
            params = jax.tree.unflatten(treedef, [np.asarray(w) for w in weights])
            checkpointer.save(next_step[0], {"params": params},
                              metadata={"kind": "async-center-snapshot"})
            next_step[0] += 1

    # -- training --------------------------------------------------------------
    def train(self, dataset: Dataset, shuffle: bool = True, checkpointer=None,
              validation_data: Optional[Dataset] = None) -> Model:
        self.model.spec.reject_rng_spec(type(self).__name__ + ".train")
        if validation_data is not None:
            raise ValueError(
                "per-epoch validation is not supported for async trainers "
                "(workers race the hub; there is no synchronized epoch "
                "boundary to score) — evaluate the returned model, or use "
                "the sync trainer family")
        if checkpointer is not None and self.ps_address is None:
            # restore only when WE own the hub: in worker-only mode the
            # external hub's center wins (workers pull it immediately), so
            # restoring into self.model would be silently discarded —
            # multi-host resume = restart distkeras-ps from the snapshot
            # (its --save-final / the checkpointer's saved model)
            self._maybe_restore(checkpointer)
        self.record_training_start()
        flat0, treedef = flatten_weights(self.model.params)
        bad = {str(np.asarray(w).dtype) for w in flat0} - {"float32"}
        if bad:
            # the PS hubs (Python and C++) hold the center as flat float32;
            # silently retyping bf16/f64 params through pull/commit was
            # round-1 verdict weak #6 — refuse instead
            raise TypeError(
                f"async trainers require float32 parameters (PS center is "
                f"float32); found dtypes {sorted(bad)} — cast the model's "
                f"params or use the mesh trainers in distkeras_tpu.trainers")
        flat_f32 = [w.astype(np.float32) for w in flat0]
        # row-sparse tables (ISSUE 9), resolved against THIS model's leaves
        sparse_idx = self._resolve_sparse_tables(flat_f32)
        if sparse_idx and self.transport == "inproc" and self.num_shards > 1:
            raise ValueError(
                "sparse_tables with transport='inproc' requires "
                "num_shards=1 (the sharded facade has no sparse direct "
                "pair; inproc moves no wire bytes to save anyway) — use "
                "the socket transport for sharded sparse runs")
        self._sparse_idx = sparse_idx
        # leaf->shard assignment (deterministic in the model's leaf
        # layout): both ends of a sharded deployment derive the same plan,
        # so worker-only mode agrees with standalone --shard-index hubs
        plan = (shard_plan(flat_f32, self.num_shards,
                           sparse_leaves=sparse_idx)
                if self.num_shards > 1 else None)
        self._shard_plan = plan
        # per-hub sparse positions (None when sparse is off, so no hub
        # ctor ever sees an unexpected kwarg)
        if sparse_idx:
            self._hub_sparse = ({sid: plan.local_sparse(sid)
                                 for sid in range(plan.num_shards)}
                                if plan is not None else {None: sparse_idx})
        else:
            self._hub_sparse = None
        if self.ps_address is not None:
            ps = None
            addresses = list(self._ps_addresses)
        else:
            if self.health_interval_s is not None or self.adaptive:
                # we own the hub, so the process-default collector/monitor
                # serve THIS run: drop the previous run's series and frozen
                # throughput baseline, or run 2's ramp-up reads as a
                # regression against run 1's steady state (remote hubs are
                # long-lived and multi-job; only the owner resets).  An
                # adaptive hub subscribes to this monitor at start(), so
                # the reset must come first
                from distkeras_tpu.observability import health as _health
                _health.reset_default()
            ps = self._allocate_hub(flat_f32, plan)
            ps.start()
            if self.replica_of is not None:
                # the trainer's hub is a STANDBY taking over a primary's
                # job: the workers below must not race the asynchronous
                # full sync — their first commit would promote the hub
                # over its fresh init weights and silently discard the
                # primary's state.  Block until the sync landed, and fail
                # LOUDLY if it never does (an unreachable primary must not
                # silently degrade into training from seed)
                if not ps.wait_synced(timeout=self.replica_sync_timeout):
                    ps.stop()
                    self._cleanup_shm_dir()
                    raise RuntimeError(
                        f"replica_of={self.replica_of}: no full sync "
                        f"arrived from the primary within "
                        f"{self.replica_sync_timeout}s "
                        f"(replica_sync_timeout) — it is unreachable or "
                        f"not a Python hub.  Refusing to train from fresh "
                        f"weights; drop replica_of to do that deliberately")
                # this trainer IS the deliberate takeover: promote
                # explicitly (fence at the sync clock, feed severed)
                # before any worker runs — the commit-time promotion
                # trigger is for unplanned failovers and refuses commits
                # while the primary's feed is still live
                ps.promote(reason="trainer replica_of takeover (synced)")
            addresses = [("127.0.0.1", p)
                         for p in (ps.ports if plan is not None else [ps.port])]
        self.parameter_server = ps

        def control_client(**kw):
            """A fresh blocking client for control-plane reads (center
            snapshots, the worker-only final pull): striped when sharded,
            the plain PSClient otherwise.  Carries the run's failover list
            so a control read mid-failover lands on the standby too."""
            if plan is not None:
                return ShardedPSClient(addresses, flat0, plan,
                                       failover=self._ps_failover, **kw)
            return PSClient(addresses[0][0], addresses[0][1],
                            templates=flat0,
                            failover=(self._ps_failover[0]
                                      if self._ps_failover else ()), **kw)
        # distributed tracing: one job id for every worker this run spawns
        # (explicit trace_context joins multi-host workers under one job).
        # Resolved once here so a restarted worker keeps the job identity.
        # The process clock-sync estimate resets per run: an offset
        # measured against a PREVIOUS run's hub must not outlive it.
        # Adaptive runs create contexts even with telemetry off: the
        # hub's per-worker staleness series (what the rate controller
        # scales from) are keyed by the announced worker identity
        trace_job = ((self.trace_context or dtrace.new_job_id())
                     if obs.enabled() or self.adaptive else None)
        if trace_job is not None:
            dtrace.reset_clock_sync()
            if os.environ.get("DKT_TRACE_DIR"):
                # this run flushes its ring at the end under THIS job id:
                # spans surviving from a previous train() in the same
                # process must not be re-flushed (and double-counted by
                # merge_traces/fleet_report) under the new job
                obs.TRACER.clear()

        # note: chunk_windows is moot here — the async worker loop already
        # feeds one window per device transfer (stacked_epoch slices are
        # zero-copy views), so feeding is O(window) by construction
        if self._window_fn is None:
            self._window_fn = _make_window_fn(self, self.model.spec.apply_fn(),
                                              self.loss, self.optimizer)
        window_fn = self._window_fn
        devices = jax.devices()
        histories: List[List[float]] = [[] for _ in range(self.num_workers)]
        errors: List[BaseException] = []

        def unflatten(flat: Sequence[np.ndarray]):
            return jax.tree.unflatten(treedef, list(flat))

        # telemetry (near-zero when disabled): window wall vs DEVICE time
        # histograms are the round-5 VERDICT hand measurement (371 ms wall
        # vs 1.6 ms device per window) made permanent.  Occupancy is two
        # monotonic counters (started minus finished = live workers); a
        # worker records its finish only if it recorded its start, so
        # enabling telemetry mid-run can never drive the difference
        # negative (a disable mid-run leaves at most a one-run positive
        # residual — the finish inc no-ops)
        m_wall = obs.histogram("async_window_wall_seconds")
        m_dev = obs.histogram("async_window_device_seconds")
        m_windows = obs.counter("async_windows_total")
        m_started = obs.counter("async_workers_started_total")
        m_finished = obs.counter("async_workers_finished_total")

        restart_counts = [0] * self.num_workers

        # self-scaling fleet (ISSUE 19): per-run control state shared by
        # the worker threads and the controller callbacks.  fleet_lock
        # exists even with autoscale off — the dynamic join below reads
        # `threads` under it either way
        self.worker_preemptions = []
        with self._window_log_lock:
            self._window_log = []
        fleet_lock = threading.Lock()
        drain_requests: set = set()   # worker idxs asked to retire
        drained: set = set()          # worker idxs that drained clean
        exited_workers: set = set()   # idxs whose threads returned (respawn pool)
        controller = None
        if self.autoscale:
            from distkeras_tpu.observability import health as _health
            from distkeras_tpu.runtime.fleet_controller import FleetController

            def _spawn_replacement(_worker) -> None:
                # replacement capacity re-enters through an EXITED worker
                # slot (its dataset shard is otherwise orphaned); with
                # the whole fleet live there is nothing to replace, so
                # the decision stays advisory
                with fleet_lock:
                    if not exited_workers:
                        return
                    ridx = exited_workers.pop()
                t = threading.Thread(target=run_worker, args=(ridx,))
                with fleet_lock:
                    threads.append(t)
                t.start()

            def _request_drain(worker: str) -> None:
                try:
                    widx = int(worker)
                except (TypeError, ValueError):
                    return
                with fleet_lock:
                    drain_requests.add(widx)

            controller = FleetController(_health.monitor(),
                                         spawn_fn=_spawn_replacement,
                                         retire_fn=_request_drain,
                                         min_fleet=max(
                                             1, self.num_workers // 2))
        self.fleet_controller = controller

        def worker_once(idx: int, start_epoch: int, progress: List[int],
                        losses: List[Any]) -> None:
            """One attempt at a worker's epoch loop, starting at
            ``start_epoch``.  ``progress[0]`` tracks the epoch currently
            being trained so the supervisor can resume a restarted worker
            there (windows already committed within the interrupted epoch
            replay — async SGD tolerates re-applied windows far better
            than skipped data); ``progress[1]`` records ``len(losses)``
            at that epoch's start so the supervisor can drop the aborted
            attempt's partial-epoch losses before the replay re-records
            them (history must not double-count replayed windows)."""
            device = devices[idx % len(devices)]
            # per-worker trace context: announced over the PS wire (socket)
            # or read thread-locally by the hub's direct path (inproc), so
            # hub-side spans are attributable to THIS worker.  A restarted
            # worker gets a fresh span_id under the same job/worker ids
            ctx = None
            if trace_job is not None:
                ctx = dtrace.TraceContext(job_id=trace_job, worker_id=idx,
                                          span_id=dtrace.new_span_id())
                dtrace.activate(ctx)
            if self.transport == "inproc":
                client = InprocPSClient(ps, templates=flat0,
                                        compress=self.compress_commits,
                                        trace_context=ctx,
                                        sparse_leaves=sparse_idx,
                                        sparse_cache_rows=self.sparse_cache_rows)
            elif plan is not None:
                # striped worker: one pipelined connection per shard,
                # pulls/commits fan out and land per shard (the same
                # zero-copy machinery per connection)
                client = ShardedPSClient(addresses, flat0, plan,
                                         compress=self.compress_commits,
                                         max_inflight=self.max_inflight_commits,
                                         max_reconnects=self.max_reconnects,
                                         reconnect_backoff=self.reconnect_backoff,
                                         heartbeat_interval=self.heartbeat_interval,
                                         trace_context=ctx,
                                         failover=self._ps_failover,
                                         sparse_leaves=sparse_idx,
                                         adaptive=self.adaptive,
                                         shm=self.transport == "shm")
            else:
                client = PSClient(addresses[0][0], addresses[0][1],
                                  templates=flat0,
                                  compress=self.compress_commits,
                                  max_inflight=self.max_inflight_commits,
                                  max_reconnects=self.max_reconnects,
                                  reconnect_backoff=self.reconnect_backoff,
                                  heartbeat_interval=self.heartbeat_interval,
                                  trace_context=ctx,
                                  failover=(self._ps_failover[0]
                                            if self._ps_failover else ()),
                                  sparse_leaves=sparse_idx,
                                  adaptive=self.adaptive,
                                  sparse_cache_rows=self.sparse_cache_rows,
                                  shm=self.transport == "shm")
            pipeline = self.pipeline
            # row-sparse exchange (ISSUE 9): each window's pull/commit
            # carries the sorted-unique row ids its batches touch.
            # Architectures with a sparse_field_map (ISSUE 15) get an
            # INDEPENDENT id set per table from that table's own feature
            # columns; the rest keep the shared-vocabulary contract (one
            # id set for every table).  Fully inert when no sparse tables
            # are configured
            sparse_on = bool(sparse_idx)
            sparse_fields = getattr(self, "_sparse_fields", None)
            cache_on = sparse_on and self.sparse_cache_rows is not None

            def rows_of(window_x) -> List[np.ndarray]:
                x = np.asarray(window_x)
                if sparse_fields is None:
                    ids = np.unique(x.ravel().astype(np.int64))
                    return [ids] * len(sparse_idx)
                flat_x = x.reshape(-1, x.shape[-1])
                return [np.unique(flat_x[:, list(cols)].ravel()
                                  .astype(np.int64))
                        for cols in sparse_fields]
            # live health plane (ISSUE 8): periodic compact reports to the
            # hub's collector.  Wholly inert when off (health_interval is
            # None -> zero extra calls on the window path)
            health_interval = self.health_interval_s
            h_next = time.monotonic() + (health_interval or 0.0)
            h_seq = 0          # per-worker report sequence number
            h_windows = 0      # cumulative windows this worker ran
            h_wall_ms = 0.0    # window wall accumulated since last report
            h_wall_n = 0
            h_rows = 0         # cumulative sparse rows this worker committed

            def send_health() -> None:
                nonlocal h_seq, h_wall_ms, h_wall_n
                metrics = {
                    # *_total = cumulative (the collector's rate()
                    # convention); window_wall_ms = point sample (the
                    # mean since the last report)
                    "windows_total": float(h_windows),
                    "window_wall_ms": (h_wall_ms / h_wall_n
                                       if h_wall_n else None),
                    "reconnects_total": float(client.reconnects_used),
                    "failovers_total": float(client.failovers_used),
                }
                if sparse_on:
                    # the health plane sees sparse traffic too: committed
                    # rows as a cumulative series (rate = rows/s in
                    # distkeras-top and the live fleet_report)
                    metrics["sparse_rows_total"] = float(h_rows)
                if cache_on:
                    # hot-tier cache standing (ISSUE 15): cumulative hit/
                    # miss series — the HIT% column in distkeras-top and
                    # fleet_report["sparse"]["hot_tier"]
                    metrics["sparse_cache_hits_total"] = float(
                        client.sparse_cache_hits)
                    metrics["sparse_cache_misses_total"] = float(
                        client.sparse_cache_misses)
                client.report_health({
                    "job": trace_job or "local", "worker": idx,
                    "seq": h_seq, "t_wall": time.time(),
                    # which transport this worker's frames actually move
                    # over ("shm" only after a successful attach — a
                    # declined attach honestly reports "tcp"); the TRANS
                    # column in distkeras-top and fleet_report's
                    # transport block read this
                    "transport": getattr(client, "transport", None),
                    "metrics": metrics})
                h_seq += 1
                h_wall_ms, h_wall_n = 0.0, 0
            try:
                shard = dataset.shard(self.num_workers, idx)
                # worker state lives on the device for the whole run;
                # each window touches the host only for the PS wire
                # exchange (pull in, commit out) and the feed slices.
                # np.array: the socket client's pull buffers are reused
                # by later prefetches, and params must own its storage.
                # On a restart this pull IS the recovery point: the
                # worker resumes from the hub's current center
                seed_host = [np.array(w) for w in client.pull()]
                params = jax.device_put(unflatten(seed_host), device)
                # hot-tier mode (ISSUE 15): one full-shape DEVICE-resident
                # mirror per sparse table, seeded from the initial full
                # pull and scatter-refreshed each window with the [k, dim]
                # row block the bounded client cache hands back — the
                # full-shape host copy the PR-9 path re-uploaded per
                # window no longer exists, and per-window H2D for the
                # table drops to the touched rows
                sset = frozenset(sparse_idx)
                mirror = ({i: jax.device_put(seed_host[i], device)
                           for i in sparse_idx} if cache_on else None)
                # the seed's host copy must NOT outlive the transfer: a
                # named local would pin one full-size host array per
                # sparse table for the whole run — the exact footprint
                # sparse_cache_rows exists to eliminate
                del seed_host
                row_caps: Optional[List[int]] = None
                opt_state = jax.device_put(self.optimizer.init(params), device)
                # one pull rides ahead of the window being computed (set
                # when the previous window prefetched this window's pull)
                pull_pending = False
                for epoch in range(start_epoch, self.num_epoch):
                    progress[0] = epoch
                    progress[1] = len(losses)
                    ds = shard.shuffle(seed=self.seed + 1000 * idx + epoch) if shuffle else shard
                    stacked = ds.stacked_epoch(self.batch_size,
                                               [self.features_col, self.label_col],
                                               window=self.communication_window)
                    xs, ys = stacked[self.features_col], stacked[self.label_col]
                    n_windows = xs.shape[0]
                    if cache_on and row_caps is None:
                        # fixed scatter capacity per table: distinct ids
                        # per window are bounded by rows-per-window x the
                        # table's column count, so padding to this bound
                        # keeps the device scatter ONE compiled shape
                        per_window = int(xs.shape[1])
                        ncols = ([len(c) for c in sparse_fields]
                                 if sparse_fields is not None
                                 else [int(xs.shape[-1])] * len(sparse_idx))
                        row_caps = [min(int(flat0[i].shape[0]),
                                        per_window * nc)
                                    for i, nc in zip(sparse_idx, ncols)]
                    # with telemetry ON, window slices ride the shared
                    # feed machinery with a no-op place: the producer
                    # thread stages (wx, wy) views one window ahead and
                    # records the feed queue gauges, while the device
                    # transfer itself STAYS fused with the pull below —
                    # one batched H2D per window.  With telemetry off the
                    # loop is the plain zero-thread slice walk (no queue
                    # handoff on the hot path)
                    slices = ((xs[w], ys[w]) for w in range(xs.shape[0]))
                    feed = (prefetch_to_device(slices, lambda s: s,
                                               metric_prefix="async_feed")
                            if obs.enabled() else slices)
                    # rows the pending prefetched pull was issued with
                    # (sparse only): the commit for window w must carry
                    # the SAME id set its pull asked for
                    next_rows: Optional[List[np.ndarray]] = None
                    for w, (wx_h, wy_h) in enumerate(feed):
                        if controller is not None:
                            with fleet_lock:
                                wants_drain = idx in drain_requests
                            if wants_drain:
                                # retire lands at a window BOUNDARY: the
                                # previous window's commit is already on
                                # the wire, no new work starts
                                raise _DrainRequested(idx, w)
                        if self.fault_hook is not None:
                            self.fault_hook(idx, w)
                        telemetry = obs.enabled()
                        t_wall = (time.perf_counter()
                                  if telemetry or health_interval is not None
                                  else 0.0)
                        rows_w: Optional[List[np.ndarray]] = None
                        if sparse_on:
                            rows_w = (next_rows if next_rows is not None
                                      else rows_of(xs[w]))
                            next_rows = None
                        with obs.span("async.window", worker=idx,
                                      epoch=epoch, window=w):
                            if not pull_pending:
                                if sparse_on:
                                    client.pull_nowait(sparse_rows=rows_w)
                                else:
                                    client.pull_nowait()
                            pulled_host = client.wait_weights()
                            pull_pending = False
                            # ONE batched H2D per window (center + feed
                            # slices) — on a relayed device every transfer
                            # call is a host round trip, so they are fused
                            if cache_on:
                                # sparse slots of pulled_host are [k, dim]
                                # row blocks aligned with rows_w; pad each
                                # to its fixed capacity (repeating the
                                # last row — duplicate scatter indices
                                # carry identical values) and refresh the
                                # device mirrors, then assemble the full-
                                # order pulled tree from mirrors + dense
                                pads: List[Any] = []
                                for si, i in enumerate(sparse_idx):
                                    ids = rows_w[si]
                                    k = int(ids.size)
                                    if k == 0:
                                        pads.append(None)
                                        continue
                                    block = np.asarray(pulled_host[i],
                                                       np.float32)
                                    cap = row_caps[si]
                                    if k < cap:
                                        pid = np.empty(cap, np.int64)
                                        pid[:k] = ids
                                        pid[k:] = ids[k - 1]
                                        pblk = np.empty(
                                            (cap, block.shape[1]),
                                            np.float32)
                                        pblk[:k] = block
                                        pblk[k:] = block[k - 1]
                                    else:
                                        pid, pblk = ids, block
                                    pads.append((pid, pblk))
                                dense_host = [pulled_host[j]
                                              for j in range(len(pulled_host))
                                              if j not in sset]
                                dense_dev, pad_dev, wx, wy = jax.device_put(
                                    (dense_host, pads, wx_h, wy_h), device)
                                flat_dev: List[Any] = []
                                di = si = 0
                                for j in range(len(pulled_host)):
                                    if j in sset:
                                        pd = pad_dev[si]
                                        if pd is not None:
                                            mirror[j] = mirror[j].at[
                                                pd[0]].set(pd[1])
                                        flat_dev.append(mirror[j])
                                        si += 1
                                    else:
                                        flat_dev.append(dense_dev[di])
                                        di += 1
                                pulled = unflatten(flat_dev)
                            else:
                                pulled, wx, wy = jax.device_put(
                                    (unflatten(pulled_host), wx_h, wy_h),
                                    device)
                            t_dev = time.perf_counter() if telemetry else 0.0
                            params, opt_state, commit, mloss = window_fn(
                                params, opt_state, pulled, wx, wy)
                            # prefetch the NEXT window's pull while this
                            # window's program runs: the request leaves
                            # now (jax dispatch is async) and the weights
                            # stream into the other landing buffer under
                            # the compute — the center it snapshots
                            # predates this window's commit below
                            # (self-staleness 1; ARCHITECTURE.md)
                            last_window = (w == n_windows - 1
                                           and epoch == self.num_epoch - 1)
                            if pipeline and not last_window:
                                if sparse_on:
                                    # sparse prefetch needs the NEXT
                                    # window's ids, so it stops at the
                                    # epoch tail (the next epoch's
                                    # reshuffled slices don't exist yet);
                                    # window 0 then issues its own pull —
                                    # one pipeline bubble per epoch
                                    if w + 1 < n_windows:
                                        next_rows = rows_of(xs[w + 1])
                                        client.pull_nowait(
                                            sparse_rows=next_rows)
                                        pull_pending = True
                                else:
                                    client.pull_nowait()
                                    pull_pending = True
                            if telemetry:
                                # block on the window program ONLY when
                                # measuring: dispatch-to-completion is
                                # the device leg of the wall/device
                                # decomposition (the commit d2h below
                                # would serialize on it anyway)
                                jax.block_until_ready(mloss)
                                m_dev.observe(time.perf_counter() - t_dev)
                            # one batched D2H for the payload; leaf order is
                            # the same tree.flatten order as the templates
                            payload = jax.tree.leaves(jax.device_get(commit))
                            if pipeline:
                                # fire-and-forget: the ack coalesces into
                                # the next window's weights receive
                                if sparse_on:
                                    client.commit_nowait(payload,
                                                         sparse_rows=rows_w)
                                else:
                                    client.commit_nowait(payload)
                            elif sparse_on:
                                client.commit(payload, sparse_rows=rows_w)
                            else:
                                client.commit(payload)
                        if sparse_on:
                            h_rows += int(sum(ids.size for ids in rows_w))
                        if telemetry:
                            m_wall.observe(time.perf_counter() - t_wall)
                            m_windows.inc()
                        if health_interval is not None:
                            h_windows += 1
                            h_wall_ms += (time.perf_counter() - t_wall) * 1e3
                            h_wall_n += 1
                            if time.monotonic() >= h_next:
                                send_health()
                                h_next = time.monotonic() + health_interval
                        if controller is not None:
                            # fleet-throughput sample (bench pre/post-
                            # preemption rates); autoscale runs only, so
                            # the default path appends nothing
                            with self._window_log_lock:
                                self._window_log.append(
                                    (time.monotonic(), idx))
                        # loss stays a device scalar until the run ends:
                        # float() here would add one more blocking round
                        # trip per window
                        losses.append(mloss)
                if health_interval is not None:
                    # final report: a run (or epoch tail) shorter than the
                    # interval still lands at least one report per worker
                    send_health()
                # trailing acks (and nothing else: the last window never
                # prefetches) — commits must be APPLIED before the run's
                # final center read, not just queued on the wire
                client.drain()
            except (WorkerPreempted, _DrainRequested) as stop_ev:
                # graceful drain (ISSUE 19): finish the in-flight
                # exchange — pipelined commit acks plus the unused
                # prefetched pull — then flush the int8 residual so
                # error feedback is not lost with the worker, and leave
                # through the normal BYE in the finally below.  The hub
                # sees a voluntary departure (elastic denominators
                # shrink through member_leave), never a torn stream, and
                # every acked commit is already in the center: zero
                # acked-commit loss by construction
                clean = True
                outstanding = 0
                try:
                    client.drain()
                    if self.compress_commits == "int8" and not sparse_on:
                        # the residual chain advances at quantization
                        # time, so one zero-delta commit carries exactly
                        # the accumulated residual
                        client.commit([np.zeros_like(t) for t in flat0])
                except Exception:
                    clean = False
                    pend = getattr(client, "_pending", None)
                    outstanding = len(pend) if pend is not None else -1
                if isinstance(stop_ev, WorkerPreempted):
                    with fleet_lock:
                        self.worker_preemptions.append({
                            "worker": idx, "window": stop_ev.window,
                            "deadline_s": stop_ev.deadline_s,
                            "drained_clean": clean,
                            "outstanding_after_drain": outstanding})
                    if obs.enabled():
                        obs.counter("worker.preemptions").inc()
                    if controller is not None:
                        controller.notify_drained(idx, clean=clean)
                    raise  # the supervisor respawns, budget-neutral
                # controller-requested retire: record, then exit as a
                # finished worker — the supervisor must not restart it
                with fleet_lock:
                    drain_requests.discard(idx)
                    drained.add(idx)
                if controller is not None:
                    controller.notify_drained(idx, clean=clean)
                return
            finally:
                client.close()
        def run_worker(idx: int) -> None:
            losses: List[Any] = []
            start_counted = obs.enabled()
            if start_counted:
                m_started.inc()
            if controller is not None:
                controller.notify_worker_started(idx)
            progress = [0, 0]  # [resume epoch, losses length at its start]
            try:
                while True:
                    try:
                        worker_once(idx, progress[0], progress, losses)
                        return
                    except BaseException as e:
                        if (isinstance(e, WorkerPreempted)
                                and controller is not None
                                and controller.notify_preempted(
                                    idx, deadline_s=e.deadline_s)):
                            # planned capacity loss, already drained
                            # clean: the authorized respawn re-enters at
                            # the interrupted epoch WITHOUT burning a
                            # restart-budget slot (a preemption is not a
                            # crash), re-pulling the hub's CURRENT center
                            # like any restart
                            del losses[progress[1]:]
                            continue
                        # supervision: "restart" re-runs the worker from the
                        # hub's CURRENT center (its committed progress
                        # survives there), bounded by max_worker_restarts
                        # and resuming at the epoch it died in; any other
                        # policy records the error for the run-level
                        # raise/continue handling below
                        if (self.on_worker_failure != "restart"
                                or restart_counts[idx] >= self.max_worker_restarts):
                            errors.append(e)
                            return
                        restart_counts[idx] += 1
                        # the replay re-records the aborted epoch's
                        # windows: drop its partial losses so history
                        # counts each trained window once
                        del losses[progress[1]:]
                        # surface the swallowed cause: an operator must be
                        # able to tell two transient faults from the same
                        # deterministic bug recurring every attempt
                        import warnings

                        warnings.warn(
                            f"worker {idx} restarting "
                            f"({restart_counts[idx]}/{self.max_worker_restarts}) "
                            f"after {type(e).__name__}: {e}")
                        if obs.enabled():
                            obs.counter("worker.restarts").inc()
            finally:
                if controller is not None:
                    controller.notify_worker_exited(idx)
                    with fleet_lock:
                        # retired workers stay out of the respawn pool —
                        # re-admitting the drifting worker the controller
                        # just drained would undo the retire
                        if idx not in drained:
                            exited_workers.add(idx)
                if start_counted:
                    m_finished.inc()
                # flush even on a mid-run crash: windows whose commits
                # already reached the center must stay in history / the
                # samples metric (the 'continue' failure policy counts on
                # this, and the old per-window float() accounting had it)
                try:
                    histories[idx].extend(float(x) for x in jax.device_get(losses))
                except Exception:
                    # a dead device can fail the final fetch; the run's
                    # primary error is already in `errors`
                    pass

        snap_stop = snap_thread = None
        if checkpointer is not None:
            def get_center():
                if ps is not None:
                    return ps.get_weights()
                with control_client() as c:
                    return c.pull()

            next_step = [(checkpointer.latest_step() or 0) + 1]
            snap_stop = threading.Event()
            snap_lock = threading.Lock()
            snap_thread = threading.Thread(
                target=self._snapshot_loop,
                args=(checkpointer, snap_stop, get_center, treedef, next_step, snap_lock),
                daemon=True)
            snap_thread.start()

        threads = [threading.Thread(target=run_worker, args=(i,)) for i in range(self.num_workers)]
        with self._profile_ctx():
            for t in threads:
                t.start()
            # spawned replacements append to `threads` mid-join (fleet
            # controller): keep joining until a pass finds no new threads
            joined = 0
            while True:
                with fleet_lock:
                    batch = threads[joined:]
                if not batch:
                    break
                for t in batch:
                    t.join()
                joined += len(batch)
        if snap_stop is not None:
            snap_stop.set()
            snap_thread.join(timeout=10)
            # final center snapshot while the hub is still up; best-effort —
            # a dead hub here must not mask the workers' root-cause errors
            # (checked right below), and with 'continue' the run's result
            # still stands even if this last save fails
            try:
                self._snapshot(checkpointer, get_center, treedef, next_step, snap_lock)
            except Exception as snap_err:
                if not errors and self.on_worker_failure == "raise":
                    raise
                errors.append(snap_err)  # recorded in worker_errors below
        if controller is not None:
            controller.stop()
        if ps is not None:
            ps.stop()
        self._cleanup_shm_dir()
        self.worker_restarts = sum(restart_counts)
        self.worker_errors = list(errors)
        if errors and self.on_worker_failure == "raise":
            # surface the workers' root cause before touching the hub again
            # (it may be gone, and that must not mask the real failure)
            raise errors[0]
        if ps is None:
            # worker-only mode: the external hub outlives us; read the center
            # (with the run's reconnect budget — a hub restart racing the
            # end of the run must not lose an otherwise-complete result)
            with control_client(
                    max_reconnects=self.max_reconnects,
                    reconnect_backoff=self.reconnect_backoff) as final_client:
                final = final_client.pull()
        else:
            final = ps.get_weights()
        # interleave per-worker histories into one trace (order is arbitrary
        # under real asynchrony; per-worker order is preserved)
        for h in histories:
            self._record_window_losses(h)
        total_windows = sum(len(h) for h in histories)
        self._record_epoch_metrics(
            epoch=self.num_epoch - 1,
            samples=total_windows * self.communication_window * self.batch_size,
            seconds=self.get_training_time(),
            chips=min(self.num_workers, len(devices)))
        # fleet-wide merge hook: when DKT_TRACE_DIR is set (and telemetry
        # on), flush this process's span ring — in worker-only mode every
        # worker host writes its own file with its PS-round-trip clock
        # offset, and merge_traces(dir) aligns them all on the hub timeline
        trace_dir = os.environ.get("DKT_TRACE_DIR")
        if trace_dir and obs.enabled():
            try:
                dtrace.flush_process_trace(
                    trace_dir, job_id=trace_job,
                    role="trainer" if ps is not None else "worker")
            except OSError as e:
                import warnings

                warnings.warn(f"trace flush to {trace_dir} failed: {e}")
        self.model = Model(spec=self.model.spec,
                           params=jax.tree.unflatten(treedef, [jnp.asarray(w) for w in final]))
        self.record_training_end()
        return self.model

class AsyncDOWNPOUR(AsyncDistributedTrainer):
    """DOWNPOUR with real asynchrony (reference §2.5): train from the fresh
    center, commit the raw accumulated delta."""

    def allocate_parameter_server(self, weights, shard_id=None):
        if self.native_ps:
            from distkeras_tpu.runtime.native import MODE_DELTA, NativeParameterServer

            return NativeParameterServer(weights, mode=MODE_DELTA,
                                         **self._hub_kwargs(shard_id))
        return DeltaParameterServer(weights, **self._hub_kwargs(shard_id))

    def device_commit(self, pulled, local_after):
        delta = jax.tree.map(lambda l, p: l - p, local_after, pulled)
        return delta, local_after


class AsyncADAG(AsyncDOWNPOUR):
    """ADAG (reference §2.6): DOWNPOUR-style worker, PS normalizes each
    delta by num_workers."""

    def allocate_parameter_server(self, weights, shard_id=None):
        if self.native_ps:
            from distkeras_tpu.runtime.native import MODE_ADAG, NativeParameterServer

            return NativeParameterServer(weights, mode=MODE_ADAG,
                                         num_workers=self.num_workers,
                                         elastic=self.elastic,
                                         **self._hub_kwargs(shard_id))
        return ADAGParameterServer(weights, num_workers=self.num_workers,
                                   elastic=self.elastic,
                                   **self._hub_kwargs(shard_id))


class AsyncDynSGD(AsyncDOWNPOUR):
    """DynSGD (reference §2.7): DOWNPOUR-style worker, PS scales each delta
    by 1/(staleness+1) from its commit clock."""

    def allocate_parameter_server(self, weights, shard_id=None):
        if self.native_ps:
            from distkeras_tpu.runtime.native import MODE_DYNSGD, NativeParameterServer

            return NativeParameterServer(weights, mode=MODE_DYNSGD,
                                         **self._hub_kwargs(shard_id))
        return DynSGDParameterServer(weights, **self._hub_kwargs(shard_id))


class AsyncAEASGD(AsyncDistributedTrainer):
    """AEASGD (reference §2.8, §3.5): locals stay divergent; each window
    commits the elastic difference ``alpha * (local - center)`` and subtracts
    it locally."""

    def __init__(self, model, rho: float = 5.0, communication_window: int = 32, **kwargs):
        super().__init__(model, communication_window=communication_window, **kwargs)
        if callable(self.learning_rate):
            # same guard (and workaround guidance) as the sync AEASGD: a
            # schedule would otherwise surface as a raw float * function
            # TypeError on the next line
            raise ValueError(
                "elastic trainers need a scalar learning_rate (the elastic "
                "coupling alpha = rho * lr is a constant); to schedule the "
                "local steps, pass an optax optimizer built with the schedule "
                "as worker_optimizer and keep learning_rate scalar")
        self.rho = float(rho)
        self.alpha = self.rho * self.learning_rate

    def allocate_parameter_server(self, weights, shard_id=None):
        if self.native_ps:
            from distkeras_tpu.runtime.native import MODE_DELTA, NativeParameterServer

            return NativeParameterServer(weights, mode=MODE_DELTA,
                                         **self._hub_kwargs(shard_id))
        return DeltaParameterServer(weights, **self._hub_kwargs(shard_id))

    def device_window_start(self, pulled, local):
        return local  # elastic workers keep their own trajectory

    def device_commit(self, pulled, local_after):
        ediff = jax.tree.map(lambda l, p: self.alpha * (l - p), local_after, pulled)
        return ediff, jax.tree.map(lambda l, e: l - e, local_after, ediff)


class AsyncEAMSGD(AsyncAEASGD):
    """EAMSGD (reference §2.9): AEASGD with Nesterov momentum on the local
    optimizer."""

    def __init__(self, model, rho: float = 5.0, momentum: float = 0.9, **kwargs):
        kwargs.setdefault("worker_optimizer", "nesterov")
        super().__init__(model, rho=rho, momentum=momentum, **kwargs)
