"""Deterministic chaos harness for the async PS stack.

Fault-tolerance code is only as trustworthy as the faults it was tested
against, and ad-hoc fault injection (kill a thread "somewhere in the
middle", sleep and hope) makes failures unreproducible.  This module makes
every fault a *scheduled, seedable event*:

- :class:`Fault` / :class:`FaultPlan` — a declarative schedule of faults,
  either written explicitly (``FaultPlan([Fault(conn=0, direction="s2c",
  frame=3, kind="sever")])``) or generated from a seed
  (:meth:`FaultPlan.random`), so a chaos test replays bit-identically.
- :class:`ChaosProxy` — a frame-aware TCP proxy inserted between PSClient
  workers and a hub.  It parses the length-prefixed frame stream in both
  directions and, per the plan, **severs** the connection at frame *k*,
  **delays** frame *k*, or **truncates** frame *k* mid-payload (the
  half-written-frame shape a crashing peer actually produces).  Everything
  not faulted is forwarded byte-exactly, so a proxied run with an empty
  plan is indistinguishable from a direct one.
- :class:`WorkerKillPlan` — seeded worker-kill schedule for the trainers'
  ``fault_hook`` (raise at planned ``(worker, window)`` pairs, each fired
  at most once — so a restarted worker replaying the window survives).

Used by ``tests/test_faults.py`` (the fault-injection matrix) and
``bench.py :: _bench_async_recovery`` (time-to-recover + loss-parity leg).
"""

from __future__ import annotations

import dataclasses
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

SEVER = "sever"
DELAY = "delay"
TRUNCATE = "truncate"

_KINDS = (SEVER, DELAY, TRUNCATE)
_DIRECTIONS = ("c2s", "s2c")


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault: on proxied connection ``conn`` (accept
    ordinal), in ``direction`` (``"c2s"`` client->server, ``"s2c"``
    server->client), when frame ``frame`` (0-based per direction) crosses
    the proxy, apply ``kind``:

    - ``sever``: drop both directions of the connection before the frame
      is forwarded (a crashed peer / yanked cable).
    - ``delay``: hold the frame for ``delay_s`` seconds, then forward it
      intact (a congested or GC-pausing peer).
    - ``truncate``: forward the 8-byte header plus ``keep_bytes`` of the
      payload, then sever (a peer that died MID-frame — the shape that
      desynchronizes a stream and provokes half-read hangs).

    ``shard`` targets one shard of a sharded-hub deployment: a
    :class:`ShardedChaosProxy` routes each fault to the proxy in front of
    that shard's hub (the default 0 is also the only shard of an
    unsharded :class:`ChaosProxy`, which ignores the field)."""

    conn: int
    frame: int
    direction: str = "s2c"
    kind: str = SEVER
    delay_s: float = 0.05
    keep_bytes: int = 0
    shard: int = 0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {self.kind!r}")
        if self.direction not in _DIRECTIONS:
            raise ValueError(f"direction must be one of {_DIRECTIONS}, "
                             f"got {self.direction!r}")


class FaultPlan:
    """An immutable schedule of :class:`Fault` events, looked up by
    ``(conn, direction, frame)``.  At most one fault per key (later
    entries win).  ``seed`` only matters for :meth:`random`-built plans;
    it is carried so a failing test can print the plan's provenance."""

    def __init__(self, faults: Sequence[Fault] = (), seed: Optional[int] = None):
        self.seed = seed
        self.faults = tuple(faults)
        self._by_key: Dict[Tuple[int, str, int], Fault] = {
            (f.conn, f.direction, f.frame): f for f in self.faults}

    @classmethod
    def random(cls, seed: int, conns: int, frames: int,
               n_faults: int = 1, kinds: Sequence[str] = (SEVER,),
               direction: str = "s2c", delay_s: float = 0.05) -> "FaultPlan":
        """Seeded plan: ``n_faults`` faults spread over ``conns``
        connections x ``frames`` frames, deterministic in ``seed`` (the
        reproducibility contract chaos tests rely on)."""
        rng = np.random.default_rng(seed)
        faults = []
        for _ in range(n_faults):
            faults.append(Fault(
                conn=int(rng.integers(0, max(1, conns))),
                # frame 0 is the very first exchange; faulting past it
                # exercises an ESTABLISHED pipeline, which is the
                # interesting case — so draw from [1, frames)
                frame=int(rng.integers(1, max(2, frames))),
                direction=direction,
                kind=str(kinds[int(rng.integers(0, len(kinds)))]),
                delay_s=delay_s,
                keep_bytes=int(rng.integers(0, 9))))
        return cls(faults, seed=seed)

    def lookup(self, conn: int, direction: str, frame: int) -> Optional[Fault]:
        return self._by_key.get((conn, direction, frame))

    def __repr__(self) -> str:
        return f"FaultPlan(seed={self.seed}, faults={list(self.faults)})"


class ChaosProxy:
    """Frame-aware TCP proxy: client connects to ``proxy.port``, the proxy
    connects onward to ``(upstream_host, upstream_port)`` and pumps frames
    both ways, consulting ``plan`` at every frame boundary.

    Each accepted connection gets the next accept ordinal — a client that
    reconnects after a sever arrives as a NEW ordinal, so a plan that
    faults only ``conn=0`` exercises exactly one failure + recovery.

    The proxy counts telemetry-free and allocation-light: frames are
    relayed in bounded chunks (no whole-frame buffering), and an idle
    proxy holds no locks on the data path.

    ``delay_all_s`` holds EVERY frame (both directions) for that long
    before forwarding — replication-lag injection: front a primary hub's
    address with it and point the replica's ``replica_of`` at the proxy,
    and the standby tracks the primary with a measured, constant lag
    (planned per-frame faults still apply on top).

    Slow-NIC emulation (ISSUE 10): ``bandwidth_bytes_per_s`` adds each
    frame's serialization time at that bandwidth (big weight frames slow
    proportionally, small acks barely), and ``jitter_delay_s=(lo, hi)``
    adds a per-frame uniform draw from a ``seed``-derived RNG — each
    (conn, direction) pump owns an independent stream keyed
    ``(seed, conn, direction)``, so a throttled chaos run replays its
    delay schedule bit-identically.  ``slow_conns`` restricts both to
    the named accept ordinals (default: every connection) — fronting a
    whole fleet with one proxy while throttling only conn 0 is how the
    bench's adaptive leg makes exactly one straggler."""

    _CHUNK = 1 << 16

    def __init__(self, upstream_host: str, upstream_port: int,
                 plan: Optional[FaultPlan] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 delay_all_s: float = 0.0,
                 bandwidth_bytes_per_s: Optional[float] = None,
                 jitter_delay_s: Optional[Tuple[float, float]] = None,
                 seed: Optional[int] = None,
                 slow_conns: Optional[Sequence[int]] = None):
        self.upstream = (upstream_host, int(upstream_port))
        self.plan = plan or FaultPlan()
        self.delay_all_s = float(delay_all_s)
        self.bandwidth_bytes_per_s = (None if not bandwidth_bytes_per_s
                                      else float(bandwidth_bytes_per_s))
        if jitter_delay_s is not None:
            lo, hi = float(jitter_delay_s[0]), float(jitter_delay_s[1])
            if not 0.0 <= lo <= hi:
                raise ValueError(f"jitter_delay_s must be 0 <= lo <= hi, "
                                 f"got ({lo}, {hi})")
            jitter_delay_s = (lo, hi)
        self.jitter_delay_s = jitter_delay_s
        self.seed = seed
        self.slow_conns = (None if slow_conns is None
                           else frozenset(int(c) for c in slow_conns))
        self.host = host
        self.port = int(port)
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._threads: List[threading.Thread] = []
        self._pairs: List[Tuple[socket.socket, socket.socket]] = []
        self._lock = threading.Lock()
        self._running = False
        self._conn_seq = 0
        self.faults_fired: List[Fault] = []  # observability for tests/bench

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> "ChaosProxy":
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(64)
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        if self._listener is not None:
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            for a, b in self._pairs:
                for s in (a, b):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- data path -------------------------------------------------------------
    def _accept_loop(self) -> None:
        assert self._listener is not None
        while self._running:
            try:
                client, _ = self._listener.accept()
            except OSError:
                break
            try:
                server = socket.create_connection(self.upstream, timeout=30)
            except OSError:
                try:
                    client.close()
                except OSError:
                    pass
                continue
            conn_idx = self._conn_seq
            self._conn_seq += 1
            with self._lock:
                if not self._running:
                    for s in (client, server):
                        try:
                            s.close()
                        except OSError:
                            pass
                    break
                self._pairs.append((client, server))
            for direction, src, dst in (("c2s", client, server),
                                        ("s2c", server, client)):
                t = threading.Thread(target=self._pump,
                                     args=(conn_idx, direction, src, dst),
                                     daemon=True)
                t.start()
                self._threads.append(t)
            self._threads = [t for t in self._threads if t.is_alive()]

    def _sever_pair(self, *socks: socket.socket) -> None:
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def _relay(self, src: socket.socket, dst: socket.socket, n: int) -> None:
        """Move exactly ``n`` payload bytes src->dst in bounded chunks."""
        left = n
        buf = bytearray(min(self._CHUNK, max(1, n)))
        while left:
            want = min(len(buf), left)
            got = src.recv_into(memoryview(buf)[:want], want)
            if got == 0:
                raise ConnectionError("peer closed mid-frame")
            dst.sendall(memoryview(buf)[:got])
            left -= got

    def _frame_delay(self, rng, nbytes: int) -> float:
        """Per-frame slow-NIC delay: serialization time at the configured
        bandwidth plus one seeded jitter draw.  Deterministic per
        (seed, conn, direction, frame ordinal) and the stream's frame
        sizes — the reproducibility contract throttled chaos runs rely
        on."""
        d = 0.0
        if self.bandwidth_bytes_per_s:
            d += nbytes / self.bandwidth_bytes_per_s
        if rng is not None:
            lo, hi = self.jitter_delay_s
            d += float(rng.uniform(lo, hi))
        return d

    def _pump(self, conn_idx: int, direction: str,
              src: socket.socket, dst: socket.socket) -> None:
        frame_idx = 0
        # slow-NIC emulation state: applies to this pump only when its
        # conn ordinal is in slow_conns (or no restriction is set)
        throttled = ((self.slow_conns is None or conn_idx in self.slow_conns)
                     and (self.bandwidth_bytes_per_s is not None
                          or self.jitter_delay_s is not None))
        rng = (np.random.default_rng(
            (0 if self.seed is None else int(self.seed), conn_idx,
             0 if direction == "c2s" else 1))
            if throttled and self.jitter_delay_s is not None else None)
        try:
            while True:
                hdr = b""
                while len(hdr) < 8:
                    chunk = src.recv(8 - len(hdr))
                    if not chunk:
                        raise ConnectionError("EOF")
                    hdr += chunk
                (n,) = struct.unpack(">Q", hdr)
                fault = self.plan.lookup(conn_idx, direction, frame_idx)
                if fault is not None:
                    self.faults_fired.append(fault)
                    if fault.kind == SEVER:
                        self._sever_pair(src, dst)
                        return
                    if fault.kind == TRUNCATE:
                        # forward the header claiming n bytes, deliver only
                        # keep_bytes, then die: the receiver is left
                        # blocked mid-frame exactly like a crashed peer
                        keep = min(int(fault.keep_bytes), n)
                        dst.sendall(hdr)
                        if keep:
                            self._relay(src, dst, keep)
                        self._sever_pair(src, dst)
                        return
                    if fault.kind == DELAY:
                        time.sleep(fault.delay_s)
                if self.delay_all_s > 0.0:
                    time.sleep(self.delay_all_s)
                if throttled:
                    d = self._frame_delay(rng, 8 + n)
                    if d > 0.0:
                        time.sleep(d)
                dst.sendall(hdr)
                self._relay(src, dst, n)
                frame_idx += 1
        except (ConnectionError, OSError):
            # one side died (or a planned sever on the twin pump): make
            # sure the other side observes it too, then exit quietly
            self._sever_pair(src, dst)


class WorkerKillPlan:
    """Deterministic in-process worker kills for the trainers'
    ``fault_hook``: raises :class:`InjectedWorkerFault` the first time a
    planned ``(worker, window)`` boundary is reached — and never again for
    that pair, so a supervisor-restarted worker replaying the same window
    proceeds.  Thread-safe (each worker runs its own thread)."""

    def __init__(self, kills: Sequence[Tuple[int, int]] = (),
                 seed: Optional[int] = None):
        self.seed = seed
        self.kills: Set[Tuple[int, int]] = {(int(w), int(k)) for w, k in kills}
        self.fired: List[Tuple[int, int]] = []
        self._lock = threading.Lock()

    @classmethod
    def random(cls, seed: int, num_workers: int, windows: int,
               n_kills: int = 1) -> "WorkerKillPlan":
        rng = np.random.default_rng(seed)
        kills = {(int(rng.integers(0, max(1, num_workers))),
                  int(rng.integers(1, max(2, windows))))
                 for _ in range(n_kills)}
        return cls(kills, seed=seed)

    def hook(self, worker: int, window: int) -> None:
        """Pass as ``fault_hook=plan.hook``."""
        key = (worker, window)
        with self._lock:
            if key in self.kills and key not in self.fired:
                self.fired.append(key)
                raise InjectedWorkerFault(
                    f"injected fault: worker {worker} dies at window {window} "
                    f"(plan seed={self.seed})")


class InjectedWorkerFault(RuntimeError):
    """The exception :class:`WorkerKillPlan` kills workers with — a
    distinct type so tests can assert the recorded error is the injected
    one and not an incidental bug."""


class WorkerPreempted(RuntimeError):
    """The notice :class:`SpotPreemptionPlan` delivers — the in-process
    analog of SIGTERM-with-a-deadline from a spot/preemptible scheduler.
    Unlike :class:`InjectedWorkerFault` (the SIGKILL analog) the worker
    is expected to DRAIN: finish in-flight commits, flush residuals,
    send BYE within ``deadline_s``, and let the supervisor respawn a
    replacement against the current center."""

    def __init__(self, worker: int, window: int, deadline_s: float):
        super().__init__(
            f"spot preemption notice: worker {worker} at window {window}, "
            f"drain deadline {deadline_s:g}s")
        self.worker = int(worker)
        self.window = int(window)
        self.deadline_s = float(deadline_s)


class SpotPreemptionPlan:
    """Deterministic planned-preemption drill (ISSUE 19) for the
    trainers' ``fault_hook``: raises :class:`WorkerPreempted` the first
    time a planned ``(worker, window)`` boundary is reached — and never
    again for that pair, so the respawned replacement replaying the same
    window proceeds.  Thread-safe (each worker runs its own thread).

    The trainer's autoscale path catches the notice, drains the client
    gracefully (every in-flight commit acked, int8 residuals flushed,
    BYE sent), records the drain in ``worker_preemptions``, and
    respawns — planned preemptions do not count against
    ``max_worker_restarts``."""

    def __init__(self, preemptions: Sequence[Tuple[int, int]] = (),
                 deadline_s: float = 5.0):
        self.preemptions: Set[Tuple[int, int]] = {
            (int(w), int(k)) for w, k in preemptions}
        self.deadline_s = float(deadline_s)
        self.fired: List[Tuple[int, int]] = []
        # monotonic timestamp per firing, aligned with ``fired`` — the
        # bench splits its throughput window log on these
        self.fired_at: List[float] = []
        self._lock = threading.Lock()

    def hook(self, worker: int, window: int) -> None:
        """Pass as ``fault_hook=plan.hook``."""
        key = (worker, window)
        with self._lock:
            if key in self.preemptions and key not in self.fired:
                self.fired.append(key)
                self.fired_at.append(time.monotonic())
                raise WorkerPreempted(worker, window, self.deadline_s)


class HubKillPlan:
    """Deterministic kill-primary drill (ISSUE 7): crash a hub —
    ``hub.kill()``, the SIGKILL-equivalent teardown — once it has applied
    ``after_commits`` commits.  Scheduling on the hub's own commit clock
    (not wall time) makes the drill replay at the same training progress
    every run, so failover tests and the bench's failover leg are
    comparable across machines.

    ``start(hub)`` spawns the watcher; ``fired`` is set once the kill
    happened, with ``fired_at_clock`` recording the commit count at the
    trigger — the "last primary-acked clock" bound the replica's center
    must meet after promotion."""

    def __init__(self, after_commits: int, poll_interval: float = 0.002):
        self.after_commits = int(after_commits)
        self.poll_interval = float(poll_interval)
        self.fired = threading.Event()
        self.fired_at_clock: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._cancel = threading.Event()

    def start(self, hub) -> "HubKillPlan":
        def watch():
            while not self._cancel.is_set():
                n = hub.num_updates
                if n >= self.after_commits:
                    # read the clock BEFORE the kill: everything applied
                    # up to here was (or is being) acked to some worker
                    self.fired_at_clock = int(n)
                    hub.kill()
                    self.fired.set()
                    return
                time.sleep(self.poll_interval)

        self._thread = threading.Thread(target=watch, daemon=True)
        self._thread.start()
        return self

    def cancel(self) -> None:
        """Stop watching without killing (drill teardown on test failure)."""
        self._cancel.set()

    def join(self, timeout: Optional[float] = 30.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)


class ShardedChaosProxy:
    """One :class:`ChaosProxy` per shard hub: clients connect to
    ``proxy.ports[s]`` instead of shard ``s``'s real port, and the shared
    ``plan``'s faults are routed to the proxy fronting ``fault.shard`` —
    so a chaos test can sever exactly one shard connection of a striped
    worker while the other stripes keep flowing (the partial-stripe
    failure mode only a sharded hub has).

    ``upstreams`` is one ``(host, port)`` per shard, aligned with the
    deployment's :class:`~distkeras_tpu.runtime.parameter_server.
    ShardPlan`.  Accept ordinals and frame counts stay PER SHARD PROXY —
    conn 0 is each shard's first accepted connection, exactly as with a
    single :class:`ChaosProxy`."""

    def __init__(self, upstreams: Sequence[Tuple[str, int]],
                 plan: Optional[FaultPlan] = None,
                 host: str = "127.0.0.1"):
        plan = plan or FaultPlan()
        self.plan = plan
        self.proxies: List[ChaosProxy] = []
        for sid, (up_host, up_port) in enumerate(upstreams):
            shard_faults = [f for f in plan.faults if f.shard == sid]
            self.proxies.append(ChaosProxy(
                up_host, up_port,
                plan=FaultPlan(shard_faults, seed=plan.seed), host=host))

    @property
    def ports(self) -> List[int]:
        return [p.port for p in self.proxies]

    @property
    def faults_fired(self) -> List[Fault]:
        return [f for p in self.proxies for f in p.faults_fired]

    def start(self) -> "ShardedChaosProxy":
        started = []
        try:
            for p in self.proxies:
                p.start()
                started.append(p)
        except BaseException:
            for p in started:
                try:
                    p.stop()
                except Exception:
                    pass
            raise
        return self

    def stop(self) -> None:
        for p in self.proxies:
            p.stop()

    def __enter__(self) -> "ShardedChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


__all__ = [
    "Fault", "FaultPlan", "ChaosProxy", "ShardedChaosProxy", "WorkerKillPlan",
    "HubKillPlan", "InjectedWorkerFault", "SpotPreemptionPlan",
    "WorkerPreempted", "SEVER", "DELAY", "TRUNCATE",
]
