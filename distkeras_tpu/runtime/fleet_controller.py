"""Hub-side fleet controller (ISSUE 19): act on capacity, don't just
detect its loss.

The health plane already *names* the problems — ``straggler``,
``staleness_drift``, ``throughput_regression`` — and the adaptive hub
reacts inside the aggregation math (rate scales, backpressure).  The
:class:`FleetController` closes the next loop up: it subscribes to the
:class:`~distkeras_tpu.observability.health.HealthMonitor` push hook and
changes the FLEET —

- **spawn** a replacement worker when fleet throughput lags the frozen
  run-start EWMA (the monitor's ``throughput_regression`` detector),
  cooldown-limited so one sustained regression does not fork-bomb the
  host;
- **retire** a worker the ``staleness_drift`` detector names
  persistently (``drift_strikes`` consecutive namings), never below
  ``min_fleet`` — the drain rides the existing elastic-membership path,
  so ADAG denominators shrink exactly as for a voluntary leave
  (arXiv:2204.03211's elastic aggregation semantics; arXiv:1611.04581's
  staleness analysis is why retiring beats waiting the straggler out);
- **respawn** after a planned preemption
  (:class:`~distkeras_tpu.runtime.faults.SpotPreemptionPlan` /
  SIGTERM-with-deadline): the drained worker's exit is authorized
  capacity loss, the replacement re-admits against the current center.

Decision *mechanics* are injected (``spawn_fn`` / ``retire_fn``): the
async trainers pass thread-respawning closures, the launcher passes
nothing and runs the controller in advisory mode (decisions recorded +
telemetry only — an operator or supervisor acts on them).  Both
callbacks are invoked OUTSIDE the controller lock, so they may take hub
or trainer locks freely; the controller lock is a leaf.

The join/drain/admission lifecycle the controller participates in is
model-checked in ``analysis/protocol_model.FLEET_RULES`` /
``explore_fleet`` — the contract predates this code.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Set

from distkeras_tpu import observability as obs

__all__ = ["FleetController"]


class FleetController:
    """Subscribes to a ``HealthMonitor`` and acts on capacity events.

    Parameters
    ----------
    monitor:
        The :class:`HealthMonitor` to subscribe to.  The subscription is
        released by :meth:`stop`.
    spawn_fn:
        ``spawn_fn(worker)`` — start a replacement for ``worker`` (or a
        fresh worker when ``worker`` is ``None``).  ``None`` = advisory
        mode: the decision is recorded and counted but nothing spawns.
    retire_fn:
        ``retire_fn(worker)`` — request a graceful drain of ``worker``
        (finish in-flight commits, flush residuals, BYE, detach).
        ``None`` = advisory mode.
    min_fleet:
        Never retire below this many live workers.
    max_spawns:
        Lifetime cap on throughput-triggered spawns (a regression that
        spawning cannot fix must not spawn forever).
    drift_strikes:
        Consecutive ``staleness_drift`` namings before a worker is
        retired (one firing can be a scheduling hiccup; the cooldown on
        the detector makes each strike a distinct episode).
    cooldown_s:
        Minimum seconds between throughput-triggered spawns.
    """

    def __init__(self, monitor: Any, *,
                 spawn_fn: Optional[Callable[[Optional[int]], Any]] = None,
                 retire_fn: Optional[Callable[[str], Any]] = None,
                 min_fleet: int = 1,
                 max_spawns: int = 8,
                 drift_strikes: int = 3,
                 cooldown_s: float = 5.0,
                 decision_capacity: int = 256):
        if min_fleet < 1:
            raise ValueError(f"min_fleet must be >= 1, got {min_fleet}")
        if drift_strikes < 1:
            raise ValueError(
                f"drift_strikes must be >= 1, got {drift_strikes}")
        self.monitor = monitor
        self.spawn_fn = spawn_fn
        self.retire_fn = retire_fn
        self.min_fleet = int(min_fleet)
        self.max_spawns = int(max_spawns)
        self.drift_strikes = int(drift_strikes)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._live: Set[str] = set()
        self._retiring: Set[str] = set()
        self._strikes: Dict[str, int] = {}
        self._decisions: Deque[Dict[str, Any]] = collections.deque(
            maxlen=int(decision_capacity))
        self._last_spawn = 0.0
        self._spawns = 0
        self._retires = 0
        self._preemptions = 0
        self._stopped = False
        self._sub = monitor.subscribe(self.on_event) \
            if monitor is not None else None

    # -- the push hook ---------------------------------------------------------

    def on_event(self, event: Any) -> None:
        """Monitor callback — runs on the emitting thread, outside the
        monitor lock (the subscribe contract), and must never raise."""
        kind = getattr(event, "kind", None)
        if kind == "throughput_regression":
            self._maybe_spawn(event)
        elif kind == "staleness_drift":
            worker = getattr(event, "worker", None)
            if worker is not None:
                self._maybe_retire(str(worker), event)

    def _maybe_spawn(self, event: Any) -> None:
        now = time.monotonic()
        with self._lock:
            if self._stopped or self._spawns >= self.max_spawns \
                    or now - self._last_spawn < self.cooldown_s:
                return
            self._last_spawn = now
            self._spawns += 1
            self._push_decision_locked(
                "spawn", worker=None,
                reason="throughput_regression",
                evidence=dict(getattr(event, "evidence", {}) or {}))
            fn = self.spawn_fn
        if obs.enabled():
            obs.counter("ps_fleet_spawns_total").inc()
        if fn is not None:
            try:
                fn(None)
            except Exception:
                pass  # a failed spawn must not take down the health plane

    def _maybe_retire(self, worker: str, event: Any) -> None:
        with self._lock:
            if self._stopped or worker in self._retiring:
                return
            strikes = self._strikes.get(worker, 0) + 1
            self._strikes[worker] = strikes
            if strikes < self.drift_strikes:
                return
            # never shrink below the floor: count workers that are live
            # and not already on their way out
            remaining = len(self._live - self._retiring)
            if self._live and remaining <= self.min_fleet:
                return
            self._retiring.add(worker)
            self._strikes.pop(worker, None)
            self._retires += 1
            self._push_decision_locked(
                "retire", worker=worker, reason="staleness_drift",
                evidence=dict(getattr(event, "evidence", {}) or {}))
            fn = self.retire_fn
        if obs.enabled():
            obs.counter("ps_fleet_retires_total").inc()
        if fn is not None:
            try:
                fn(worker)
            except Exception:
                pass

    # -- lifecycle notifications (trainer / launcher side) ---------------------

    def notify_worker_started(self, worker: Any) -> None:
        with self._lock:
            self._live.add(str(worker))
            self._retiring.discard(str(worker))
            self._strikes.pop(str(worker), None)

    def notify_worker_exited(self, worker: Any) -> None:
        with self._lock:
            self._live.discard(str(worker))
            self._retiring.discard(str(worker))
            self._strikes.pop(str(worker), None)

    def notify_preempted(self, worker: Any,
                         deadline_s: Optional[float] = None) -> bool:
        """A planned preemption notice landed on ``worker``.  Records the
        decision and returns ``True`` when a replacement respawn is
        authorized (always, unless the controller is stopped) — the
        trainer's supervisor respawns WITHOUT burning a restart budget
        slot, because planned capacity loss is not a crash."""
        with self._lock:
            if self._stopped:
                return False
            self._preemptions += 1
            self._push_decision_locked(
                "respawn", worker=str(worker), reason="spot_preemption",
                evidence={} if deadline_s is None
                else {"deadline_s": float(deadline_s)})
        if obs.enabled():
            obs.counter("ps_fleet_preemptions_total").inc()
        return True

    def notify_drained(self, worker: Any, *, clean: bool = True) -> None:
        """The worker finished its graceful drain (in-flight commits
        acked, residuals flushed, BYE sent)."""
        with self._lock:
            self._push_decision_locked(
                "drained", worker=str(worker), reason="drain_complete",
                evidence={"clean": bool(clean)})
            self._live.discard(str(worker))
            self._retiring.discard(str(worker))

    # -- introspection ---------------------------------------------------------

    def _push_decision_locked(self, action: str, *, worker: Optional[str],
                              reason: str,
                              evidence: Dict[str, Any]) -> None:
        self._decisions.append({
            "action": action, "worker": worker, "reason": reason,
            "ts_wall": time.time(), "evidence": evidence})

    def decisions(self) -> List[Dict[str, Any]]:
        """All recorded decisions, oldest first, JSON-safe copies."""
        with self._lock:
            return [dict(d) for d in self._decisions]

    def fleet_size(self) -> int:
        with self._lock:
            return len(self._live)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"live": len(self._live),
                   "retiring": len(self._retiring),
                   "spawns": self._spawns,
                   "retires": self._retires,
                   "preemptions": self._preemptions,
                   "decisions": len(self._decisions)}
        if obs.enabled():
            obs.gauge("ps_fleet_target_size").set(
                out["live"] + out["spawns"] - out["retires"])
        return out

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
        if self.monitor is not None and self._sub is not None:
            try:
                self.monitor.unsubscribe(self._sub)
            except Exception:
                pass
            self._sub = None
