"""Job deployment — reference parity for ``distkeras/job_deployment.py``.

The reference shipped "Punchcard" (SURVEY.md §2.18 [M]): a long-running
service on the cluster head accepting remote job submissions — each job
described by an identity/secret, a data path, and a trainer config — plus
a ``Job`` client with ``send``/``run``.  Mechanism recalled as Flask-or-
sockets [L]; no verified file:line citations exist (reference mount empty).

TPU-native redesign, not a port:

- Transport is this repo's framed JSON/tensor protocol
  (``runtime/networking.py``) — no pickle, no Flask.  Control messages are
  JSON frames; inline datasets and trained models travel as raw frames.
- Auth is HMAC-SHA256 challenge/response: the server sends a fresh nonce
  per connection and the client proves possession of the shared secret
  without the secret (or a replayable token) ever crossing the wire.
  The reference's secrets-file identity [L] becomes this shared secret.
- The service owns the host's TPU devices, so jobs run FIFO on one
  executor thread — "queue on the cluster head" semantics without Spark.
- Datasets arrive either inline (tensor frame, schema in the job JSON) or
  as a server-side ``.npz`` path confined to the daemon's ``data_root``.

Typical use::

    pc = Punchcard(secret="s3cret", data_root="/data")   # on the TPU host
    pc.start()

    job = Job(host, pc.port, secret="s3cret", name="mnist",
              model=spec, trainer="adag",
              trainer_kwargs={"num_epoch": 5, "batch_size": 64},
              data=train_ds)                              # anywhere
    model = job.run()                                     # submit+wait+fetch
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import queue
import secrets as _secrets
import shutil
import socket
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.runtime import networking as net

PROTOCOL_VERSION = 1

# frame-size bounds: before auth only a tiny hello/auth message is legal;
# after auth, control JSON stays small; bulk tensor frames get their own cap
AUTH_FRAME_LIMIT = 64 * 1024
CTRL_FRAME_LIMIT = 8 * (1 << 20)
DATA_FRAME_LIMIT = 8 * (1 << 30)

# job lifecycle
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"

# single source for name validation AND the late-import registry (the
# daemon module must stay importable without jax)
_TRAINER_PATHS = {
    "single": ("distkeras_tpu.trainers", "SingleTrainer"),
    "adag": ("distkeras_tpu.trainers", "ADAG"),
    "downpour": ("distkeras_tpu.trainers", "DOWNPOUR"),
    "aeasgd": ("distkeras_tpu.trainers", "AEASGD"),
    "eamsgd": ("distkeras_tpu.trainers", "EAMSGD"),
    "dynsgd": ("distkeras_tpu.trainers", "DynSGD"),
    "averaging": ("distkeras_tpu.trainers", "AveragingTrainer"),
    "ensemble": ("distkeras_tpu.trainers", "EnsembleTrainer"),
    "async-adag": ("distkeras_tpu.runtime.async_trainer", "AsyncADAG"),
    "async-downpour": ("distkeras_tpu.runtime.async_trainer", "AsyncDOWNPOUR"),
    "async-aeasgd": ("distkeras_tpu.runtime.async_trainer", "AsyncAEASGD"),
    "async-eamsgd": ("distkeras_tpu.runtime.async_trainer", "AsyncEAMSGD"),
    "async-dynsgd": ("distkeras_tpu.runtime.async_trainer", "AsyncDynSGD"),
}
_TRAINER_NAMES = tuple(_TRAINER_PATHS)


def _trainer_registry() -> Dict[str, Any]:
    import importlib

    return {name: getattr(importlib.import_module(mod), attr)
            for name, (mod, attr) in _TRAINER_PATHS.items()}


def _mac(secret: str, nonce: str) -> str:
    return hmac.new(secret.encode("utf-8"), bytes.fromhex(nonce), hashlib.sha256).hexdigest()


class _FatalProtocolError(Exception):
    """The connection's byte stream is desynced; report once, then drop."""


class JobRecord:
    """Server-side state of one submitted job."""

    def __init__(self, job_id: str, job: Dict[str, Any]):
        self.job_id = job_id
        self.job = job
        self.state = QUEUED
        self.error: Optional[str] = None
        self.history: List[float] = []
        self.training_time: Optional[float] = None
        self.model_blobs: List[bytes] = []
        self.submitted_at = time.time()
        self.data: Optional[Dict[str, np.ndarray]] = None  # inline columns

    def public(self) -> Dict[str, Any]:
        return {
            "job_id": self.job_id,
            "name": self.job.get("name"),
            "trainer": self.job.get("trainer"),
            "state": self.state,
            "error": self.error,
            "history": self.history,
            "training_time": self.training_time,
            "num_models": len(self.model_blobs),
        }

    def manifest(self) -> Dict[str, Any]:
        """Everything needed to resurrect this record after a daemon
        restart EXCEPT bulk payloads (inline data -> data.npz, model blobs
        -> model_N.bin files beside the manifest)."""
        return {
            "job_id": self.job_id,
            "job": self.job,
            "state": self.state,
            "error": self.error,
            "history": self.history,
            "training_time": self.training_time,
            "num_models": len(self.model_blobs),
            "submitted_at": self.submitted_at,
        }

    @staticmethod
    def from_manifest(m: Dict[str, Any]) -> "JobRecord":
        rec = JobRecord(m["job_id"], m["job"])
        rec.state = m["state"]
        rec.error = m.get("error")
        rec.history = list(m.get("history") or [])
        rec.training_time = m.get("training_time")
        rec.submitted_at = m.get("submitted_at", time.time())
        return rec


class Punchcard:
    """The job-deployment daemon (reference: ``Punchcard`` service loop).

    One accept loop, one handler thread per connection, one FIFO executor
    thread (the host's TPU devices are a single resource).  ``port=0``
    binds an ephemeral port, read it from ``self.port`` after ``start()``.
    """

    def __init__(self, secret: str, host: str = "127.0.0.1", port: int = 0,
                 data_root: Optional[str] = None,
                 state_dir: Optional[str] = None, max_retained: int = 20):
        if not secret:
            raise ValueError("Punchcard requires a non-empty shared secret")
        self._secret = secret
        self._host = host
        self._port = port
        self._data_root = os.path.realpath(data_root) if data_root else None
        # durability (round-2 weak #6: a restart lost the queue, the running
        # job, and every fetchable model): job records + payloads spool to
        # state_dir and the queue reloads on start().  Defaults to
        # <data_root>/.punchcard-state when a data_root exists; None (no
        # data_root, no explicit state_dir) stays RAM-only.
        if state_dir is None and self._data_root is not None:
            state_dir = os.path.join(self._data_root, ".punchcard-state")
        self._state_dir = os.path.realpath(state_dir) if state_dir else None
        self._max_retained = int(max_retained)
        self._jobs: Dict[str, JobRecord] = {}
        self._queue: "queue.Queue[Optional[str]]" = queue.Queue()
        self._lock = threading.Lock()
        # serializes all spool mutation (handler threads save on cancel
        # while the executor saves transitions; shared tmp paths must not
        # interleave) and freezes the spool after stop() so an orphaned
        # executor can't corrupt state a restarted daemon now owns
        self._spool_lock = threading.Lock()
        self._running = False
        self._sock: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []

    # -- lifecycle -------------------------------------------------------------
    @property
    def port(self) -> int:
        if self._sock is None:
            raise RuntimeError("Punchcard not started")
        return self._sock.getsockname()[1]

    def start(self) -> "Punchcard":
        # bind FIRST: a second daemon pointed at a live daemon's port must
        # die on EADDRINUSE before it can touch (and corrupt) the spool
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self._host, self._port))
        self._sock.listen(16)
        try:
            self._acquire_spool_lock()
            self._running = True  # before reload: its saves must not be frozen
            self._reload_state()
        except BaseException:
            # a failed start must leak neither the bound port nor the lock
            self._running = False
            self._sock.close()
            self._sock = None
            self._release_spool_lock()
            raise
        for target in (self._accept_loop, self._executor_loop):
            th = threading.Thread(target=target, daemon=True)
            th.start()
            self._threads.append(th)
        return self

    def _acquire_spool_lock(self) -> None:
        """Exclusive spool ownership: two daemons sharing a state_dir would
        double-run each other's jobs and rmtree records the other serves.
        The lock is a pidfile; a stale lock (holder dead, e.g. SIGKILL) is
        taken over, so crashes never brick restarts."""
        if self._state_dir is None:
            return
        os.makedirs(self._state_dir, exist_ok=True)
        path = os.path.join(self._state_dir, "daemon.lock")
        # the whole check-remove-create sequence holds an flock on a guard
        # file: without it two daemons racing a stale lock can BOTH read the
        # dead pid, and the slower one's os.remove() deletes the faster
        # one's freshly created pidfile (TOCTOU) — then both own the spool
        import fcntl

        try:
            # 0o666 (pre-umask) so another user of a SHARED state_dir can
            # still open the guard after this process dies — a 0600 guard
            # would permanently block the cross-user stale-lock takeover
            # the pidfile's EPERM handling explicitly supports
            guard = os.open(os.path.join(self._state_dir, ".lock-guard"),
                            os.O_CREAT | os.O_RDWR, 0o666)
            try:
                # os.open's mode is masked by umask (022 → 0644), which
                # would deny other users the O_RDWR open and silently
                # reopen the TOCTOU this guard closes; fchmod realizes the
                # intended world-RW bits (best-effort: may not own the file)
                os.fchmod(guard, 0o666)
            except OSError:
                pass
        except PermissionError:
            # a prior owner created the guard with a restrictive umask and
            # we can't open it: degrade to unguarded acquisition (the
            # O_EXCL pidfile still provides mutual exclusion; only the
            # stale-takeover race window reopens) rather than bricking
            # every other user's restart forever
            guard = None
        try:
            if guard is not None:
                fcntl.flock(guard, fcntl.LOCK_EX)
            while True:
                try:
                    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.write(fd, str(os.getpid()).encode())
                    os.close(fd)
                    self._lock_path = path  # lint: unguarded-ok start-time store, before the accept/executor threads exist; all later mutation goes through _release_spool_lock under _lock
                    return
                except FileExistsError:
                    try:
                        with open(path) as f:
                            holder = int(f.read().strip() or "0")
                    except (OSError, ValueError):
                        holder = 0
                    alive = False
                    if holder == os.getpid():
                        alive = True  # a second daemon in THIS process is still
                        #               a second daemon — reject it too
                    elif holder > 0:
                        try:
                            os.kill(holder, 0)
                            alive = True
                        except ProcessLookupError:
                            alive = False
                        except PermissionError:
                            alive = True  # EPERM means the pid EXISTS (another
                            #               user's daemon) — standard pidfile idiom
                    if alive:
                        raise RuntimeError(
                            f"state_dir {self._state_dir!r} is owned by a live "
                            f"Punchcard daemon (pid {holder}); two daemons must "
                            "not share a spool") from None
                    try:
                        os.remove(path)  # stale: holder is gone, take over
                    except FileNotFoundError:
                        pass
        finally:
            if guard is not None:
                try:
                    fcntl.flock(guard, fcntl.LOCK_UN)
                finally:
                    os.close(guard)

    def stop(self) -> None:
        self._running = False  # also freezes the spool (see _save_record)
        self._queue.put(None)  # wake the executor
        if self._sock is not None:
            # close() alone does NOT wake a concurrently-blocked accept()
            # on Linux; shutdown() makes it return EINVAL immediately, which
            # the join below needs now that lock release waits on the threads
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        for th in self._threads:
            th.join(timeout=5)
        # release the pidfile only AFTER the executor thread confirmed exit:
        # dropping it while a job is still running would let a restarted
        # daemon requeue the spooled RUNNING record and execute it a second
        # time, concurrently, on the same devices.  If the join timed out the
        # lock stays for now (this pid is alive, so a takeover is correctly
        # refused) and the executor itself releases it when the job finally
        # ends (_executor_loop's exit path) — otherwise nothing ever would.
        if not any(th.is_alive() for th in self._threads):
            self._release_spool_lock()

    def _release_spool_lock(self) -> None:
        """Idempotent pidfile release; callable from stop() AND from the
        executor's own exit path (they may race after a timed-out join)."""
        with self._lock:
            lock = getattr(self, "_lock_path", None)
            self._lock_path = None
        if lock is not None:
            try:
                os.remove(lock)
            except OSError:
                pass

    # -- accept/handle ---------------------------------------------------------
    def _accept_loop(self) -> None:
        assert self._sock is not None
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            th = threading.Thread(target=self._handle, args=(conn,), daemon=True)
            th.start()

    def _handle(self, conn: socket.socket) -> None:
        nonce = _secrets.token_hex(16)
        authed = False
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            net.send_json(conn, {"punchcard": PROTOCOL_VERSION, "nonce": nonce,
                                 "data_limit": DATA_FRAME_LIMIT})
            while self._running:
                try:
                    # pre-auth only the tiny auth message is legal; post-auth
                    # control JSON gets the full control budget
                    req = net.recv_json(
                        conn, limit=CTRL_FRAME_LIMIT if authed else AUTH_FRAME_LIMIT)
                except (ConnectionError, OSError):
                    return
                except (ValueError, UnicodeDecodeError):
                    return  # oversized / desynced / non-JSON frame: drop connection
                if not isinstance(req, dict):
                    return  # valid JSON but not a request object: drop
                action = req.get("action")
                if not authed:
                    mac = req.get("mac", "")
                    if not isinstance(mac, str) or \
                            not hmac.compare_digest(mac, _mac(self._secret, nonce)):
                        net.send_json(conn, {"ok": False, "error": "authentication failed"})
                        return
                    authed = True
                    if action == "auth":  # dedicated handshake message
                        net.send_json(conn, {"ok": True})
                        continue
                try:
                    stop_after = self._dispatch(conn, action, req)
                except _FatalProtocolError as e:
                    net.send_json(conn, {"ok": False, "error": str(e)})
                    return  # stream is desynced; further frames are garbage
                except Exception as e:  # request error: report, keep serving
                    net.send_json(conn, {"ok": False, "error": f"{type(e).__name__}: {e}"})
                    continue
                if stop_after:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn: socket.socket, action: str, req: Dict[str, Any]) -> bool:
        if action == "submit":
            rec = self._submit(conn, req)
            net.send_json(conn, {"ok": True, "job_id": rec.job_id})
        elif action == "status":
            rec = self._get(req["job_id"])
            net.send_json(conn, {"ok": True, **rec.public()})
        elif action == "list":
            with self._lock:
                jobs = [r.public() for r in self._jobs.values()]
            net.send_json(conn, {"ok": True, "jobs": jobs})
        elif action == "cancel":
            rec = self._get(req["job_id"])
            with self._lock:
                if rec.state == QUEUED:
                    rec.state = CANCELLED
            self._save_record(rec)
            net.send_json(conn, {"ok": True, "state": rec.state})
        elif action == "fetch":
            rec = self._get(req["job_id"])
            if rec.state != DONE:
                net.send_json(conn, {"ok": False,
                                     "error": f"job {rec.job_id} is {rec.state}, not {DONE}"})
                return False
            net.send_json(conn, {"ok": True, "num_models": len(rec.model_blobs)})
            for blob in rec.model_blobs:
                net.send_frame(conn, blob)
        elif action == "telemetry":
            # remote telemetry pull (ISSUE #1): a running job's metrics —
            # PS counters, staleness gauges, window histograms, feed
            # gauges — and optionally the span ring as a Chrome trace,
            # readable WHILE the executor is mid-job (the registry and
            # tracer are thread-safe; no job lock is taken)
            resp: Dict[str, Any] = {
                "ok": True,
                "enabled": obs.enabled(),
                "metrics": obs.snapshot(),
            }
            if req.get("prometheus"):
                resp["prometheus"] = obs.render_prometheus()
            if req.get("trace"):
                resp["trace"] = obs.chrome_trace()
            if req.get("fleet"):
                # straggler/staleness attribution over this process's span
                # ring (ISSUE #5) — when a trace directory is configured
                # the report instead joins EVERY flushed process's spans.
                # ISSUE 8: the live collector rides along so the report's
                # coverage reflects streaming health too
                from distkeras_tpu.observability import health as _health
                from distkeras_tpu.observability.distributed import fleet_report

                resp["fleet"] = fleet_report(
                    trace_dir=os.environ.get("DKT_TRACE_DIR") or None,
                    live=_health.collector())
            if req.get("health"):
                # live fleet health (ISSUE 8): this process's collector
                # (per-worker sliding-window series, fed by wire action M
                # or direct folds) + the monitor's ringed HealthEvents —
                # the payload distkeras-top redraws.  Reading runs the
                # rate-limited detector pass, so polling IS the detection
                # cadence when no report has triggered one recently
                from distkeras_tpu.observability import health as _health

                resp["health"] = _health.health_snapshot()
            net.send_json(conn, resp)
        elif action == "shutdown":
            net.send_json(conn, {"ok": True})
            threading.Thread(target=self.stop, daemon=True).start()
            return True
        else:
            net.send_json(conn, {"ok": False, "error": f"unknown action {action!r}"})
        return False

    def _get(self, job_id: str) -> JobRecord:
        with self._lock:
            if job_id not in self._jobs:
                raise KeyError(f"unknown job_id {job_id!r}")
            return self._jobs[job_id]

    # -- durable state ---------------------------------------------------------
    def _job_dir(self, job_id: str) -> str:
        assert self._state_dir is not None
        return os.path.join(self._state_dir, "jobs", job_id)

    def _save_record(self, rec: JobRecord, with_payloads: bool = False) -> None:
        """Persist the manifest (and optionally inline data / model blobs)
        atomically: tmp file + rename, so a crash mid-write leaves either
        the old or the new manifest, never a torn one.  All spool mutation
        serializes on ``_spool_lock`` and freezes once ``stop()`` ran — an
        orphaned executor thread must not overwrite state a restarted
        daemon may already own."""
        if self._state_dir is None:
            return
        with self._spool_lock:
            if not self._running:
                return
            d = self._job_dir(rec.job_id)
            os.makedirs(d, exist_ok=True)
            if with_payloads and rec.data is not None:
                # hand-rolled npz (zip of .npy members): np.savez(**cols)
                # would collide with its own 'file' parameter for a column
                # literally named "file"
                import io
                import zipfile

                tmp = os.path.join(d, ".data.npz.tmp")
                with zipfile.ZipFile(tmp, "w", zipfile.ZIP_STORED) as zf:
                    for k, v in rec.data.items():
                        buf = io.BytesIO()
                        np.save(buf, np.asarray(v))
                        zf.writestr(f"{k}.npy", buf.getvalue())
                os.replace(tmp, os.path.join(d, "data.npz"))
            if with_payloads:
                for i, blob in enumerate(rec.model_blobs):
                    tmp = os.path.join(d, f".model_{i}.bin.tmp")
                    with open(tmp, "wb") as f:
                        f.write(blob)
                    os.replace(tmp, os.path.join(d, f"model_{i}.bin"))
            tmp = os.path.join(d, ".manifest.json.tmp")
            with open(tmp, "w") as f:
                json.dump(rec.manifest(), f)
            os.replace(tmp, os.path.join(d, "manifest.json"))

    def _drop_spooled_data(self, rec: JobRecord) -> None:
        if self._state_dir is None:
            return
        with self._spool_lock:
            if not self._running:
                return
            path = os.path.join(self._job_dir(rec.job_id), "data.npz")
            if os.path.exists(path):
                os.remove(path)

    def _evict_old(self) -> None:
        """Cap disk/RAM retention: beyond ``max_retained`` terminal jobs,
        the oldest are dropped entirely (records and spool dirs)."""
        with self._lock:
            terminal = sorted(
                (r for r in self._jobs.values()
                 if r.state in (DONE, FAILED, CANCELLED)),
                key=lambda r: r.submitted_at)
            victims = terminal[:max(0, len(terminal) - self._max_retained)]
            for rec in victims:
                del self._jobs[rec.job_id]
        if self._state_dir is not None:
            with self._spool_lock:
                if not self._running:
                    return
                for rec in victims:
                    shutil.rmtree(self._job_dir(rec.job_id), ignore_errors=True)

    def _reload_state(self) -> None:
        """Resurrect spooled jobs: terminal records become fetchable again
        (model blobs read back), queued AND interrupted-running jobs are
        re-queued in original submission order."""
        if self._state_dir is None:
            return
        jobs_root = os.path.join(self._state_dir, "jobs")
        os.makedirs(jobs_root, exist_ok=True)
        recs = []
        for job_id in os.listdir(jobs_root):
            d = os.path.join(jobs_root, job_id)
            try:
                with open(os.path.join(d, "manifest.json")) as f:
                    m = json.load(f)
                rec = JobRecord.from_manifest(m)
                num_models = int(m.get("num_models") or 0)
            except (OSError, ValueError, KeyError, TypeError):
                continue  # torn/foreign dir: skip, don't brick the daemon
            if rec.state == DONE:
                try:
                    blobs = []
                    for i in range(num_models):
                        with open(os.path.join(d, f"model_{i}.bin"), "rb") as f:
                            blobs.append(f.read())
                    rec.model_blobs = blobs
                except OSError:
                    rec.state = FAILED
                    rec.error = "daemon restart: model blobs missing from spool"
                    self._save_record(rec)  # memory and spool must agree
            elif rec.state in (QUEUED, RUNNING):
                if rec.state == RUNNING:
                    # the interrupted run never completed; start over
                    rec.state = QUEUED
                data_path = os.path.join(d, "data.npz")
                if os.path.exists(data_path):
                    try:
                        with np.load(data_path) as npz:
                            rec.data = {k: npz[k] for k in npz.files}
                    except Exception:  # torn/foreign npz: fail the JOB, not boot
                        rec.state = FAILED
                        rec.error = "daemon restart: spooled dataset unreadable"
                        self._save_record(rec)
                elif "columns" in (rec.job.get("dataset") or {}):
                    rec.state = FAILED
                    rec.error = "daemon restart: inline dataset missing from spool"
                    self._save_record(rec)
            recs.append(rec)
        recs.sort(key=lambda r: r.submitted_at)
        with self._lock:
            for rec in recs:
                self._jobs[rec.job_id] = rec
        for rec in recs:
            if rec.state == QUEUED:
                self._save_record(rec)  # persist the RUNNING->QUEUED reset
                self._queue.put(rec.job_id)
        # an operator may restart with a LOWER --max-retained over a large
        # spool; trim immediately rather than on the next completed job
        self._evict_old()

    def _submit(self, conn: socket.socket, req: Dict[str, Any]) -> JobRecord:
        job = req["job"]
        dataset = job.get("dataset") or {}
        trainer = job.get("trainer")
        if trainer not in _TRAINER_NAMES:
            raise ValueError(f"unknown trainer {trainer!r}; known: {_TRAINER_NAMES}")
        rec = JobRecord(uuid.uuid4().hex[:12], job)
        if "columns" in dataset:
            # two-phase inline upload: validation above happens BEFORE the
            # go-ahead, so a rejected client never streams its dataset (and
            # never hits a TCP reset racing the error reply); blobs arrive
            # in schema order, reinterpreted by declared dtype/shape
            net.send_json(conn, {"ok": True, "send_data": True})
            try:
                _, blobs = net.recv_tensors(conn, limit=DATA_FRAME_LIMIT)
            except ValueError as e:
                # declared frame over the data cap: unread payload bytes are
                # in flight, the stream can't be reused
                raise _FatalProtocolError(str(e)) from None
            schema = dataset["columns"]
            if len(blobs) != len(schema):
                raise ValueError(f"inline data has {len(blobs)} tensors, schema {len(schema)}")
            cols = {}
            for meta, blob in zip(schema, blobs):
                # zero-copy reinterpret of the received uint8 buffer
                arr = np.frombuffer(blob, dtype=np.dtype(meta["dtype"]))
                cols[meta["name"]] = arr.reshape(meta["shape"])
            rec.data = cols
        elif "path" in dataset:
            self._resolve_data_path(dataset["path"])  # validate before queuing
        else:
            raise ValueError("job.dataset needs either 'columns' (inline) or 'path'")
        with self._lock:
            self._jobs[rec.job_id] = rec
        self._save_record(rec, with_payloads=True)
        self._queue.put(rec.job_id)
        return rec

    def _resolve_data_path(self, path: str) -> str:
        if self._data_root is None:
            raise ValueError("this Punchcard accepts only inline datasets (no data_root)")
        full = os.path.realpath(os.path.join(self._data_root, path))
        if not (full == self._data_root or full.startswith(self._data_root + os.sep)):
            raise ValueError(f"dataset path {path!r} escapes the data root")
        if self._state_dir is not None and (
                full == self._state_dir
                or full.startswith(self._state_dir + os.sep)):
            # the spool holds OTHER submitters' inline datasets and models
            # (and eviction may delete files mid-run); it is not servable
            raise ValueError(f"dataset path {path!r} points into the daemon's "
                             "state spool")
        if not os.path.exists(full):
            raise FileNotFoundError(f"dataset path {path!r} not found under data root")
        return full

    # -- executor --------------------------------------------------------------
    def _executor_loop(self) -> None:
        while True:
            job_id = self._queue.get()
            if job_id is None or not self._running:
                # stop() must not let queued jobs keep the devices.  If
                # stop()'s join timed out because a job outlived it, stop()
                # left the pidfile for us — release it now that no job can
                # ever run again, or restarts in this process would be
                # refused forever ("owned by a live daemon", our own pid)
                self._release_spool_lock()
                return
            rec = self._jobs.get(job_id)
            if rec is None:
                continue  # evicted while queued (restart + cap)
            try:
                with self._lock:
                    if rec.state != QUEUED:
                        continue  # cancelled while queued (finally still runs)
                    rec.state = RUNNING
                self._save_record(rec)
                with obs.span("punchcard.job", job_id=rec.job_id,
                              trainer=rec.job.get("trainer")):
                    self._run(rec)
                rec.state = DONE
            except Exception as e:
                rec.error = f"{type(e).__name__}: {e}"
                rec.state = FAILED
            finally:
                obs.counter("punchcard_jobs_total", state=rec.state).inc()
                # a long-running daemon must not pin submitted datasets in
                # RAM — cancelled ones included; only the fetchable model
                # blobs outlive the run (and the spooled data.npz goes too).
                # Spool-write failures (ENOSPC, permissions) must NOT kill
                # the executor thread — durability degrades, execution lives
                rec.data = None
                try:
                    self._save_record(rec, with_payloads=True)
                    self._drop_spooled_data(rec)
                    self._evict_old()
                except Exception as e:
                    rec.error = ((rec.error + "; ") if rec.error else "") +                         f"spool write failed: {type(e).__name__}: {e}"
                    import sys as _sys
                    print(f"punchcard: spool write failed for {rec.job_id}: {e}",
                          file=_sys.stderr, flush=True)

    def _run(self, rec: JobRecord) -> None:
        from distkeras_tpu.data.dataset import Dataset
        from distkeras_tpu.models.base import Model, ModelSpec

        job = rec.job
        spec = ModelSpec.from_dict(job["model"])
        kwargs = dict(job.get("trainer_kwargs") or {})
        trainer = _trainer_registry()[job["trainer"]](spec, **kwargs)

        if rec.data is not None:
            ds = Dataset(rec.data)
        else:
            full = self._resolve_data_path(job["dataset"]["path"])
            with np.load(full) as npz:
                ds = Dataset({k: npz[k] for k in npz.files})

        result = trainer.train(ds)
        models = result if isinstance(result, list) else [result]
        rec.model_blobs = [m.serialize() for m in models]
        rec.history = [float(x) for x in getattr(trainer, "history", [])]
        rec.training_time = trainer.get_training_time()


class _Conn:
    """One authenticated client connection; reusable for many requests
    (the server's handler loop keeps serving until the socket closes)."""

    def __init__(self, host: str, port: int, secret: str):
        self.sock = net.connect(host, port)
        try:
            hello = net.recv_json(self.sock)
            self.data_limit = hello.get("data_limit")
            # dedicated auth handshake: proves the secret (and surfaces
            # PermissionError) before any real payload is built or sent
            net.send_json(self.sock, {"action": "auth",
                                      "mac": _mac(secret, hello["nonce"])})
            resp = net.recv_json(self.sock)
            if not resp.get("ok"):
                raise PermissionError(resp.get("error", "authentication failed"))
        except BaseException:
            self.close()
            raise

    def request(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        net.send_json(self.sock, payload)
        resp = net.recv_json(self.sock)
        if not resp.get("ok"):
            err = resp.get("error", "request failed")
            if "authentication" in err:
                raise PermissionError(err)
            raise RuntimeError(err)
        return resp

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self) -> "_Conn":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Job:
    """Client handle for one remote job (reference: ``Job.send``/``run``)."""

    def __init__(self, host: str, port: int, secret: str, name: str,
                 model: Any, trainer: str = "adag",
                 trainer_kwargs: Optional[Dict[str, Any]] = None,
                 data: Optional[Any] = None, dataset_path: Optional[str] = None):
        from distkeras_tpu.models.base import Model, ModelSpec

        if isinstance(model, Model):
            model = model.spec
        if not isinstance(model, ModelSpec):
            raise TypeError(f"model must be a Model or ModelSpec, got {type(model)}")
        if (data is None) == (dataset_path is None):
            raise ValueError("pass exactly one of data= (inline) or dataset_path= (server-side)")
        self.host, self.port, self.secret, self.name = host, port, secret, name
        self.model_spec = model
        self.trainer = trainer
        self.trainer_kwargs = dict(trainer_kwargs or {})
        self.dataset_path = dataset_path
        self._columns = None
        if data is not None:
            cols = data._columns if hasattr(data, "_columns") else dict(data)
            self._columns = {k: np.ascontiguousarray(v) for k, v in cols.items()}
        self.job_id: Optional[str] = None

    # -- wire helpers ----------------------------------------------------------
    def _connect(self) -> _Conn:
        return _Conn(self.host, self.port, self.secret)

    def _request(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        with self._connect() as conn:
            return conn.request(payload)

    # -- public API ------------------------------------------------------------
    def submit(self) -> str:
        job: Dict[str, Any] = {
            "name": self.name,
            "trainer": self.trainer,
            "trainer_kwargs": self.trainer_kwargs,
            "model": self.model_spec.to_dict(),
        }
        if self._columns is not None:
            job["dataset"] = {"columns": [
                {"name": k, "dtype": v.dtype.str, "shape": list(v.shape)}
                for k, v in self._columns.items()]}
        else:
            job["dataset"] = {"path": self.dataset_path}
        with self._connect() as conn:
            resp = conn.request({"action": "submit", "job": job})
            if resp.get("send_data"):
                # two-phase upload: the server validated the job and asked
                # for the dataset; stream it and read the final reply
                # pre-flight the encoded-frame size the server will check
                nbytes = net.encoded_tensors_size(list(self._columns.values()))
                if conn.data_limit and nbytes > conn.data_limit:
                    raise ValueError(
                        f"inline dataset frame is {nbytes} bytes; daemon accepts "
                        f"at most {conn.data_limit} — use a server-side dataset_path")
                net.send_tensors(conn.sock, net.ACTION_COMMIT,
                                 list(self._columns.values()))
                resp = net.recv_json(conn.sock)
                if not resp.get("ok"):
                    raise RuntimeError(resp.get("error", "submit failed"))
        self.job_id = resp["job_id"]
        return self.job_id

    def status(self) -> Dict[str, Any]:
        if self.job_id is None:
            raise RuntimeError("job not submitted")
        return self._request({"action": "status", "job_id": self.job_id})

    def telemetry(self, trace: bool = False, fleet: bool = False,
                  health: bool = False) -> Dict[str, Any]:
        """The daemon's live telemetry snapshot (see :func:`fetch_telemetry`);
        daemon-wide, so it does not require this job to be submitted."""
        return fetch_telemetry(self.host, self.port, self.secret, trace=trace,
                               fleet=fleet, health=health)

    def cancel(self) -> str:
        if self.job_id is None:
            raise RuntimeError("job not submitted")
        return self._request({"action": "cancel", "job_id": self.job_id})["state"]

    def wait(self, timeout: Optional[float] = None, poll_interval: float = 0.2) -> Dict[str, Any]:
        if self.job_id is None:
            raise RuntimeError("job not submitted")
        deadline = None if timeout is None else time.time() + timeout
        # one authenticated connection for the whole poll loop — not a fresh
        # TCP+HMAC handshake per 0.2s status check
        with self._connect() as conn:
            while True:
                st = conn.request({"action": "status", "job_id": self.job_id})
                if st["state"] in (DONE, FAILED, CANCELLED):
                    return st
                if deadline is not None and time.time() > deadline:
                    raise TimeoutError(f"job {self.job_id} still {st['state']} after {timeout}s")
                time.sleep(poll_interval)

    def fetch_models(self) -> List[Any]:
        from distkeras_tpu.models.base import Model

        if self.job_id is None:
            raise RuntimeError("job not submitted")
        with self._connect() as conn:
            resp = conn.request({"action": "fetch", "job_id": self.job_id})
            blobs = [net.recv_frame(conn.sock) for _ in range(resp["num_models"])]
        return [Model.deserialize(b) for b in blobs]

    def run(self, timeout: Optional[float] = None):
        """submit + wait + fetch; returns the trained Model (or list for
        ensemble trainers).  Raises on job failure (reference ``Job.run``)."""
        self.submit()
        st = self.wait(timeout=timeout)
        if st["state"] != DONE:
            raise RuntimeError(f"job {self.job_id} {st['state']}: {st.get('error')}")
        models = self.fetch_models()
        return models if len(models) > 1 else models[0]


def list_jobs(host: str, port: int, secret: str) -> List[Dict[str, Any]]:
    """List all jobs known to a Punchcard daemon."""
    with _Conn(host, port, secret) as conn:
        return conn.request({"action": "list"})["jobs"]


def fetch_telemetry(host: str, port: int, secret: str,
                    trace: bool = False,
                    prometheus: bool = False,
                    fleet: bool = False,
                    health: bool = False) -> Dict[str, Any]:
    """Pull the daemon process's telemetry (authenticated): the metrics
    snapshot, plus the span ring as Chrome ``trace_event`` JSON when
    ``trace=True``, the Prometheus text exposition when
    ``prometheus=True``, the distributed-tracing
    :func:`~distkeras_tpu.observability.distributed.fleet_report`
    (straggler ranking, per-worker staleness attribution, reconnect
    storms) when ``fleet=True``, and the LIVE fleet health view
    (per-worker sliding-window series + ringed ``HealthEvent``s from the
    daemon process's collector/monitor — what ``distkeras-top`` renders)
    when ``health=True``.  Works mid-job — this is how a running job's
    counters/staleness/window histograms are read remotely."""
    with _Conn(host, port, secret) as conn:
        return conn.request({"action": "telemetry", "trace": bool(trace),
                             "prometheus": bool(prometheus),
                             "fleet": bool(fleet),
                             "health": bool(health)})


def shutdown(host: str, port: int, secret: str) -> None:
    """Remotely stop a Punchcard daemon (authenticated)."""
    with _Conn(host, port, secret) as conn:
        conn.request({"action": "shutdown"})


def main(argv: Optional[List[str]] = None) -> None:
    """Daemon CLI: ``distkeras-punchcard --secret-file s.txt --port 5000``."""
    import argparse

    parser = argparse.ArgumentParser(description="dist-keras-tpu job daemon")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=5000)
    parser.add_argument("--secret-file", required=True,
                        help="file whose (stripped) contents are the shared secret")
    parser.add_argument("--data-root", default=None,
                        help="directory server-side dataset paths are confined to")
    parser.add_argument("--state-dir", default=None,
                        help="spool job records/models here so the queue and "
                             "fetchable results survive a restart (default: "
                             "<data-root>/.punchcard-state when --data-root is set)")
    parser.add_argument("--max-retained", type=int, default=20,
                        help="terminal jobs kept (records + model blobs); older evicted")
    args = parser.parse_args(argv)
    with open(args.secret_file) as f:
        secret = f.read().strip()
    pc = Punchcard(secret=secret, host=args.host, port=args.port,
                   data_root=args.data_root, state_dir=args.state_dir,
                   max_retained=args.max_retained).start()
    print(f"punchcard listening on {args.host}:{pc.port}", flush=True)
    try:
        while True:
            time.sleep(1)
            if not pc._running:
                return
    except KeyboardInterrupt:
        pc.stop()


if __name__ == "__main__":
    main()
