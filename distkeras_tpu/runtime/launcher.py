"""Multi-host launch helpers — the Spark-cluster replacement (SURVEY §2.14).

The reference scaled out by letting Spark place one worker per executor
and pointing them all at the driver's TCP parameter server.  This module
provides the two TPU-native equivalents:

1. **SPMD multi-host** (sync mesh trainers): every host runs the SAME
   program; :func:`initialize_multihost` wires the hosts into one JAX
   runtime (coordinator handshake, Gloo/ICI collectives), after which
   ``jax.devices()`` is the global device list and the existing mesh
   trainers work unchanged — collectives ride ICI within a slice and DCN
   across hosts.  The WindowEngine feeds the mesh with
   ``make_array_from_process_local_data`` (each process contributes the
   batch columns its devices own, preserving exact single-process
   replica-to-rows parity — proven by ``tests/test_multihost.py ::
   test_two_process_engine_adag_matches_single_process``).
   :func:`process_shard` gives each host its row-slice of a dataset (the
   reference's ``df.repartition(num_workers)``) for data planes that
   cannot hold the full set per host — e.g. async PS workers.

2. **PS multi-host** (async family): :func:`start_parameter_server` runs
   the hub standalone (CLI: ``distkeras-ps``) on a head node; worker hosts
   run Async* trainers with ``ps_address=(head, port)`` — one process per
   host, the reference's actual topology with sockets replacing Spark.

Both paths are exercised by ``tests/test_multihost.py`` with real separate
processes on CPU (2 processes x 2 virtual devices), the CI stand-in for
2 TPU hosts.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import numpy as np


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         cpu_devices_per_process: Optional[int] = None) -> None:
    """Join this process into a multi-host JAX runtime.

    Thin, env-var-aware wrapper over ``jax.distributed.initialize``:
    arguments fall back to ``DKT_COORDINATOR`` / ``DKT_NUM_PROCESSES`` /
    ``DKT_PROCESS_ID``, and on real TPU pods everything may be ``None``
    (JAX auto-discovers from the TPU metadata).

    ``cpu_devices_per_process`` simulates a multi-host slice on CPU: it
    pins the CPU platform with that many virtual devices BEFORE the
    coordinator handshake (the 2-hosts-in-CI shape; cross-process
    collectives run over Gloo).  Must be called before any backend use.
    """
    import jax

    if cpu_devices_per_process is not None:
        # jax_num_cpu_devices wins over any inherited XLA_FLAGS device-count
        # (pin_cpu_devices' fallback path, made the primary here)
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", int(cpu_devices_per_process))

    coordinator_address = coordinator_address or os.environ.get("DKT_COORDINATOR")
    if num_processes is None and "DKT_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DKT_NUM_PROCESSES"])
    if process_id is None and "DKT_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DKT_PROCESS_ID"])

    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    if cpu_devices_per_process is not None:
        local = len(jax.local_devices())
        if local != cpu_devices_per_process:
            raise RuntimeError(
                f"requested {cpu_devices_per_process} local CPU devices, got {local} "
                f"(a backend may have initialized before initialize_multihost)")


def process_shard(dataset: Any) -> Any:
    """This host's contiguous shard of the dataset — the multi-host data
    plane (reference: Spark repartition handing each worker one partition).
    Identity when running single-process.

    NOTE: the sync WindowEngine does NOT need pre-sharded data — it takes
    the global chunk on every host and slices each process's batch columns
    internally (exact single-process parity).  Use this for async PS
    workers or memory-bound hosts that must not load the full dataset."""
    import jax

    n, i = jax.process_count(), jax.process_index()
    return dataset if n == 1 else dataset.shard(n, i)


def start_parameter_server(model: Any, mode: str = "delta", num_workers: int = 1,
                           host: str = "0.0.0.0", port: int = 0,
                           native: bool = False,
                           elastic: bool = False,
                           idle_timeout: Optional[float] = 300.0,
                           snapshot_dir: Optional[str] = None,
                           snapshot_interval: float = 30.0,
                           restore: bool = False,
                           num_shards: int = 1,
                           shard_index: Optional[int] = None,
                           replica_of: Optional[Any] = None,
                           health_jsonl: Optional[str] = None,
                           sparse_tables: Optional[Any] = None,
                           adaptive: bool = False,
                           shm_dir: Optional[str] = None,
                           recv_batch_depth: int = 0) -> Any:
    """Start a standalone PS hub serving ``model``'s weights (head-node side
    of the async multi-host topology).  Returns the started server; read
    ``.port``, stop with ``.stop()``, final weights via ``.get_weights()``.

    ``mode``: ``delta`` (DOWNPOUR/elastic) | ``adag`` | ``dynsgd``.
    ``native=True`` uses the C++ hub (commits apply outside the GIL).

    Fault tolerance (both hubs): ``snapshot_dir`` makes the hub snapshot
    its center + commit clock every ``snapshot_interval`` seconds (atomic
    tmp+rename via the Checkpointer); ``restore=True`` resumes a restarted
    hub from the newest readable snapshot — with a clock fence that clamps
    pre-restart pull clocks — BEFORE serving, so workers reconnecting via
    backoff land on the recovered center.  ``idle_timeout`` evicts
    half-open connections; ``elastic`` (adag) normalizes commits by the
    live worker count instead of ``num_workers``.

    Sharded hub (``num_shards > 1``): the center is partitioned by the
    deterministic :func:`~distkeras_tpu.runtime.parameter_server.
    shard_plan` — the same plan the trainers derive from the same model,
    so no plan travels on the wire.  ``shard_index=i`` serves ONLY shard
    ``i``'s slice from this process (one ``distkeras-ps`` per shard, the
    scale-out topology); ``shard_index=None`` starts all shards in this
    process behind a :class:`~distkeras_tpu.runtime.parameter_server.
    ShardedParameterServer` facade (read ``.ports``).  When sharded,
    ``snapshot_dir`` gets a ``shard-NN`` subdirectory per shard; on the
    facade path the per-shard snapshots are COORDINATED — one commit
    barrier per set, restored only as a complete clock-consistent set
    (:class:`~distkeras_tpu.runtime.parameter_server.
    SnapshotSetCoordinator`) — while one-daemon-per-shard deployments
    keep independent per-shard snapshots (no cross-process barrier).

    High availability (``replica_of=(host, port)``): start this hub as a
    HOT STANDBY of the primary at that address — it serves pulls
    immediately, tracks the primary's applied commits over the
    replication feed (wire action ``R``), and promotes itself behind the
    clock fence when the primary dies.  Served by BOTH hubs (the C++
    standby runs its feed thread native-side); with
    ``num_shards > 1`` it requires ``shard_index`` (one standby daemon
    per shard primary, pointed at THAT shard's address).

    Live fleet health (ISSUE 8): a Python hub automatically folds worker
    health reports (wire action ``M``, sent by trainers with
    ``health_interval_s``) into this process's
    :mod:`~distkeras_tpu.observability.health` collector and runs the
    online detectors over them; ``health_jsonl`` additionally appends
    every :class:`HealthEvent` to that path as JSON lines (durable even
    if the process dies before anyone polls).

    Adaptive aggregation (ISSUE 10): ``adaptive=True`` makes the hub
    merge queued commits Adasum-style, scale each worker's commits by
    its live staleness standing (driven by the health plane's detector
    events), and answer adaptive clients' reconnect hellos with
    retry-after hints while a reconnect storm is live.  Served by BOTH
    hubs (the C++ hub runs the Adasum merger and backpressure natively);
    pair with trainers started with the matching ``adaptive=True``.

    Row-sparse embedding service (ISSUE 9): ``sparse_tables="auto"``
    registers the model's declared EmbeddingTable leaves
    (``sparse_param_names`` on the architecture) so workers started with
    the matching ``sparse_tables`` knob exchange only touched rows; an
    iterable names flat-leaf indices explicitly.  Both ends derive the
    same leaf set (and, sharded, the same row-range plan) from the same
    model — nothing travels on the wire.  Served by BOTH hubs.

    Zero-copy transport (ISSUE 18): ``shm_dir`` lets same-host workers
    that dialed with ``shm=True`` attach a pair of mmap-backed frame
    rings (wire action ``Z``) and move the SAME frame bytes without the
    kernel TCP stack; unset, every attach is declined and clients ride
    TCP unchanged.  ``recv_batch_depth=N`` drains up to N queued frames
    per receive-loop wakeup (recvmmsg where available).  Served by BOTH
    hubs (the C++ hub's wakeup loop already drains its buffer; the knob
    is accepted for parity).
    """
    from distkeras_tpu.runtime.parameter_server import (
        ShardedParameterServer, shard_plan)
    from distkeras_tpu.utils import flatten_weights

    flat, _ = flatten_weights(model.params)
    weights = [np.asarray(w, dtype=np.float32) for w in flat]
    num_shards = int(num_shards)
    if sparse_tables is None:
        sparse_idx: tuple = ()
    elif sparse_tables == "auto":
        from distkeras_tpu.models.base import sparse_leaf_indices

        sparse_idx = sparse_leaf_indices(model.spec, model.params)
        if not sparse_idx:
            raise ValueError(
                f"sparse_tables='auto' but architecture "
                f"{model.spec.name!r} declares no sparse embedding tables")
    else:
        sparse_idx = tuple(sorted({int(i) for i in sparse_tables}))
    if shard_index is not None and not (0 <= int(shard_index) < num_shards):
        raise ValueError(f"shard_index={shard_index} out of range for "
                         f"num_shards={num_shards}")
    if replica_of is not None:
        replica_of = (str(replica_of[0]), int(replica_of[1]))
        if num_shards > 1 and shard_index is None:
            raise ValueError("replica_of with num_shards > 1 requires "
                             "shard_index: run one standby daemon per "
                             "shard, each pointed at its own primary")

    def make_hub(hub_weights, shard_id, hub_port, own_snapshots=True,
                 hub_sparse=()):
        shard_snap = snapshot_dir if own_snapshots else None
        if shard_snap is not None and shard_id is not None:
            shard_snap = os.path.join(shard_snap, f"shard-{shard_id:02d}")
        common = dict(idle_timeout=idle_timeout, snapshot_dir=shard_snap,
                      snapshot_interval=snapshot_interval,
                      restore=restore if own_snapshots else False,
                      shard_id=shard_id, shm_dir=shm_dir,
                      recv_batch_depth=recv_batch_depth)
        if hub_sparse:
            common["sparse_leaves"] = hub_sparse
        if native:
            from distkeras_tpu.runtime.native import (
                MODE_ADAG, MODE_DELTA, MODE_DYNSGD, NativeParameterServer)

            native_mode = {"delta": MODE_DELTA, "adag": MODE_ADAG,
                           "dynsgd": MODE_DYNSGD}[mode]
            # the C++ hub binds all interfaces; host selection is
            # Python-hub only.  Sparse tables, adaptive aggregation and
            # hot-standby replication all run native-side (ISSUE 11)
            return NativeParameterServer(hub_weights, mode=native_mode,
                                         num_workers=num_workers,
                                         port=hub_port, elastic=elastic,
                                         replica_of=replica_of,
                                         adaptive=adaptive,
                                         **common)
        from distkeras_tpu.runtime.parameter_server import (
            ADAGParameterServer, DeltaParameterServer, DynSGDParameterServer)

        cls = {"delta": DeltaParameterServer, "adag": ADAGParameterServer,
               "dynsgd": DynSGDParameterServer}[mode]
        kwargs = ({"num_workers": num_workers, "elastic": elastic}
                  if mode == "adag" else {})
        return cls(hub_weights, host=host, port=hub_port,
                   replica_of=replica_of, adaptive=adaptive,
                   **kwargs, **common)

    if health_jsonl is not None:
        # arm the process monitor's durable sink BEFORE serving: the first
        # detector firing (possibly triggered by the very first worker
        # report) must already land on disk
        from distkeras_tpu.observability import health as _health

        _health.monitor().jsonl_path = str(health_jsonl)

    if num_shards == 1:
        ps = make_hub(weights, None, port, hub_sparse=sparse_idx)
    else:
        plan = shard_plan(weights, num_shards, sparse_leaves=sparse_idx)
        if shard_index is not None:
            sid = int(shard_index)
            # plan.split row-slices sparse tables; the pre-sparse
            # assignment indexing stays byte-identical when nothing is
            # sparse (split is then exactly the indexed selection)
            ps = make_hub(plan.split(weights)[sid],
                          sid, port, hub_sparse=plan.local_sparse(sid))
        else:
            # all shards in one process: consecutive ports from --port, or
            # all-ephemeral when port=0 (a fixed port can only bind once).
            # Durability lives in the facade's COORDINATED snapshot sets
            # (the per-hub dirs stay unset so the two mechanisms never
            # fight over the same shard-NN directories)
            ps = ShardedParameterServer(
                weights, plan,
                lambda w, sid: make_hub(w, sid, port + sid if port else 0,
                                        own_snapshots=False,
                                        hub_sparse=plan.local_sparse(sid)),
                snapshot_dir=snapshot_dir,
                snapshot_interval=snapshot_interval,
                restore=restore)
    ps.start()
    return ps


def main(argv: Optional[List[str]] = None) -> None:
    """``distkeras-ps``: serve a standalone PS hub for async multi-host runs.

    The model file is the no-pickle ``Model.serialize()`` blob:
    ``open(path, 'wb').write(Model.init(spec).serialize())``.
    """
    import argparse
    import threading

    parser = argparse.ArgumentParser(description="dist-keras-tpu parameter-server daemon")
    parser.add_argument("--model", required=True, help="serialized Model file")
    parser.add_argument("--mode", default="delta", choices=["delta", "adag", "dynsgd"])
    parser.add_argument("--num-workers", type=int, default=1,
                        help="expected worker count (adag normalization)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=5000)
    parser.add_argument("--native", action="store_true", help="use the C++ hub")
    parser.add_argument("--save-final", default=None,
                        help="on shutdown, write the final center model here")
    parser.add_argument("--snapshot-dir", default=None,
                        help="periodically snapshot center+clock here (atomic; "
                             "survives SIGKILL)")
    parser.add_argument("--snapshot-interval", type=float, default=30.0,
                        help="seconds between hub snapshots")
    parser.add_argument("--restore", action="store_true",
                        help="resume from the newest readable snapshot in "
                             "--snapshot-dir before serving (clock-fenced)")
    parser.add_argument("--idle-timeout", type=float, default=300.0,
                        help="evict connections silent for this many seconds "
                             "(half-open liveness); <= 0 disables")
    parser.add_argument("--elastic", action="store_true",
                        help="adag: normalize commits by the LIVE worker "
                             "count instead of --num-workers")
    parser.add_argument("--num-shards", type=int, default=1,
                        help="partition the center across this many hub "
                             "shards (deterministic shard_plan; trainers "
                             "pass the same num_shards)")
    parser.add_argument("--shard-index", type=int, default=None,
                        help="serve ONLY this shard from this process (one "
                             "distkeras-ps per shard); omit to serve every "
                             "shard from one process")
    parser.add_argument("--health-jsonl", default=None, metavar="PATH",
                        help="append every fleet HealthEvent (straggler, "
                             "staleness spike, reconnect/failover storm, "
                             "replication lag, throughput regression) to "
                             "this file as JSON lines; live view: "
                             "distkeras-top against a punchcard daemon")
    parser.add_argument("--sparse-tables", default=None, metavar="SPEC",
                        help="row-sparse embedding service (both hubs): "
                             "'auto' registers the model's declared "
                             "EmbeddingTable leaves, or a comma-separated "
                             "list of flat-leaf indices; workers started "
                             "with the matching sparse_tables knob then "
                             "exchange only the rows each batch touches")
    parser.add_argument("--shm-dir", default=None, metavar="DIR",
                        help="serve shared-memory frame-ring attaches (wire "
                             "action Z) to same-host clients dialed with "
                             "shm=True, creating ring files under DIR "
                             "(ideally tmpfs, e.g. /dev/shm); omit to "
                             "decline every attach (clients ride TCP "
                             "unchanged)")
    parser.add_argument("--recv-batch-depth", type=int, default=0,
                        help="drain up to N queued frames per receive-loop "
                             "wakeup (recvmmsg where available); 0 = one "
                             "recv per frame, today's loop")
    parser.add_argument("--adaptive", action="store_true",
                        help="telemetry-driven adaptive aggregation (both "
                             "hubs): merge queued commits "
                             "Adasum-style, scale each worker's commits "
                             "by its live staleness standing, and shed "
                             "reconnect storms with retry-after hints "
                             "(pair with trainers started adaptive=True)")
    parser.add_argument("--replica-of", default=None, metavar="HOST:PORT",
                        help="start as a hot standby of the primary hub at "
                             "this address: serve pulls immediately, stream "
                             "its applied commits, promote on its death "
                             "(both hubs; sharded: one standby daemon "
                             "per shard, paired with --shard-index)")
    parser.add_argument("--autoscale", action="store_true",
                        help="run an ADVISORY FleetController on this hub's "
                             "health monitor: spawn/retire/respawn "
                             "decisions are recorded and counted "
                             "(ps_fleet_* telemetry, printed at shutdown) "
                             "for an operator or supervisor to act on — "
                             "the daemon itself starts no workers")
    args = parser.parse_args(argv)
    if args.restore and not args.snapshot_dir:
        parser.error("--restore requires --snapshot-dir")
    if args.shard_index is not None and args.num_shards <= 1:
        parser.error("--shard-index requires --num-shards > 1")
    if args.save_final and args.shard_index is not None:
        parser.error("--save-final needs the full center; a single-shard "
                     "process only holds its slice")
    replica_of = None
    if args.replica_of:
        if args.num_shards > 1 and args.shard_index is None:
            parser.error("--replica-of with --num-shards > 1 requires "
                         "--shard-index (one standby daemon per shard)")
        host_part, _, port_part = args.replica_of.rpartition(":")
        if not host_part or not port_part.isdigit():
            parser.error(f"--replica-of expects HOST:PORT, got "
                         f"{args.replica_of!r}")
        replica_of = (host_part, int(port_part))
    sparse_tables: Optional[Any] = None
    if args.sparse_tables:
        if args.sparse_tables == "auto":
            sparse_tables = "auto"
        else:
            try:
                sparse_tables = tuple(
                    int(p) for p in args.sparse_tables.split(",") if p)
            except ValueError:
                parser.error(f"--sparse-tables expects 'auto' or a comma-"
                             f"separated index list, got "
                             f"{args.sparse_tables!r}")

    from distkeras_tpu.models.base import Model

    with open(args.model, "rb") as f:
        model = Model.deserialize(f.read())
    # graceful preemption drain (ISSUE 19): SIGTERM — the notice every
    # spot/preemptible scheduler sends ahead of the kill — exits the wait
    # loop below and runs the SAME shutdown as Ctrl-C.  Installed BEFORE
    # the hub starts (and before the "listening" banner): a supervisor
    # that SIGTERMs the moment the daemon reports ready must get the
    # drain, never the default-action kill
    import signal

    stop_event = threading.Event()

    def _on_sigterm(_signum, _frame):
        stop_event.set()

    prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    ps = start_parameter_server(model, mode=args.mode, num_workers=args.num_workers,
                                host=args.host, port=args.port, native=args.native,
                                elastic=args.elastic,
                                idle_timeout=(args.idle_timeout
                                              if args.idle_timeout > 0 else None),
                                snapshot_dir=args.snapshot_dir,
                                snapshot_interval=args.snapshot_interval,
                                restore=args.restore,
                                num_shards=args.num_shards,
                                shard_index=args.shard_index,
                                replica_of=replica_of,
                                health_jsonl=args.health_jsonl,
                                sparse_tables=sparse_tables,
                                adaptive=args.adaptive,
                                shm_dir=args.shm_dir,
                                recv_batch_depth=args.recv_batch_depth)
    if replica_of is not None:
        print(f"ps standby (replica of {replica_of[0]}:{replica_of[1]}) "
              f"listening on {args.host}:{ps.port}", flush=True)
    if args.num_shards > 1 and args.shard_index is None:
        for sid, p in enumerate(ps.ports):
            print(f"ps shard {sid}/{args.num_shards} listening on "
                  f"{args.host}:{p}", flush=True)
    elif args.shard_index is not None:
        print(f"ps shard {args.shard_index}/{args.num_shards} listening on "
              f"{args.host}:{ps.port}", flush=True)
    else:
        print(f"ps listening on {args.host}:{ps.port}", flush=True)
    controller = None
    if args.autoscale:
        from distkeras_tpu.observability import health as _health
        from distkeras_tpu.runtime.fleet_controller import FleetController

        controller = FleetController(_health.monitor())
    # the drain itself: ps.stop() takes a final snapshot, flushes and
    # severs the replication feed (a standby's stream ends with a clean
    # EOF, never a torn frame), shuts the listener down and severs worker
    # connections.  Workers reconnect to the standby/restart under their
    # own budgets; nothing acked is lost
    try:
        while not stop_event.wait(1.0):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, prev_handler)
        if stop_event.is_set():
            print("SIGTERM: draining hub (final snapshot, feed flush, "
                  "listener shutdown)", flush=True)
        if controller is not None:
            controller.stop()
            for d in controller.decisions():
                print(f"fleet decision: {d['action']} "
                      f"worker={d['worker']} reason={d['reason']}",
                      flush=True)
        ps.stop()
        # distributed tracing: the hub process is the merge's clock
        # REFERENCE (offset 0) — flush its spans (handler-side
        # ps.handle_commit/pull, snapshot, eviction; the C++ hub's drained
        # commit log lands here through stop()'s sync_telemetry) so
        # merge_traces(DKT_TRACE_DIR) can align every worker against it.
        # DKT_TELEMETRY=1 DKT_TRACE_DIR=... is the whole recipe
        trace_dir = os.environ.get("DKT_TRACE_DIR")
        if trace_dir:
            from distkeras_tpu import observability as obs

            if obs.enabled():
                from distkeras_tpu.observability.distributed import (
                    flush_process_trace,
                )

                try:
                    flush_process_trace(trace_dir, role="hub")
                except OSError as e:
                    print(f"trace flush failed: {e}", flush=True)
        if args.save_final:
            from distkeras_tpu.utils import flatten_weights, unflatten_weights

            _, treedef = flatten_weights(model.params)
            final = Model(spec=model.spec,
                          params=unflatten_weights(treedef, ps.get_weights()))
            with open(args.save_final, "wb") as f:
                f.write(final.serialize())
            print(f"final model written to {args.save_final}", flush=True)


if __name__ == "__main__":
    main()
