"""ctypes bindings for the C++ parameter-server hub (``native/ps_server.cpp``).

The shared library is built on demand with ``g++`` (no pybind11 in this
environment — plain ``extern "C"`` + ctypes) and cached next to this file;
rebuilds happen only when the source is newer than the binary.  If no
toolchain is available, callers fall back to the pure-Python hub — the two
implementations speak the same wire protocol, so
:class:`distkeras_tpu.runtime.parameter_server.PSClient` works against
either.

``NativeParameterServer`` mirrors the Python ``SocketParameterServer``
surface (``start``/``stop``/``get_weights``/``num_updates``/``port``) so
the async trainers can swap hubs with a constructor flag.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import distributed as dtrace

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native", "ps_server.cpp")
_LIB = os.path.join(_HERE, "_native_ps.so")

MODE_DELTA = 0   # center += d              (DOWNPOUR, elastic)
MODE_ADAG = 1    # center += d/num_workers  (ADAG)
MODE_DYNSGD = 2  # center += d/(staleness+1)



def build_shared(src: str, lib: str) -> Optional[str]:
    """Compile ``src`` to the shared library ``lib`` if missing/stale.
    Returns an error string on failure, None on success.  Shared by every
    native component (PS hub, data loader)."""
    if not os.path.exists(src):
        return f"native source not found: {src}"
    if os.path.exists(lib) and os.path.getmtime(lib) >= os.path.getmtime(src):
        return None
    # compile to a private temp path, then atomically rename into place:
    # a concurrent process either dlopens the complete old .so or the
    # complete new one, never a half-written file
    tmp = f"{lib}.build-{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17", src, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ invocation failed: {e}"
    if proc.returncode != 0:
        return f"g++ failed:\n{proc.stderr}"
    os.replace(tmp, lib)
    return None




class LazyNativeLib:
    """Build-once/load-once native library with cached failure — the shared
    state machine for every native component (PS hub, data loader, ...).

    ``bind(lib)`` is called exactly once after a successful dlopen to set
    restype/argtypes.  ``load()`` returns the CDLL or None; ``error()``
    returns the cached build failure, if any.
    """

    def __init__(self, src: str, lib_path: str, bind):
        self._src = src
        self._lib_path = lib_path
        self._bind = bind
        self._lock = threading.Lock()
        self._lib: Optional[ctypes.CDLL] = None
        self._error: Optional[str] = None

    def load(self) -> Optional[ctypes.CDLL]:
        with self._lock:
            if self._lib is not None:
                return self._lib
            if self._error is not None:
                return None
            err = build_shared(self._src, self._lib_path)
            if err is not None:
                self._error = err
                return None
            lib = ctypes.CDLL(self._lib_path)
            self._bind(lib)
            self._lib = lib
            return lib

    def error(self) -> Optional[str]:
        self.load()
        return self._error


def _bind_ps(lib: ctypes.CDLL) -> None:
    lib.dk_ps_create.restype = ctypes.c_void_p
    lib.dk_ps_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
                                 ctypes.c_int, ctypes.c_int]
    lib.dk_ps_restore.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
                                  ctypes.c_int64, ctypes.c_int64]
    lib.dk_ps_start.restype = ctypes.c_int
    lib.dk_ps_start.argtypes = [ctypes.c_void_p]
    lib.dk_ps_stop.argtypes = [ctypes.c_void_p]
    lib.dk_ps_get_weights.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
    lib.dk_ps_set_weights.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
    lib.dk_ps_num_updates.restype = ctypes.c_int64
    lib.dk_ps_num_updates.argtypes = [ctypes.c_void_p]
    lib.dk_ps_port.restype = ctypes.c_int
    lib.dk_ps_port.argtypes = [ctypes.c_void_p]
    lib.dk_ps_pull.restype = ctypes.c_int64
    lib.dk_ps_pull.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
    lib.dk_ps_snapshot.restype = ctypes.c_int64
    lib.dk_ps_snapshot.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
    lib.dk_ps_commit.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
                                 ctypes.c_int64]
    lib.dk_ps_commit_ctx.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_int64, ctypes.c_int64]
    lib.dk_ps_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.dk_ps_staleness_hist.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_int64)]
    lib.dk_ps_drain_commits.restype = ctypes.c_int64
    lib.dk_ps_drain_commits.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_int64),
                                        ctypes.c_int64]
    lib.dk_ps_time_ns.restype = ctypes.c_int64
    lib.dk_ps_time_ns.argtypes = [ctypes.c_void_p]
    lib.dk_ps_destroy.argtypes = [ctypes.c_void_p]


_ps_lib = LazyNativeLib(_SRC, _LIB, _bind_ps)


def _load() -> Optional[ctypes.CDLL]:
    return _ps_lib.load()


def native_available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    return _ps_lib.error()


class NativeParameterServer:
    """C++ PS hub with the Python hub's interface.  ``mode`` selects the
    commit-scaling rule (MODE_DELTA / MODE_ADAG / MODE_DYNSGD).

    Fault-tolerance surface matches the Python hub: ``idle_timeout``
    evicts half-open connections via ``SO_RCVTIMEO``; ``elastic=True``
    normalizes ADAG commits by the live committer count; ``snapshot_dir``
    attaches a :class:`~.parameter_server.HubSnapshotter` (periodic atomic
    center+clock snapshots) and ``restore=True`` reloads the newest one —
    with the clock fence armed in C++ — before serving."""

    def __init__(self, weights: Sequence[np.ndarray], mode: int = MODE_DELTA,
                 num_workers: int = 1, port: int = 0,
                 elastic: bool = False,
                 idle_timeout: Optional[float] = 300.0,
                 snapshot_dir: Optional[str] = None,
                 snapshot_interval: float = 30.0,
                 snapshot_keep: int = 3,
                 restore: bool = False,
                 shard_id: Optional[int] = None,
                 replica_of: Optional[tuple] = None,
                 adaptive: bool = False):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native PS unavailable: {build_error()}")
        if adaptive:
            # Documented Python-hub-only fallback (ISSUE 10): the adaptive
            # combiner, rate controller and backpressure all live in the
            # Python hub's commit/accept paths — the C++ hub applies
            # commits in C++ with no hook for any of them.
            raise NotImplementedError(
                "adaptive aggregation requires the Python hub; the C++ hub "
                "has no adaptive combiner or backpressure handlers — run "
                "SocketParameterServer / distkeras-ps without --native "
                "(identical wire protocol)")
        if replica_of is not None:
            # Documented Python-hub-only fallback (ISSUE 7): the C++ hub's
            # commit log (dk_ps_drain_commits) records clocks and timings
            # but not delta payloads, so a faithful applied-commit stream
            # cannot be rebuilt from it.  HA deployments run the Python
            # hub — same wire protocol, so clients are unaffected.
            raise NotImplementedError(
                "hot-standby replication (replica_of) requires the Python "
                "hub; the C++ hub has no replication feed — run "
                "SocketParameterServer / distkeras-ps without --native for "
                "the replica and primary (identical wire protocol)")
        self._lib = lib
        self._templates = [np.array(w, dtype=np.float32) for w in weights]
        sizes = (ctypes.c_int64 * len(self._templates))(*[t.size for t in self._templates])
        idle_ms = 0 if idle_timeout is None else max(1, int(idle_timeout * 1000))
        self._handle = lib.dk_ps_create(int(port), len(self._templates), sizes,
                                        int(mode), int(num_workers),
                                        1 if elastic else 0, idle_ms)
        if not self._handle:
            raise RuntimeError("dk_ps_create failed")
        flat = np.concatenate([t.reshape(-1) for t in self._templates]) if self._templates \
            else np.zeros(0, np.float32)
        self._total = int(flat.size)
        lib.dk_ps_set_weights(self._handle, flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        self.port = -1
        self._started = False
        # telemetry bridge state: last-seen cumulative stats/histogram so
        # sync_telemetry() can inc() registry counters by DELTAS only
        self._stats_lock = threading.Lock()
        self._last_stats = [0] * 9
        self._last_stale_hist = [0] * 65
        self._drain_buf = np.zeros(4096 * 5, np.int64)
        # sharded-hub identity: mirrors the Python hub — when serving one
        # shard of a partitioned center, every synced metric/span carries
        # the shard label (None = the exact pre-sharding series)
        self.shard_id = None if shard_id is None else int(shard_id)
        self._mlabels = ({} if shard_id is None
                         else {"shard": str(int(shard_id))})
        self._restore = bool(restore)
        self.snapshotter = None
        if restore and snapshot_dir is None:
            raise ValueError("restore=True requires snapshot_dir")
        if snapshot_dir is not None:
            from distkeras_tpu.runtime.parameter_server import HubSnapshotter

            self.snapshotter = HubSnapshotter(self, snapshot_dir,
                                              interval=snapshot_interval,
                                              keep=snapshot_keep)

    def start(self) -> None:
        if self._restore and self.snapshotter is not None:
            # same contract as the Python hub: unreadable-but-present
            # snapshots are fatal (don't silently discard a job's
            # progress); a genuinely empty dir is a first boot
            if not self.snapshotter.restore_latest():
                if self.snapshotter.checkpointer.all_steps():
                    raise RuntimeError(
                        f"restore requested: snapshots exist in "
                        f"{self.snapshotter.checkpointer.directory} but none "
                        f"is readable (see warnings)")
                import warnings

                warnings.warn("restore requested but no snapshot exists "
                              "yet; serving initial weights")
        port = self._lib.dk_ps_start(self._handle)
        if port < 0:
            raise RuntimeError("native PS failed to bind")
        self.port = port
        self._started = True
        if self.snapshotter is not None:
            self.snapshotter.start()

    def stop(self) -> None:
        self._shutdown(final_snapshot=True)

    def kill(self) -> None:
        """Crash-like teardown (no final snapshot) — the C++ twin of
        ``SocketParameterServer.kill``."""
        self._shutdown(final_snapshot=False)

    def _shutdown(self, final_snapshot: bool) -> None:
        if self._started:
            if self.snapshotter is not None:
                self.snapshotter.stop(final_snapshot=final_snapshot)
            # surface the C++ hub's final counters/commit log into the
            # registry/tracer before the serving threads go away
            try:
                self.sync_telemetry()
            except Exception:
                pass  # telemetry must never block a teardown
            self._lib.dk_ps_stop(self._handle)
            self._started = False

    # -- telemetry bridge (dk_ps_stats and friends) ----------------------------
    def _shard_attrs(self) -> Dict[str, int]:
        return {} if self.shard_id is None else {"shard": self.shard_id}

    _STAT_KEYS = ("commits", "pulls", "commit_bytes", "pull_bytes",
                  "fenced_commits", "live_workers", "idle_evictions", "clock",
                  "commit_log_dropped")

    def stats(self) -> Dict[str, int]:
        """The C++ hub's cumulative counters, by name (see ``dk_ps_stats``
        in ``native/ps_server.cpp``)."""
        out = (ctypes.c_int64 * 9)()
        self._lib.dk_ps_stats(self._handle, out)
        return dict(zip(self._STAT_KEYS, [int(v) for v in out]))

    def time_ns(self) -> int:
        """The hub's CLOCK_MONOTONIC in ns — the same epoch Python's
        ``time.perf_counter_ns`` reads on Linux (offset sanity checks)."""
        return int(self._lib.dk_ps_time_ns(self._handle))

    def sync_telemetry(self) -> None:
        """Drain the C++ hub's telemetry into the process registry/tracer
        under the SAME names the Python hub emits (``ps_commits_total``,
        ``ps_commit_staleness``, ...), so Prometheus/punchcard output is
        hub-implementation-agnostic.  Counters advance by deltas against
        the last sync; the commit log becomes ``ps.handle_commit`` spans
        (worker attribution from the wire ``T`` announce or
        ``commit_direct``'s caller context).  Called automatically at
        shutdown and on every hub snapshot; call it directly for an
        up-to-the-moment mid-run view."""
        if not obs.enabled():
            return
        with self._stats_lock:
            stats = self.stats()
            vals = [stats[k] for k in self._STAT_KEYS]
            delta = {k: v - last for k, v, last
                     in zip(self._STAT_KEYS, vals, self._last_stats)}
            self._last_stats = vals
            for key, name in (("commits", "ps_commits_total"),
                              ("pulls", "ps_pulls_total"),
                              ("commit_bytes", "ps_commit_bytes_total"),
                              ("pull_bytes", "ps_pull_bytes_total"),
                              ("fenced_commits", "ps_fenced_commits_total"),
                              ("idle_evictions", "ps_idle_evictions_total"),
                              # commit-log ring wraps between drains lose
                              # per-commit spans; the loss must be VISIBLE
                              # (same contract as SpanTracer.dropped)
                              ("commit_log_dropped",
                               "ps_commit_log_dropped_total")):
                if delta[key] > 0:
                    obs.counter(name, **self._mlabels).inc(delta[key])
            obs.gauge("ps_live_workers",
                      **self._mlabels).set(stats["live_workers"])
            # exact small-integer staleness counts -> the shared log-bucket
            # histogram (value == slot; the overflow slot observes as its
            # lower bound, a documented approximation)
            hist = (ctypes.c_int64 * 65)()
            self._lib.dk_ps_staleness_hist(self._handle, hist)
            stale = obs.histogram("ps_commit_staleness", **self._mlabels)
            for slot in range(65):
                # bulk replay: O(65) per sync regardless of commit count
                stale.observe_n(slot, int(hist[slot]) - self._last_stale_hist[slot])
                self._last_stale_hist[slot] = int(hist[slot])
            # commit log -> hub-side spans on a dedicated "native-hub"
            # track (timestamps are CLOCK_MONOTONIC ns — the tracer's own
            # epoch, so no conversion)
            while True:
                n = int(self._lib.dk_ps_drain_commits(
                    self._handle,
                    self._drain_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    4096))
                for i in range(n):
                    clock, worker, staleness, t_ns, dur_ns = \
                        (int(v) for v in self._drain_buf[i * 5:i * 5 + 5])
                    attrs = {"staleness": staleness, "clock": clock,
                             "hub": "native", **self._shard_attrs()}
                    if worker >= 0:
                        attrs["worker"] = worker
                    obs.TRACER.record_span("ps.handle_commit", t_ns,
                                           t_ns + dur_ns, tid="native-hub",
                                           **attrs)
                if n < 4096:
                    break

    # -- durability (HubSnapshotter surface) -----------------------------------
    def snapshot_state(self):
        """(center tensors, JSON-typed state dict) — one atomic view via the
        C++ snapshot path (center + clock under the hub mutex; NOT counted
        as a pull — the Python hub's snapshot_state is uncounted too).
        Piggybacks a telemetry sync: a snapshotting hub surfaces its C++
        counters into the registry at least once per snapshot interval, so
        mid-run punchcard pulls see fresh native-hub numbers."""
        try:
            self.sync_telemetry()
        except Exception:
            pass
        flat = np.empty(self._total, np.float32)
        clock = int(self._lib.dk_ps_snapshot(
            self._handle, flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float))))
        center, off = [], 0
        for t in self._templates:
            center.append(flat[off:off + t.size].reshape(t.shape).copy())
            off += t.size
        return (center,
                {"clock": clock, "num_updates": int(self.num_updates)})

    def restore_state(self, center: Sequence[np.ndarray], state) -> None:
        if len(center) != len(self._templates):
            raise ValueError(f"snapshot has {len(center)} tensors, center has "
                             f"{len(self._templates)}")
        parts = [np.ascontiguousarray(c, np.float32).reshape(-1) for c in center]
        flat = np.concatenate(parts) if parts else np.zeros(0, np.float32)
        if flat.size != self._total:
            raise ValueError(f"snapshot has {flat.size} values, center has "
                             f"{self._total}")
        self._lib.dk_ps_restore(
            self._handle, flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            int(state.get("clock", 0)), int(state.get("num_updates", 0)))

    def get_weights(self) -> List[np.ndarray]:
        out = np.zeros(self._total, np.float32)
        self._lib.dk_ps_get_weights(self._handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        result = []
        off = 0
        for t in self._templates:
            result.append(out[off:off + t.size].reshape(t.shape).copy())
            off += t.size
        return result

    # -- in-process transport (transport="inproc") -----------------------------
    # Mirrors SocketParameterServer.pull_direct/commit_direct: co-located
    # workers exchange with the C++ center through two ctypes calls (both
    # release the GIL for the memcpy/apply), no sockets, no framing.

    def pull_direct(self):
        """(center tensors, clock at snapshot) — the clock rides back in
        with the matching :meth:`commit_direct`."""
        flat = np.empty(self._total, np.float32)
        clock = int(self._lib.dk_ps_pull(
            self._handle, flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float))))
        out, off = [], 0
        for t in self._templates:
            out.append(flat[off:off + t.size].reshape(t.shape))
            off += t.size
        return out, clock

    def commit_direct(self, delta: Sequence[np.ndarray], last_pull_clock: int) -> None:
        if len(delta) != len(self._templates):
            raise ValueError(f"commit has {len(delta)} tensors, center has "
                             f"{len(self._templates)}")
        parts = []
        for d, t in zip(delta, self._templates):
            a = np.ascontiguousarray(d, dtype=np.float32).reshape(-1)
            if a.size != t.size:
                raise ValueError(f"commit tensor size {a.size} != center "
                                 f"size {t.size}")
            parts.append(a)
        flat = np.concatenate(parts) if parts else np.zeros(0, np.float32)
        # attribute the commit to the calling worker thread's trace
        # context (inproc workers have no connection to announce T on);
        # -1 = uncontexted, matching the wire default
        ctx = dtrace.current()
        worker = int(ctx.worker_id) if ctx is not None else -1
        self._lib.dk_ps_commit_ctx(
            self._handle, flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            int(last_pull_clock), worker)

    @property
    def num_updates(self) -> int:
        return int(self._lib.dk_ps_num_updates(self._handle))

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                if self._started:
                    self._lib.dk_ps_stop(self._handle)
                self._lib.dk_ps_destroy(self._handle)
                self._handle = None
        except Exception:
            pass
