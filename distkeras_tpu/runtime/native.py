"""ctypes bindings for the C++ parameter-server hub (``native/ps_server.cpp``).

The shared library is built on demand with ``g++`` (no pybind11 in this
environment — plain ``extern "C"`` + ctypes) and cached next to this file;
rebuilds happen only when the source is newer than the binary.  If no
toolchain is available, callers fall back to the pure-Python hub — the two
implementations speak the same wire protocol, so
:class:`distkeras_tpu.runtime.parameter_server.PSClient` works against
either.

``NativeParameterServer`` mirrors the Python ``SocketParameterServer``
surface at FEATURE PARITY (ISSUE 11): row-sparse embedding traffic
(actions ``S``/``V``/``U``/``X``), Adasum flat-combining adaptive
aggregation (``adaptive=True`` — per-worker rates still driven by the
Python :class:`~.parameter_server.AdaptiveRateController`, whose verdicts
are pushed into the C++ apply path), hot-standby replication on BOTH
sides (the ``R`` feed as primary, ``replica_of=`` as standby), reconnect
backpressure (``G``/``Y``) and health-report ingestion (``M``, drained
into the process HealthCollector by a poll thread).  The Python hub stays
the executable spec via the bit-parity matrices in ``tests/``.

The ONE remaining Python-hub-only surface is the row-sparse INPROC pair
(``pull_sparse_direct``/``commit_sparse_direct``) — see those methods.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import distributed as dtrace
from distkeras_tpu.runtime import networking as net

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native", "ps_server.cpp")
_LIB = os.path.join(_HERE, "_native_ps.so")

MODE_DELTA = 0   # center += d              (DOWNPOUR, elastic)
MODE_ADAG = 1    # center += d/num_workers  (ADAG)
MODE_DYNSGD = 2  # center += d/(staleness+1)

# build flags shared by every native component.  -ffp-contract=off pins
# the apply arithmetic to separate multiply-then-add (no FMA fusion), the
# exact float32 sequence numpy performs — the cross-hub bit-parity pins
# depend on it
BUILD_FLAGS = ["-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
               "-ffp-contract=off"]


def build_shared(src: str, lib: str) -> Optional[str]:
    """Compile ``src`` to the shared library ``lib`` if missing/stale.
    Returns an error string on failure, None on success.  Shared by every
    native component (PS hub, data loader)."""
    if not os.path.exists(src):
        return f"native source not found: {src}"
    if os.path.exists(lib) and os.path.getmtime(lib) >= os.path.getmtime(src):
        return None
    # compile to a private temp path, then atomically rename into place:
    # a concurrent process either dlopens the complete old .so or the
    # complete new one, never a half-written file
    tmp = f"{lib}.build-{os.getpid()}"
    cmd = ["g++"] + BUILD_FLAGS + [src, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ invocation failed: {e}"
    if proc.returncode != 0:
        return f"g++ failed:\n{proc.stderr}"
    os.replace(tmp, lib)
    return None


class LazyNativeLib:
    """Build-once/load-once native library with cached failure — the shared
    state machine for every native component (PS hub, data loader, ...).

    ``bind(lib)`` is called exactly once after a successful dlopen to set
    restype/argtypes.  ``load()`` returns the CDLL or None; ``error()``
    returns the cached build failure, if any.
    """

    def __init__(self, src: str, lib_path: str, bind):
        self._src = src
        self._lib_path = lib_path
        self._bind = bind
        self._lock = threading.Lock()
        self._lib: Optional[ctypes.CDLL] = None
        self._error: Optional[str] = None

    def load(self) -> Optional[ctypes.CDLL]:
        with self._lock:
            if self._lib is not None:
                return self._lib
            if self._error is not None:
                return None
            err = build_shared(self._src, self._lib_path)
            if err is not None:
                self._error = err
                return None
            lib = ctypes.CDLL(self._lib_path)
            self._bind(lib)
            self._lib = lib
            return lib

    def error(self) -> Optional[str]:
        self.load()
        return self._error


def _bind_ps(lib: ctypes.CDLL) -> None:
    P = ctypes.POINTER
    lib.dk_ps_create.restype = ctypes.c_void_p
    lib.dk_ps_create.argtypes = [
        ctypes.c_int, ctypes.c_int, P(ctypes.c_int64), ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, P(ctypes.c_int32), P(ctypes.c_int64), ctypes.c_int,
        ctypes.c_int64]
    lib.dk_ps_set_replica_of.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_int, ctypes.c_int,
                                         ctypes.c_int]
    lib.dk_ps_restore.argtypes = [ctypes.c_void_p, P(ctypes.c_float),
                                  ctypes.c_int64, ctypes.c_int64]
    lib.dk_ps_start.restype = ctypes.c_int
    lib.dk_ps_start.argtypes = [ctypes.c_void_p]
    lib.dk_ps_stop.argtypes = [ctypes.c_void_p]
    lib.dk_ps_get_weights.argtypes = [ctypes.c_void_p, P(ctypes.c_float)]
    lib.dk_ps_set_weights.argtypes = [ctypes.c_void_p, P(ctypes.c_float)]
    lib.dk_ps_num_updates.restype = ctypes.c_int64
    lib.dk_ps_num_updates.argtypes = [ctypes.c_void_p]
    lib.dk_ps_port.restype = ctypes.c_int
    lib.dk_ps_port.argtypes = [ctypes.c_void_p]
    lib.dk_ps_pull.restype = ctypes.c_int64
    lib.dk_ps_pull.argtypes = [ctypes.c_void_p, P(ctypes.c_float)]
    lib.dk_ps_snapshot.restype = ctypes.c_int64
    lib.dk_ps_snapshot.argtypes = [ctypes.c_void_p, P(ctypes.c_float)]
    lib.dk_ps_commit.restype = ctypes.c_int
    lib.dk_ps_commit.argtypes = [ctypes.c_void_p, P(ctypes.c_float),
                                 ctypes.c_int64]
    lib.dk_ps_commit_ctx.restype = ctypes.c_int
    lib.dk_ps_commit_ctx.argtypes = [ctypes.c_void_p, P(ctypes.c_float),
                                     ctypes.c_int64, ctypes.c_int64]
    lib.dk_ps_pull_sparse.restype = ctypes.c_int64
    lib.dk_ps_pull_sparse.argtypes = [ctypes.c_void_p, P(ctypes.c_int64),
                                      P(ctypes.c_int64), P(ctypes.c_float)]
    lib.dk_ps_commit_sparse.restype = ctypes.c_int
    lib.dk_ps_commit_sparse.argtypes = [ctypes.c_void_p, P(ctypes.c_float),
                                        P(ctypes.c_int64), P(ctypes.c_int64),
                                        ctypes.c_int64, ctypes.c_int64]
    lib.dk_ps_hot_rows.argtypes = [ctypes.c_void_p, P(ctypes.c_int64)]
    lib.dk_ps_stats.argtypes = [ctypes.c_void_p, P(ctypes.c_int64)]
    lib.dk_ps_staleness_hist.argtypes = [ctypes.c_void_p, P(ctypes.c_int64)]
    lib.dk_ps_merge_hist.argtypes = [ctypes.c_void_p, P(ctypes.c_int64)]
    lib.dk_ps_drain_commits.restype = ctypes.c_int64
    lib.dk_ps_drain_commits.argtypes = [ctypes.c_void_p, P(ctypes.c_int64),
                                        ctypes.c_int64]
    lib.dk_ps_next_health.restype = ctypes.c_int64
    lib.dk_ps_next_health.argtypes = [ctypes.c_void_p, P(ctypes.c_uint8),
                                      ctypes.c_int64]
    lib.dk_ps_set_rate_scale.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_double, ctypes.c_int64]
    lib.dk_ps_set_storm_params.argtypes = [ctypes.c_void_p] + [ctypes.c_int] * 5
    lib.dk_ps_arm_storm.argtypes = [ctypes.c_void_p]
    lib.dk_ps_is_standby.restype = ctypes.c_int
    lib.dk_ps_is_standby.argtypes = [ctypes.c_void_p]
    lib.dk_ps_promoted.restype = ctypes.c_int
    lib.dk_ps_promoted.argtypes = [ctypes.c_void_p]
    lib.dk_ps_promoted_at_clock.restype = ctypes.c_int64
    lib.dk_ps_promoted_at_clock.argtypes = [ctypes.c_void_p]
    lib.dk_ps_promote.restype = ctypes.c_int
    lib.dk_ps_promote.argtypes = [ctypes.c_void_p]
    lib.dk_ps_wait_synced.restype = ctypes.c_int
    lib.dk_ps_wait_synced.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.dk_ps_time_ns.restype = ctypes.c_int64
    lib.dk_ps_time_ns.argtypes = [ctypes.c_void_p]
    lib.dk_ps_destroy.argtypes = [ctypes.c_void_p]
    # shm transport (ISSUE 18): hub-side attach enable + standalone ring
    # handles (the cross-language layout pin drives these directly)
    lib.dk_ps_shm_attach.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dk_shm_ring_create.restype = ctypes.c_void_p
    lib.dk_shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_uint64]
    lib.dk_shm_ring_open.restype = ctypes.c_void_p
    lib.dk_shm_ring_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.dk_shm_ring_write.restype = ctypes.c_longlong
    lib.dk_shm_ring_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_longlong, ctypes.c_int]
    lib.dk_shm_ring_read.restype = ctypes.c_longlong
    lib.dk_shm_ring_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_longlong, ctypes.c_int]
    lib.dk_shm_ring_close.argtypes = [ctypes.c_void_p]
    lib.dk_shm_ring_destroy.argtypes = [ctypes.c_void_p]


_ps_lib = LazyNativeLib(_SRC, _LIB, _bind_ps)


def _load() -> Optional[ctypes.CDLL]:
    return _ps_lib.load()


def native_available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    return _ps_lib.error()


def _f32p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class NativeParameterServer:
    """C++ PS hub with the Python hub's interface.  ``mode`` selects the
    commit-scaling rule (MODE_DELTA / MODE_ADAG / MODE_DYNSGD).

    Feature parity (ISSUE 11): ``sparse_leaves`` registers row-sparse
    embedding tables served over the S/V/U/X wire actions; ``adaptive``
    enables the C++ Adasum flat-combining commit merger (per-worker rates
    pushed from the Python :class:`~.parameter_server.
    AdaptiveRateController`, which this wrapper subscribes to the process
    HealthMonitor) plus G/Y reconnect backpressure; ``replica_of``
    starts this hub as a hot STANDBY of the named primary (C++ feed
    thread, promotion behind the clock fence on feed loss or first
    commit) and an ``R`` hello from a peer attaches it to this hub's own
    replication feed as a primary.  ``idle_timeout`` evicts half-open
    connections via ``SO_RCVTIMEO``; ``elastic=True`` normalizes ADAG
    commits by the live committer count; ``snapshot_dir`` attaches a
    :class:`~.parameter_server.HubSnapshotter` and ``restore=True``
    reloads the newest snapshot — with the clock fence armed in C++ —
    before serving."""

    # matches SocketParameterServer's replica-loop defaults
    _POLL_INTERVAL_S = 0.25

    def __init__(self, weights: Sequence[np.ndarray], mode: int = MODE_DELTA,
                 num_workers: int = 1, port: int = 0,
                 elastic: bool = False,
                 idle_timeout: Optional[float] = 300.0,
                 snapshot_dir: Optional[str] = None,
                 snapshot_interval: float = 30.0,
                 snapshot_keep: int = 3,
                 restore: bool = False,
                 shard_id: Optional[int] = None,
                 replica_of: Optional[Tuple[str, int]] = None,
                 replica_feed_retries: int = 3,
                 replica_feed_backoff: float = 0.2,
                 sparse_leaves: Sequence[int] = (),
                 adaptive: bool = False,
                 shm_dir: Optional[str] = None,
                 recv_batch_depth: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native PS unavailable: {build_error()}")
        self._lib = lib
        self._templates = [np.array(w, dtype=np.float32) for w in weights]
        self.sparse_leaves = tuple(sorted({int(i) for i in sparse_leaves}))
        for i in self.sparse_leaves:
            if not 0 <= i < len(self._templates):
                raise ValueError(f"sparse leaf index {i} out of range for "
                                 f"{len(self._templates)} center leaves")
            if self._templates[i].ndim != 2:
                raise ValueError(
                    f"sparse leaf {i} must be a [rows, dim] table, got "
                    f"shape {self._templates[i].shape}")
        self.adaptive = bool(adaptive)
        self.replica_of = (None if replica_of is None
                           else (str(replica_of[0]), int(replica_of[1])))
        self.replica_feed_retries = int(replica_feed_retries)
        self.replica_feed_backoff = float(replica_feed_backoff)
        sizes = (ctypes.c_int64 * len(self._templates))(
            *[t.size for t in self._templates])
        n_sp = len(self.sparse_leaves)
        sp_idx = (ctypes.c_int32 * max(1, n_sp))(*(self.sparse_leaves
                                                   or (0,)))
        sp_dim = (ctypes.c_int64 * max(1, n_sp))(
            *([self._templates[i].shape[1] for i in self.sparse_leaves]
              or [0]))
        idle_ms = 0 if idle_timeout is None else max(1, int(idle_timeout * 1000))
        # receive bound shared with the Python hub: both implementations
        # reject the exact same oversized length prefixes
        max_payload = net.max_request_payload(self._templates,
                                              self.sparse_leaves)
        self._handle = lib.dk_ps_create(int(port), len(self._templates), sizes,
                                        int(mode), int(num_workers),
                                        1 if elastic else 0, idle_ms,
                                        n_sp, sp_idx, sp_dim,
                                        1 if self.adaptive else 0,
                                        int(max_payload))
        if not self._handle:
            raise RuntimeError("dk_ps_create failed")
        # zero-copy shm transport (ISSUE 18): with a ring directory set,
        # the C++ hub answers the opt-in 'Z' attach — same-host workers'
        # frames move over mmap rings byte-identical to the socket stream.
        # None keeps the hub TCP-only (it declines nothing: the action
        # never reaches a hub whose clients were not asked to send it,
        # and an unsolicited 'Z' is declined with an empty offer).
        self.shm_dir = None if shm_dir is None else str(shm_dir)
        if self.shm_dir is not None:
            os.makedirs(self.shm_dir, exist_ok=True)
            lib.dk_ps_shm_attach(self._handle,
                                 self.shm_dir.encode("utf-8"))
        # accepted for hub-kwarg parity with SocketParameterServer: the
        # C++ receive loop already drains a pipelined client's parked
        # frames with ONE recv() per wakeup into its grow-once buffer,
        # which is what the Python hub's BatchedReceiver approximates —
        # the knob has nothing further to turn natively
        self.recv_batch_depth = max(0, int(recv_batch_depth))
        if self.replica_of is not None:
            host = self.replica_of[0]
            if host in ("", "0.0.0.0"):
                host = "127.0.0.1"
            # the C++ dialer takes numeric addresses only: resolve DNS
            # names HERE, loudly — a standby silently never syncing is
            # the one failure mode worse than refusing to construct
            import socket as _socket

            try:
                host = _socket.gethostbyname(host)
            except OSError as e:
                raise ValueError(
                    f"replica_of host {self.replica_of[0]!r} does not "
                    f"resolve: {e}") from e
            lib.dk_ps_set_replica_of(
                self._handle, host.encode("utf-8"), int(self.replica_of[1]),
                self.replica_feed_retries,
                max(1, int(self.replica_feed_backoff * 1000)))
        flat = np.concatenate([t.reshape(-1) for t in self._templates]) if self._templates \
            else np.zeros(0, np.float32)
        self._total = int(flat.size)
        lib.dk_ps_set_weights(self._handle, _f32p(flat))
        self.port = -1
        self._started = False
        # telemetry bridge state: last-seen cumulative stats/histograms so
        # sync_telemetry() can inc() registry counters by DELTAS only
        self._stats_lock = threading.Lock()
        # serializes the two C++ drains (health ring, commit log): the
        # poll thread and sync_telemetry callers (snapshotter, shutdown)
        # share the ctypes buffers below, and ctypes releases the GIL —
        # unlocked concurrent drains would tear each other's data
        self._drain_lock = threading.Lock()
        self._last_stats = [0] * len(self._STAT_KEYS)
        self._last_stale_hist = [0] * 65
        self._last_merge_hist = [0] * 65
        self._drain_buf = np.zeros(4096 * 5, np.int64)
        self._health_buf = np.zeros(
            max(net.CONTROL_PAYLOAD_MAX, int(max_payload)), np.uint8)
        # sharded-hub identity: mirrors the Python hub
        self.shard_id = None if shard_id is None else int(shard_id)
        self._mlabels = ({} if shard_id is None
                         else {"shard": str(int(shard_id))})
        # adaptive glue (bound in start(), the Python hub's eager-bind
        # convention): Python-side rate controller + monitor subscription
        # pushing verdicts into the C++ apply path
        self._rate: Optional[Any] = None
        self._health: Optional[Any] = None
        self._health_monitor: Optional[Any] = None
        self._health_unsub: Optional[Any] = None
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._restore = bool(restore)
        self.snapshotter = None
        if restore and snapshot_dir is None:
            raise ValueError("restore=True requires snapshot_dir")
        if snapshot_dir is not None:
            from distkeras_tpu.runtime.parameter_server import HubSnapshotter

            self.snapshotter = HubSnapshotter(self, snapshot_dir,
                                              interval=snapshot_interval,
                                              keep=snapshot_keep)

    def start(self) -> None:
        if self._restore and self.snapshotter is not None:
            # same contract as the Python hub: unreadable-but-present
            # snapshots are fatal (don't silently discard a job's
            # progress); a genuinely empty dir is a first boot
            if not self.snapshotter.restore_latest():
                if self.snapshotter.checkpointer.all_steps():
                    raise RuntimeError(
                        f"restore requested: snapshots exist in "
                        f"{self.snapshotter.checkpointer.directory} but none "
                        f"is readable (see warnings)")
                import warnings

                warnings.warn("restore requested but no snapshot exists "
                              "yet; serving initial weights")
        if self.adaptive:
            # bind the health plane eagerly and SUBSCRIBE (the Python
            # adaptive hub's convention): detector events drive the rate
            # controller, whose verdicts are pushed into C++ per worker
            from distkeras_tpu.observability import health as _health
            from distkeras_tpu.runtime.parameter_server import (
                AdaptiveRateController)

            if self._health is None:
                self._health = _health.collector()
            if self._health_monitor is None:
                self._health_monitor = _health.monitor()
            self._rate = AdaptiveRateController()
            self._health_unsub = self._health_monitor.subscribe(
                self._on_health_event)
        port = self._lib.dk_ps_start(self._handle)
        if port < 0:
            raise RuntimeError("native PS failed to bind")
        self.port = port
        self._started = True
        # the poll thread is the native hub's stand-in for the Python
        # hub's in-handler folds: it drains wire 'M' health reports into
        # the process collector and (adaptive) folds per-commit staleness
        # from the C++ commit log so the detectors see the same series
        self._poll_stop.clear()
        self._poll_thread = threading.Thread(target=self._poll_loop,
                                             daemon=True)
        self._poll_thread.start()
        if self.snapshotter is not None:
            self.snapshotter.start()

    def stop(self) -> None:
        self._shutdown(final_snapshot=True)

    def kill(self) -> None:
        """Crash-like teardown (no final snapshot) — the C++ twin of
        ``SocketParameterServer.kill``."""
        self._shutdown(final_snapshot=False)

    def _shutdown(self, final_snapshot: bool) -> None:
        if self._started:
            if self._health_unsub is not None and self._health_monitor is not None:
                self._health_monitor.unsubscribe(self._health_unsub)
                self._health_unsub = None
            self._poll_stop.set()
            if self._poll_thread is not None:
                self._poll_thread.join(timeout=5)
                self._poll_thread = None
            if self.snapshotter is not None:
                self.snapshotter.stop(final_snapshot=final_snapshot)
            # surface the C++ hub's final counters/commit log into the
            # registry/tracer before the serving threads go away
            try:
                self.sync_telemetry()
            except Exception:
                pass  # telemetry must never block a teardown
            self._lib.dk_ps_stop(self._handle)
            self._started = False

    # -- hot standby (replica_of surface) ---------------------------------------
    def is_standby(self) -> bool:
        """True while this hub is a replica tracking its primary (not yet
        promoted) — the C++ feed thread owns the tracking."""
        return bool(self._lib.dk_ps_is_standby(self._handle))

    @property
    def promoted(self) -> bool:
        return bool(self._lib.dk_ps_promoted(self._handle))

    @property
    def promoted_at_clock(self) -> Optional[int]:
        v = int(self._lib.dk_ps_promoted_at_clock(self._handle))
        return None if v < 0 else v

    def wait_synced(self, timeout: Optional[float] = None) -> bool:
        """Block until this replica has applied its first full sync from
        the primary (True), or ``timeout`` elapsed (False)."""
        ms = -1 if timeout is None else max(0, int(timeout * 1000))
        return bool(self._lib.dk_ps_wait_synced(self._handle, ms))

    def promote(self, reason: str = "manual") -> bool:
        """Promote the standby to primary (ops/test hook; the C++ hub also
        promotes itself on feed loss or first commit).  Arms the clock
        fence at the replicated clock, idempotent; True if this call
        performed the promotion."""
        performed = bool(self._lib.dk_ps_promote(self._handle))
        if performed:
            import warnings

            warnings.warn(f"native replica hub promoting to primary at "
                          f"clock {self.promoted_at_clock}: {reason}")
        return performed

    # -- adaptive glue ----------------------------------------------------------
    def _on_health_event(self, event: Any) -> None:
        """HealthMonitor.subscribe callback: storm events arm C++-side
        reconnect shedding; staleness/straggler events update the Python
        rate controller, whose fresh verdict for that worker is pushed
        into the C++ apply path with an expiry deadline (an expired
        verdict reads as 1.0, so a dead controller can never pin a
        worker's scale)."""
        try:
            if getattr(event, "kind", None) in ("reconnect_storm",
                                                "failover_storm"):
                self._lib.dk_ps_arm_storm(self._handle)
            rate = self._rate
            if rate is None:
                return
            rate.on_event(event)
            worker = getattr(event, "worker", None)
            if worker is None:
                return
            try:
                wid = int(str(worker))
            except ValueError:
                return  # only wire-announceable (integer) ids reach C++
            expires = self.time_ns() + int(rate.hold_s * 1e9)
            self._lib.dk_ps_set_rate_scale(self._handle, wid,
                                           float(rate.scale_for(worker)),
                                           expires)
        except Exception:
            pass  # adaptation must never take down the emitting path

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self._POLL_INTERVAL_S):
            try:
                self._drain_health()
                if self.adaptive:
                    self._consume_commit_log()
                    mon = self._health_monitor
                    if mon is not None:
                        mon.maybe_check()
            except Exception:
                pass  # telemetry/health must never kill the hub

    def _ingest_health(self, report: Dict[str, Any]) -> None:
        """Fold one drained wire report into the process collector (lazy
        binding, the Python hub's _ingest_health)."""
        if self._health is None or self._health_monitor is None:
            from distkeras_tpu.observability import health as _health

            if self._health is None:
                self._health = _health.collector()
            if self._health_monitor is None:
                self._health_monitor = _health.monitor()
        self._health.ingest(report, shard=self.shard_id)
        self._health_monitor.maybe_check()

    def _drain_health(self) -> None:
        """Drain the C++ hub's parked action-``M`` reports into the
        process HealthCollector."""
        ptr = self._health_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        while True:
            with self._drain_lock:
                n = int(self._lib.dk_ps_next_health(self._handle, ptr,
                                                    self._health_buf.size))
                raw = bytes(self._health_buf[:n]) if n > 0 else b""
            if n == 0:
                break
            if n < 0:
                continue  # oversized report dropped (counted C++-side)
            try:
                report = json.loads(raw.decode("utf-8"))
            except Exception:
                continue  # malformed reports are ignored, never fatal
            self._ingest_health(report)

    # -- telemetry bridge (dk_ps_stats and friends) ----------------------------
    def _shard_attrs(self) -> Dict[str, int]:
        return {} if self.shard_id is None else {"shard": self.shard_id}

    # dk_ps_stats slot order (native/ps_server.cpp StatSlot) — keep in sync
    _STAT_KEYS = ("commits", "pulls", "commit_bytes", "pull_bytes",
                  "fenced_commits", "live_workers", "idle_evictions", "clock",
                  "commit_log_dropped",
                  "sparse_rows_pulled", "sparse_rows_committed",
                  "sparse_wire_bytes_saved",
                  "replicas_connected", "replicas_attached",
                  "replica_disconnects",
                  "merge_batches", "merged_commits", "max_merge_batch",
                  "backpressure_hints", "replica_frames", "promotions",
                  "health_reports_dropped", "is_standby", "promoted_flag",
                  "promoted_at_clock", "synced",
                  "repl_sparse_bytes", "repl_sparse_saved")

    # cumulative counters synced into the registry under the SAME names
    # the Python hub emits, so Prometheus/punchcard output is
    # hub-implementation-agnostic
    _COUNTER_NAMES = (("commits", "ps_commits_total"),
                      ("pulls", "ps_pulls_total"),
                      ("commit_bytes", "ps_commit_bytes_total"),
                      ("pull_bytes", "ps_pull_bytes_total"),
                      ("fenced_commits", "ps_fenced_commits_total"),
                      ("idle_evictions", "ps_idle_evictions_total"),
                      ("commit_log_dropped", "ps_commit_log_dropped_total"),
                      ("sparse_rows_pulled", "ps.sparse_rows_pulled"),
                      ("sparse_rows_committed", "ps.sparse_rows_committed"),
                      ("sparse_wire_bytes_saved", "ps.sparse_wire_bytes_saved"),
                      ("replicas_attached", "ps_replicas_attached_total"),
                      ("replica_disconnects", "ps_replica_disconnects_total"),
                      ("merged_commits", "ps_merged_commits_total"),
                      ("backpressure_hints", "ps_backpressure_hints_total"),
                      ("replica_frames", "ps_replica_frames_total"),
                      ("promotions", "ps_promotions_total"),
                      ("repl_sparse_saved", "ps.repl_sparse_bytes_saved"))

    def stats(self) -> Dict[str, int]:
        """The C++ hub's cumulative counters, by name (see ``dk_ps_stats``
        in ``native/ps_server.cpp``)."""
        out = (ctypes.c_int64 * len(self._STAT_KEYS))()
        self._lib.dk_ps_stats(self._handle, out)
        return dict(zip(self._STAT_KEYS, [int(v) for v in out]))

    @property
    def backpressure_hints(self) -> int:
        """Nonzero retry-after hints issued (reconnect-storm drills read
        it) — the Python adaptive hub's attribute, served from C++."""
        return self.stats()["backpressure_hints"]

    def fleet_info(self) -> Dict[str, Any]:
        """Fleet/admission snapshot in the Python hubs' ``fleet_info``
        shape.  The C++ hub does not namespace jobs (job-scoped T
        announces are a Python-hub feature; un-upgraded hubs reply with
        the plain time payload and the client treats that as a wire
        error), so the jobs block is always empty — callers see one
        uniform dict either way."""
        s = self.stats()
        return {"live_workers": int(s.get("live_workers", 0)),
                "jobs": {}, "clock": int(s.get("clock", 0)),
                "num_updates": int(s.get("commits", 0)),
                "jobs_admitted": 0, "jobs_rejected": 0}

    def time_ns(self) -> int:
        """The hub's CLOCK_MONOTONIC in ns — the same epoch Python's
        ``time.perf_counter_ns`` reads on Linux (offset sanity checks)."""
        return int(self._lib.dk_ps_time_ns(self._handle))

    def _consume_commit_log(self) -> None:
        """Drain the C++ commit log: each record becomes a hub-side span
        (telemetry on) and — when the health plane is bound — the
        announcing worker's staleness observation, the same series the
        Python hub's in-handler ``_observe_health`` folds feed."""
        telemetry = obs.enabled()
        fold = self._health is not None
        if not telemetry and not fold:
            return
        while True:
            with self._drain_lock:
                n = int(self._lib.dk_ps_drain_commits(
                    self._handle,
                    self._drain_buf.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int64)),
                    4096))
                records = self._drain_buf[:n * 5].copy()
            for i in range(n):
                clock, worker, staleness, t_ns, dur_ns = \
                    (int(v) for v in records[i * 5:i * 5 + 5])
                if telemetry:
                    attrs = {"staleness": staleness, "clock": clock,
                             "hub": "native", **self._shard_attrs()}
                    if worker >= 0:
                        attrs["worker"] = worker
                    obs.TRACER.record_span("ps.handle_commit", t_ns,
                                           t_ns + dur_ns, tid="native-hub",
                                           **attrs)
                if fold and worker >= 0:
                    # shard-0-only convention for sharded hubs: one logical
                    # commit lands on every shard, count it once
                    if self.shard_id is None or self.shard_id == 0:
                        self._health.observe(str(worker), "staleness",
                                             float(staleness),
                                             shard=self.shard_id)
            if n < 4096:
                break

    def sync_telemetry(self) -> None:
        """Drain the C++ hub's telemetry into the process registry/tracer
        under the SAME names the Python hub emits (``ps_commits_total``,
        ``ps_commit_staleness``, ``ps.sparse_rows_pulled``, ...), so
        Prometheus/punchcard output is hub-implementation-agnostic.
        Counters advance by deltas against the last sync; the commit log
        becomes ``ps.handle_commit`` spans.  Called automatically at
        shutdown and on every hub snapshot; call it directly for an
        up-to-the-moment mid-run view."""
        self._drain_health()
        if not obs.enabled():
            return
        with self._stats_lock:
            stats = self.stats()
            vals = [stats[k] for k in self._STAT_KEYS]
            delta = {k: v - last for k, v, last
                     in zip(self._STAT_KEYS, vals, self._last_stats)}
            self._last_stats = vals
            for key, name in self._COUNTER_NAMES:
                if delta[key] > 0:
                    obs.counter(name, **self._mlabels).inc(delta[key])
            obs.gauge("ps_live_workers",
                      **self._mlabels).set(stats["live_workers"])
            obs.gauge("ps_replicas_connected",
                      **self._mlabels).set(stats["replicas_connected"])
            # exact small-integer staleness counts -> the shared log-bucket
            # histogram (value == slot; the overflow slot observes as its
            # lower bound, a documented approximation)
            hist = (ctypes.c_int64 * 65)()
            self._lib.dk_ps_staleness_hist(self._handle, hist)
            stale = obs.histogram("ps_commit_staleness", **self._mlabels)
            for slot in range(65):
                stale.observe_n(slot, int(hist[slot]) - self._last_stale_hist[slot])
                self._last_stale_hist[slot] = int(hist[slot])
            if self.adaptive:
                self._lib.dk_ps_merge_hist(self._handle, hist)
                merge = obs.histogram("ps.merge_batch", **self._mlabels)
                for slot in range(65):
                    merge.observe_n(slot,
                                    int(hist[slot]) - self._last_merge_hist[slot])
                    self._last_merge_hist[slot] = int(hist[slot])
            if self.sparse_leaves:
                # decayed hot-set estimates under the same gauge the
                # Python hub emits (ISSUE 15 row-touch telemetry)
                hot = (ctypes.c_int64 * len(self.sparse_leaves))()
                self._lib.dk_ps_hot_rows(self._handle, hot)
                for leaf, count in zip(self.sparse_leaves, hot):
                    obs.gauge("ps.sparse_hot_rows", table=str(leaf),
                              **self._mlabels).set(int(count))
        # commit log -> hub-side spans on the "native-hub" track
        self._consume_commit_log()

    # -- durability (HubSnapshotter surface) -----------------------------------
    def snapshot_state(self):
        """(center tensors, JSON-typed state dict) — one atomic view via the
        C++ snapshot path (center + clock under the hub gate; NOT counted
        as a pull — the Python hub's snapshot_state is uncounted too).
        Piggybacks a telemetry sync: a snapshotting hub surfaces its C++
        counters into the registry at least once per snapshot interval."""
        try:
            self.sync_telemetry()
        except Exception:
            pass
        flat = np.empty(self._total, np.float32)
        clock = int(self._lib.dk_ps_snapshot(self._handle, _f32p(flat)))
        center, off = [], 0
        for t in self._templates:
            center.append(flat[off:off + t.size].reshape(t.shape).copy())
            off += t.size
        return (center,
                {"clock": clock, "num_updates": int(self.num_updates)})

    def restore_state(self, center: Sequence[np.ndarray], state) -> None:
        if len(center) != len(self._templates):
            raise ValueError(f"snapshot has {len(center)} tensors, center has "
                             f"{len(self._templates)}")
        parts = [np.ascontiguousarray(c, np.float32).reshape(-1) for c in center]
        flat = np.concatenate(parts) if parts else np.zeros(0, np.float32)
        if flat.size != self._total:
            raise ValueError(f"snapshot has {flat.size} values, center has "
                             f"{self._total}")
        self._lib.dk_ps_restore(self._handle, _f32p(flat),
                                int(state.get("clock", 0)),
                                int(state.get("num_updates", 0)))

    def get_weights(self) -> List[np.ndarray]:
        out = np.zeros(self._total, np.float32)
        self._lib.dk_ps_get_weights(self._handle, _f32p(out))
        result = []
        off = 0
        for t in self._templates:
            result.append(out[off:off + t.size].reshape(t.shape).copy())
            off += t.size
        return result

    # -- in-process transport (transport="inproc") -----------------------------
    # Mirrors SocketParameterServer.pull_direct/commit_direct: co-located
    # workers exchange with the C++ center through two ctypes calls (both
    # release the GIL for the memcpy/apply), no sockets, no framing.

    def pull_direct(self):
        """(center tensors, clock at snapshot) — the clock rides back in
        with the matching :meth:`commit_direct`."""
        if self.is_standby() and not self._lib.dk_ps_wait_synced(self._handle, 0):
            # same rule as the Python hub's pull_direct: seed weights must
            # never be served as if they were the job's state
            raise RuntimeError(
                "pull_direct from a never-synced standby refused "
                "(it holds no job state yet); wait_synced() first")
        flat = np.empty(self._total, np.float32)
        clock = int(self._lib.dk_ps_pull(self._handle, _f32p(flat)))
        out, off = [], 0
        for t in self._templates:
            out.append(flat[off:off + t.size].reshape(t.shape))
            off += t.size
        return out, clock

    def commit_direct(self, delta: Sequence[np.ndarray], last_pull_clock: int) -> None:
        if len(delta) != len(self._templates):
            raise ValueError(f"commit has {len(delta)} tensors, center has "
                             f"{len(self._templates)}")
        parts = []
        for d, t in zip(delta, self._templates):
            a = np.ascontiguousarray(d, dtype=np.float32).reshape(-1)
            if a.size != t.size:
                raise ValueError(f"commit tensor size {a.size} != center "
                                 f"size {t.size}")
            parts.append(a)
        flat = np.concatenate(parts) if parts else np.zeros(0, np.float32)
        # attribute the commit to the calling worker thread's trace
        # context (inproc workers have no connection to announce T on);
        # -1 = uncontexted, matching the wire default
        ctx = dtrace.current()
        worker = int(ctx.worker_id) if ctx is not None else -1
        rc = int(self._lib.dk_ps_commit_ctx(self._handle, _f32p(flat),
                                            int(last_pull_clock), worker))
        if rc == 1:
            raise RuntimeError(
                "commit_direct into a never-synced standby refused "
                "(it has no state to take over); wait_synced() first")
        if rc == 2:
            raise net.ProtocolError(
                "commit into a standby refused (not promoted yet; verifying "
                "the primary — retry)")

    # -- sparse in-process transport (ISSUE 15) --------------------------------
    # The former last NotImplementedError pair: the C++ hub now serves
    # the sparse direct exchange too (dk_ps_pull_sparse /
    # dk_ps_commit_sparse, GIL released), so EVERY transport x hub cell
    # composes with sparse_tables.  Semantics mirror the Python hub's
    # pull_sparse_direct/commit_sparse_direct (the bit-parity matrix in
    # tests/test_hyperscale.py pins the trajectories).

    def _check_row_ids(self, ids, leaf: int) -> np.ndarray:
        """The shared :func:`networking.check_row_ids` contract over this
        hub's templates (canonicalized to a contiguous int64 array for
        the ctypes boundary)."""
        return net.check_row_ids(
            np.ascontiguousarray(np.asarray(ids).ravel(), np.int64),
            self._templates[leaf].shape[0], leaf)

    def _pack_sparse_ids(self, ids_list):
        """Validated (sorted-unique, in-bounds) id arrays -> one
        concatenated int64 buffer + per-table counts."""
        if len(ids_list) != len(self.sparse_leaves):
            raise ValueError(f"got {len(ids_list)} id arrays, hub has "
                             f"{len(self.sparse_leaves)} sparse tables")
        norm = [self._check_row_ids(ids, i)
                for ids, i in zip(ids_list, self.sparse_leaves)]
        counts = (ctypes.c_int64 * max(1, len(norm)))(
            *([ids.size for ids in norm] or [0]))
        flat = (np.concatenate(norm) if norm
                else np.zeros(0, np.int64))
        flat = np.ascontiguousarray(flat, np.int64)
        if flat.size == 0:
            flat = np.zeros(1, np.int64)  # a valid pointer for ctypes
        return norm, flat, counts

    def pull_sparse_direct(self, ids_list):
        """The S/V exchange minus the frame against the C++ center: one
        sorted-unique id array per sparse table in, ``(per-leaf values,
        clock)`` out — full copies for dense leaves, the requested
        ``[k, dim]`` row blocks for sparse leaves."""
        if not self.sparse_leaves:
            raise RuntimeError("pull_sparse_direct on a hub with no sparse "
                               "tables (pass sparse_leaves to the hub)")
        norm, flat_ids, counts = self._pack_sparse_ids(ids_list)
        total = 0
        it = iter(norm)
        for i, t in enumerate(self._templates):
            total += (next(it).size * t.shape[1]
                      if i in set(self.sparse_leaves) else t.size)
        out = np.empty(max(1, total), np.float32)
        clock = int(self._lib.dk_ps_pull_sparse(
            self._handle,
            flat_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            counts, _f32p(out)))
        if clock == -1:
            raise RuntimeError(
                "pull_sparse_direct from a never-synced standby refused "
                "(it holds no job state yet); wait_synced() first")
        if clock == -2:
            raise ValueError("sparse pull row ids rejected by the native "
                             "hub (sorted-unique, in-bounds required)")
        values, off = [], 0
        it = iter(norm)
        for i, t in enumerate(self._templates):
            if i in set(self.sparse_leaves):
                k = next(it).size
                n = k * t.shape[1]
                values.append(out[off:off + n].reshape(k, t.shape[1]).copy())
            else:
                n = t.size
                values.append(out[off:off + n].reshape(t.shape).copy())
            off += n
        return values, clock

    def commit_sparse_direct(self, parts, last_pull_clock):
        """Apply one row-sparse commit (the U exchange minus the frame):
        ``parts`` aligned with the center — full f32 delta for dense
        leaves, ``(ids, grads)`` for sparse leaves."""
        if not self.sparse_leaves:
            raise RuntimeError("commit_sparse_direct on a hub with no "
                               "sparse tables (pass sparse_leaves)")
        if len(parts) != len(self._templates):
            raise ValueError(f"commit has {len(parts)} parts, center has "
                             f"{len(self._templates)}")
        sset = set(self.sparse_leaves)
        ids_list = []
        vals = []
        for i, (p, t) in enumerate(zip(parts, self._templates)):
            if i in sset:
                ids, grads = p
                ids = self._check_row_ids(ids, i)
                grads = np.ascontiguousarray(grads, np.float32).reshape(
                    ids.size, t.shape[1])
                ids_list.append(ids)
                vals.append(grads.reshape(-1))
            else:
                vals.append(np.ascontiguousarray(p, np.float32).reshape(-1))
        counts = (ctypes.c_int64 * max(1, len(ids_list)))(
            *([ids.size for ids in ids_list] or [0]))
        flat_ids = (np.concatenate(ids_list) if ids_list
                    else np.zeros(0, np.int64))
        flat_ids = np.ascontiguousarray(flat_ids, np.int64)
        if flat_ids.size == 0:
            flat_ids = np.zeros(1, np.int64)
        flat_vals = (np.concatenate(vals) if vals
                     else np.zeros(0, np.float32))
        flat_vals = np.ascontiguousarray(flat_vals, np.float32)
        if flat_vals.size == 0:
            flat_vals = np.zeros(1, np.float32)
        ctx = dtrace.current()
        worker = int(ctx.worker_id) if ctx is not None else -1
        rc = int(self._lib.dk_ps_commit_sparse(
            self._handle, _f32p(flat_vals),
            flat_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            counts, int(last_pull_clock), worker))
        if rc == 1:
            raise RuntimeError(
                "commit_sparse_direct into a never-synced standby refused "
                "(it has no state to take over); wait_synced() first")
        if rc == 2:
            raise net.ProtocolError(
                "commit into a standby refused (not promoted yet; verifying "
                "the primary — retry)")
        if rc == 3:
            raise ValueError("sparse commit row ids rejected by the native "
                             "hub (sorted-unique, in-bounds required)")

    @property
    def num_updates(self) -> int:
        return int(self._lib.dk_ps_num_updates(self._handle))

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                if self._started:
                    self._shutdown(final_snapshot=False)
                self._lib.dk_ps_destroy(self._handle)
                self._handle = None
        except Exception:
            pass
