"""Framed socket transport — reference parity for ``distkeras/networking.py``.

The reference framed **pickled** objects with a length prefix over TCP
(``send_data``/``recv_data``; SURVEY.md §2.12).  Pickle executes arbitrary
code at load time, so this re-design keeps the framing but replaces the
payload encodings with two safe forms:

- **JSON frames** (:func:`send_json` / :func:`recv_json`) for control-plane
  messages (job submission, PS handshakes).
- **Tensor frames** (:func:`send_tensors` / :func:`recv_tensors`) for the
  gradient plane: a 1-byte action tag + raw tensor byte blobs.  Dtype and
  shape travel out-of-band (both ends hold the model template), keeping the
  hot path a straight ``memcpy`` — this exact layout is also what the C++
  hub (``native/ps_server.cpp``) parses.

Wire format (all integers big-endian):

    frame        := u64 payload_len, payload
    json payload := utf-8 JSON bytes
    tensor payload := u8 action, u32 num_tensors,
                      num_tensors * (u64 nbytes, raw bytes)

Actions: ``P`` pull request, ``C`` commit, ``Q`` int8-compressed commit,
``B`` bye, ``W`` weights reply, ``A`` ack.

Two implementations move tensor frames:

- the **generic path** (:func:`send_tensors` / :func:`recv_tensors`) builds
  and parses frames ad hoc — control plane, tests, peers without a shared
  schema;
- the **flat path** (:class:`FlatFrameCodec`, :func:`recv_frame_into`,
  :func:`decode_tensor_views`) moves the SAME bytes through preallocated
  storage for connections with a fixed tensor schema (the PS pull/commit
  hot loop): the send frame is built once with every constant byte
  prewritten and per message only the action byte and tensor payloads are
  stamped in (one ``memcpy`` per tensor, zero intermediate ``bytes``),
  while receives scatter straight into the caller's arrays with
  ``recv_into`` — the payload is written exactly once, by the kernel, at
  its final destination.  Wire bytes are identical between the two paths,
  so the C++ hub and pre-existing peers interoperate unchanged.

``Q`` commits carry each tensor as a 4-byte big-endian float32 scale
followed by the int8-quantized values (symmetric per-tensor:
``q = round(d / scale)``, ``scale = max|d| / 127``) — 4x fewer wire
bytes than ``C``.  The hub dequantizes and applies the SAME scaling
rules as a plain commit; workers keep the quantization residual and add
it to the next window's delta (error feedback), so the committed sum
tracks the true delta sum and compression does not bias training (the
property ``tests/test_runtime.py`` pins).  The reference always shipped
full-precision pickled weight lists (SURVEY §2.12); this is the
DCN-bandwidth headroom lever for the genuinely-async PS topology.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu import observability as obs

MAX_FRAME = 1 << 34  # 16 GiB sanity bound on a single frame

ACTION_PULL = b"P"
ACTION_COMMIT = b"C"
ACTION_QCOMMIT = b"Q"
ACTION_BYE = b"B"
ACTION_WEIGHTS = b"W"
ACTION_ACK = b"A"
ACTION_PING = b"H"  # client heartbeat-on-idle; hub replies with an ack
# trace-context announce: one JSON blob (job_id/worker_id/span_id); the hub
# remembers the context for this connection's spans and replies with an
# action-T frame carrying one 8-byte big-endian blob = the hub's monotonic
# clock in ns (the NTP-style sample the client's offset estimate is built
# from).  Sent only when distributed tracing is configured, so pre-T hubs
# never see it (the PR 3/4 convention: wire bytes of every pre-existing
# frame are unchanged, new frames are opt-in).
ACTION_TRACE = b"T"
# health-report push (live fleet health plane, ISSUE 8): a worker
# periodically sends one M frame carrying a single JSON blob — its
# compact per-worker metric report (windows, rolling window wall,
# reconnect/failover totals) — which the hub folds into the process
# HealthCollector and acks (the ack coalesces into later receives like a
# commit ack, so reports ride the pipelined FIFO instead of their own
# round trip).  Opt-in like ``T``: no M frame ever moves unless the
# trainer sets ``health_interval_s``, so pre-M peers interoperate
# byte-identically.
ACTION_HEALTH = b"M"
# receive-bound allowance for control-plane frames (the single-JSON-blob
# payloads of actions T and M, whose size derives from report contents,
# not from the model): the hub receives against
# max(largest tensor frame, CONTROL_PAYLOAD_MAX), so a verbose health
# report fits even on a tiny center while a garbage length prefix still
# cannot conjure more than ~64 KiB
CONTROL_PAYLOAD_MAX = 64 * 1024
# hub-to-hub replication feed (hot-standby HA): a replica hub announces
# itself to its primary with an R "hello" frame (one 9-byte header blob);
# the primary replies on the same connection with one R full-sync frame
# (header + the whole center at one clock) and thereafter streams one R
# delta frame per APPLIED commit (header + the post-aggregation scaled
# delta), sent BEFORE the committing worker's ack leaves — see
# ``encode_repl_header``.  Opt-in like ``T``: no R frame ever moves unless
# a replica connects, so pre-R peers interoperate byte-identically.
ACTION_REPL = b"R"

# R-frame header kinds (first blob, 9 bytes big-endian: u64 clock, u8 kind)
REPL_DELTA = 0  # primary->replica: blobs[1:] = scaled applied delta
REPL_SYNC = 1   # primary->replica: blobs[1:] = full center at `clock`
REPL_HELLO = 2  # replica->primary: no tensor blobs; `clock` = replica's clock
# sparse row-delta frame (hyperscale embedding tier, ISSUE 15): blobs[1:]
# carry the applied commit in the U-commit layout — per center leaf in
# template order, one full f32 delta blob for dense leaves and TWO blobs
# (int64 row ids, f32 [k, dim] scaled row deltas) for sparse leaves — so
# replication cost is proportional to the touched rows, not the model.
# The standby applies ``center[ids] += delta`` behind the same clock
# fence as a dense delta.  A primary sends these ONLY to replicas whose
# hello announced REPL_CAP_SPARSE (attach-time capability): a legacy
# standby keeps receiving the dense-materialized REPL_DELTA stream, so
# an old-generation standby attached to a new primary is never handed a
# frame kind it cannot parse
REPL_SPARSE = 3

# hello capability bits (optional 10th byte of the hello header blob —
# a 9-byte hello reads as capabilities 0, and a pre-ISSUE-15 primary
# slices the first 9 bytes off a 10-byte hello, so both directions of
# version skew degrade to the dense stream instead of a torn one)
REPL_CAP_SPARSE = 1

# row-sparse embedding traffic (ISSUE 9): a worker whose model declares
# EmbeddingTable leaves (shape [rows, dim], registered as ``sparse_leaves``
# on both ends) exchanges only the rows a batch touches —
#
#   ``S`` sparse pull request: one int64 sorted-unique row-id blob per
#         sparse table (ascending leaf order); dense leaves need no
#         request payload, they always ride the reply whole.
#   ``V`` sparse weights reply: one blob per CENTER LEAF in template
#         order — the full leaf (f32) for dense leaves, the requested
#         ``[k, dim]`` row block (f32) for sparse leaves.
#   ``U`` sparse f32 commit: per leaf in template order — one full f32
#         delta blob for dense leaves, TWO blobs (int64 row ids, f32
#         ``[k, dim]`` row grads) for sparse leaves.
#   ``X`` sparse int8 commit: same layout with every value blob carried
#         as a ``Q`` blob (be-f32 scale + int8 values; the row block is
#         quantized as one unit).
#
# Row ids are int64 in native byte order — the same raw-tensor-bytes
# convention every other blob uses — sorted and unique, so the hub's
# ``center[ids] += rows`` apply is race-free under its lock.  Opt-in like
# ``T``/``M``/``R``: no S/V/U/X frame ever moves unless BOTH ends declare
# sparse tables, so every pre-existing frame stays byte-identical and
# un-upgraded peers interoperate unchanged.
ACTION_SPARSE_PULL = b"S"
ACTION_SPARSE_WEIGHTS = b"V"
ACTION_SPARSE_COMMIT = b"U"
ACTION_SPARSE_QCOMMIT = b"X"

# reconnect-storm backpressure (ISSUE 10): an ADAPTIVE client announces
# every reconnect with a ``G`` frame (one 8-byte big-endian blob — the
# hub-paced waits it has ALREADY taken this reconnect episode) as the
# FIRST frame on the fresh connection; the hub replies with a ``Y`` frame
# carrying a retry-after hint in milliseconds (one 8-byte big-endian
# blob).  Hint 0 means proceed; a positive hint asks the client to close,
# wait that long, and redial — the hub hands each member of a thundering
# herd a LATER slot instead of absorbing the whole herd at once, and an
# announcer that already waited its slot (blob > 0) is admitted, so every
# client waits at most once per storm.  Opt-in like ``T``/``M``: no G
# frame ever moves unless the client was constructed with
# ``adaptive=True``, so every pre-existing frame stays byte-identical and
# un-upgraded clients keep plain exponential backoff.
ACTION_RECONNECT = b"G"
ACTION_RETRY = b"Y"

ROW_ID_DTYPE = np.dtype(np.int64)


class ProtocolError(ValueError):
    """A frame violated the wire contract: garbage/oversized length prefix,
    truncated payload, tensor layout that does not match the schema.  After
    one of these the stream is desynchronized — callers must drop (and may
    re-establish) the connection.  Subclasses ``ValueError`` so every
    pre-existing ``except ValueError`` stays correct; the distinct type
    lets resilience layers (PSClient reconnect, hub eviction) treat
    malformed bytes as a connection fault rather than a caller bug."""


def determine_host_address() -> str:
    """Best-effort routable address of this host (reference:
    ``networking.determine_host_address``).  Uses a connected UDP socket so
    no traffic is actually sent."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


MIN_SOCKET_BUF = 64 << 10   # floor for SO_SNDBUF/SO_RCVBUF requests
MAX_SOCKET_BUF = 8 << 20    # cap — beyond one large frame, memory not speed


def configure_socket(sock: socket.socket, payload_hint: Optional[int] = None,
                     nodelay: bool = True) -> None:
    """Hot-path tuning applied to BOTH ends of every PS/client connection.

    - ``TCP_NODELAY``: the exchange is strictly request/response, so Nagle
      buys nothing and its interaction with delayed acks can park the
      13-byte ack/pull frames for tens of milliseconds — longer than an
      entire training window.
    - ``SO_SNDBUF``/``SO_RCVBUF`` sized to ``payload_hint`` (one full
      weights/commit frame, clamped to [64 KiB, 8 MiB]): a pipelined
      sender must be able to park a whole commit in the kernel and return
      to compute instead of blocking in ``sendall`` at the default buffer
      size.  Best-effort — the kernel may clamp further.  Without a hint
      the kernel defaults stand (control-plane connections don't need
      frame-sized buffers)."""
    if nodelay:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    if payload_hint is None:
        return
    size = max(MIN_SOCKET_BUF, min(int(payload_hint) + 4096, MAX_SOCKET_BUF))
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, size)
        except OSError:
            pass  # kernel policy may forbid resizing; defaults still work


def connect(host: str, port: int, disable_nagle: bool = True,
            timeout: Optional[float] = None,
            payload_hint: Optional[int] = None) -> socket.socket:
    """TCP connect (reference: ``networking.connect``); Nagle off by default —
    the PS exchange is request/response and latency-bound.  ``payload_hint``
    sizes the kernel buffers to the frame this connection will move."""
    sock = socket.create_connection((host, port), timeout=timeout)
    configure_socket(sock, payload_hint=payload_hint, nodelay=disable_nagle)
    return sock


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` completely from the socket (zero-copy receive)."""
    got, n = 0, view.nbytes
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError(f"peer closed mid-frame ({got}/{n} bytes)")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">Q", len(payload)) + payload)
    # count only after sendall returned: a frame dropped by a dying peer
    # must not inflate the tx accounting (mirrors the rx side's contract)
    if obs.enabled():
        obs.counter("net_tx_frames_total").inc()
        obs.counter("net_tx_bytes_total").inc(8 + len(payload))


def recv_frame(sock: socket.socket, limit: int = MAX_FRAME) -> bytes:
    """Receive one frame; ``limit`` bounds the declared payload size BEFORE
    any allocation happens, so an untrusted peer can't force a huge
    ``bytearray`` with an 8-byte header (servers pass a small limit until
    the peer has authenticated)."""
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if n > limit:
        raise ProtocolError(f"frame of {n} bytes exceeds limit={limit}")
    payload = _recv_exact(sock, n)
    # count only after the body fully arrived: a peer dying mid-frame must
    # not inflate the byte accounting by data that never landed
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return payload


def recv_frame_into(sock: socket.socket, buf: bytearray,
                    limit: int = MAX_FRAME) -> memoryview:
    """Receive one frame into the reusable ``buf`` (grown once to the
    largest frame seen, then steady-state zero-allocation), returning a
    memoryview of exactly the payload bytes.  The view aliases ``buf`` —
    it is valid only until the next call.  This is the long-lived-
    connection receive: the PS hub's handler loop reads every request
    through one of these per connection."""
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if n > limit:
        raise ProtocolError(f"frame of {n} bytes exceeds limit={limit}")
    if len(buf) < n:
        try:
            buf.extend(bytes(n - len(buf)))
        except BufferError:
            # live views of the previous frame pin the caller's buffer
            # (bytearray cannot resize with exports outstanding); receive
            # this oversized frame into a fresh buffer instead — the
            # caller's steady-state buffer is simply not grown this time
            buf = bytearray(n)
    mv = memoryview(buf)[:n]
    _recv_exact_into(sock, mv)
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return mv


def send_raw_frame(sock: socket.socket, frame: bytes) -> None:
    """Send an already-framed byte string (8-byte header included) — for
    prebuilt constant frames (acks, pull requests) on the hot path."""
    sock.sendall(frame)
    if obs.enabled():
        obs.counter("net_tx_frames_total").inc()
        obs.counter("net_tx_bytes_total").inc(len(frame))


# -- control plane: JSON frames -----------------------------------------------

def send_json(sock: socket.socket, obj: Dict[str, Any]) -> None:
    send_frame(sock, json.dumps(obj).encode("utf-8"))


def recv_json(sock: socket.socket, limit: int = MAX_FRAME) -> Dict[str, Any]:
    return json.loads(recv_frame(sock, limit=limit).decode("utf-8"))


# -- gradient plane: action + raw tensor frames -------------------------------

def encode_tensors(action: bytes, arrays: Sequence[np.ndarray]) -> bytes:
    parts = [action, struct.pack(">I", len(arrays))]
    for a in arrays:
        raw = np.ascontiguousarray(a).tobytes()
        parts.append(struct.pack(">Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def decode_tensors(payload: bytes) -> Tuple[bytes, List[bytes]]:
    action = payload[0:1]
    (count,) = struct.unpack(">I", payload[1:5])
    blobs: List[bytes] = []
    off = 5
    for _ in range(count):
        (nbytes,) = struct.unpack(">Q", payload[off:off + 8])
        off += 8
        blobs.append(payload[off:off + nbytes])
        off += nbytes
    if off != len(payload):
        raise ProtocolError(f"tensor frame has {len(payload) - off} trailing bytes")
    return action, blobs


def decode_tensor_views(payload) -> Tuple[bytes, List[memoryview]]:
    """:func:`decode_tensors` without the copies: blobs come back as
    memoryview slices into ``payload`` (pass the ``recv_frame_into`` view
    directly).  The views alias the receive buffer — decode/apply them
    before the next frame lands."""
    mv = payload if isinstance(payload, memoryview) else memoryview(payload)
    action = bytes(mv[0:1])
    (count,) = struct.unpack(">I", mv[1:5])
    blobs: List[memoryview] = []
    off = 5
    for _ in range(count):
        (nbytes,) = struct.unpack(">Q", mv[off:off + 8])
        off += 8
        if off + nbytes > len(mv):
            raise ProtocolError("tensor frame truncated mid-blob")
        blobs.append(mv[off:off + nbytes])
        off += nbytes
    if off != len(mv):
        raise ProtocolError(f"tensor frame has {len(mv) - off} trailing bytes")
    return action, blobs


def _scatter_recv_into(sock: socket.socket, out: Sequence[np.ndarray],
                       scratch: memoryview, limit: int) -> bytes:
    """The one scatter-receive core (shared by ``FlatFrameCodec.recv_into``
    and the templated ``recv_tensors`` path, so their frame validation can
    never drift apart): read one tensor frame whose layout must match
    ``out`` exactly — prefixes land in the 13-byte ``scratch``, payloads
    land in ``out`` via ``recv_into`` — and return the action byte.  Any
    mismatch raises ``ValueError`` with the stream desynchronized."""
    _recv_exact_into(sock, scratch[:8])
    (n,) = struct.unpack(">Q", scratch[:8])
    if n > limit:
        raise ProtocolError(f"frame of {n} bytes exceeds limit={limit}")
    expected = 5 + sum(8 + a.nbytes for a in out)
    if n != expected:
        raise ProtocolError(f"tensor frame of {n} payload bytes does not match "
                         f"the expected layout ({expected} bytes)")
    _recv_exact_into(sock, scratch[:5])
    action = bytes(scratch[:1])
    (count,) = struct.unpack(">I", scratch[1:5])
    if count != len(out):
        raise ProtocolError(f"frame has {count} tensors, expected {len(out)}")
    for dst in out:
        _recv_exact_into(sock, scratch[:8])
        (nbytes,) = struct.unpack(">Q", scratch[:8])
        if nbytes != dst.nbytes or not dst.flags.c_contiguous:
            raise ProtocolError(f"tensor of {nbytes} bytes does not match its "
                             f"output slot ({dst.nbytes} bytes, contiguous)")
        if nbytes:
            # zero-byte blobs are legal (an all-hit hot-tier pull, an
            # untouched per-table id set) and an empty ndarray cannot be
            # cast to a flat memoryview
            _recv_exact_into(sock, memoryview(dst).cast("B"))
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return action


def empty_tensor_frame(action: bytes) -> bytes:
    """The complete 13-byte frame of a tensor-less message (pull request,
    ack, bye) — header included, built once and reused via
    :func:`send_raw_frame`."""
    return struct.pack(">Q", 5) + action + struct.pack(">I", 0)


def recv_action(sock: socket.socket) -> bytes:
    """Receive a frame known to carry zero tensors (the ack/control leg of
    the pipelined client) and return its action byte."""
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if n != 5:
        raise ProtocolError(f"expected a tensor-less frame, got {n}-byte payload")
    payload = _recv_exact(sock, 5)
    (count,) = struct.unpack(">I", payload[1:5])
    if count != 0:
        raise ProtocolError(f"expected zero tensors, frame declares {count}")
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return payload[0:1]


# -- trace-context announce (action T) ----------------------------------------

def encode_context_payload(context_json: bytes) -> bytes:
    """The client->hub trace-context announce payload: an action-``T``
    tensor frame whose single blob is the UTF-8 JSON encoding of the
    announcing worker's :class:`~distkeras_tpu.observability.distributed.
    TraceContext`."""
    return encode_tensors(ACTION_TRACE, [np.frombuffer(context_json, np.uint8)])


def encode_health_payload(report_json: bytes) -> bytes:
    """The worker->hub health-report payload (action ``M``): a tensor
    frame whose single blob is the UTF-8 JSON report the
    :class:`~distkeras_tpu.observability.health.HealthCollector`
    ingests."""
    return encode_tensors(ACTION_HEALTH,
                          [np.frombuffer(report_json, np.uint8)])


def encode_time_payload(t_ns: int) -> bytes:
    """The hub->client ``T`` reply payload: one 8-byte big-endian blob
    carrying the hub's monotonic clock in nanoseconds."""
    return ACTION_TRACE + struct.pack(">I", 1) + struct.pack(">Q", 8) \
        + struct.pack(">Q", t_ns)


def decode_time_payload(blobs: Sequence) -> int:
    """Inverse of :func:`encode_time_payload` given the decoded blob list."""
    if not blobs:
        raise ProtocolError("T reply carries no timestamp blob")
    raw = bytes(memoryview(blobs[0]))[:8]
    if len(raw) != 8:
        raise ProtocolError(f"T timestamp blob has {len(raw)} bytes, want 8")
    (t_ns,) = struct.unpack(">Q", raw)
    return t_ns


# -- reconnect backpressure (actions G / Y) -----------------------------------

def encode_reconnect_payload(waits_taken: int) -> bytes:
    """The adaptive client's reconnect announce (action ``G``): a tensor
    frame whose single blob is the number of hub-paced waits this client
    has already taken in the CURRENT reconnect episode, as an 8-byte
    big-endian integer.  The hub hands slot hints only to announcers at
    0 — a client that already waited is admitted, so a shed herd spreads
    exactly once instead of looping on ever-later slots."""
    return encode_tensors(
        ACTION_RECONNECT,
        [np.frombuffer(struct.pack(">Q", int(waits_taken)), np.uint8)])


def encode_retry_payload(retry_after_ms: int) -> bytes:
    """The hub's ``Y`` reply payload: one 8-byte big-endian blob carrying
    the retry-after hint in milliseconds (0 = proceed now)."""
    return encode_tensors(
        ACTION_RETRY,
        [np.frombuffer(struct.pack(">Q", int(retry_after_ms)), np.uint8)])


def decode_retry_payload(blobs: Sequence) -> int:
    """Inverse of :func:`encode_retry_payload` given the decoded blobs."""
    if not blobs:
        raise ProtocolError("Y reply carries no retry-after blob")
    raw = bytes(memoryview(blobs[0]))[:8]
    if len(raw) != 8:
        raise ProtocolError(f"Y retry-after blob has {len(raw)} bytes, want 8")
    (ms,) = struct.unpack(">Q", raw)
    return ms


def decode_reconnect_payload(blobs: Sequence) -> int:
    """Inverse of :func:`encode_reconnect_payload` -> waits already taken
    (tolerant: a malformed blob reads as 0 — backpressure must not take
    down a reconnecting worker, it just gets a slot like a fresh one)."""
    try:
        raw = bytes(memoryview(blobs[0]))[:8]
        (attempt,) = struct.unpack(">Q", raw)
        return attempt
    except (IndexError, struct.error, TypeError):
        return 0


# -- replication feed (action R) ----------------------------------------------

def encode_repl_header(clock: int, kind: int) -> np.ndarray:
    """The 9-byte R-frame header blob (u64 clock, u8 kind) as a uint8
    array — blob 0 of every replication frame, sized so the header rides
    the same fixed-schema :class:`FlatFrameCodec` as the tensor payload."""
    return np.frombuffer(struct.pack(">QB", int(clock), int(kind)), np.uint8)


def decode_repl_header(blob) -> Tuple[int, int]:
    """Inverse of :func:`encode_repl_header` -> ``(clock, kind)``."""
    raw = bytes(memoryview(blob))[:9]
    if len(raw) != 9:
        raise ProtocolError(f"R header blob has {len(raw)} bytes, want 9")
    clock, kind = struct.unpack(">QB", raw)
    return int(clock), int(kind)


def encode_repl_hello(clock: int, capabilities: int = 0) -> bytes:
    """The replica->primary handshake payload: an action-``R`` frame whose
    single blob is the hello header (the replica's current clock rides
    along for observability; the primary always full-syncs regardless).
    Nonzero ``capabilities`` (:data:`REPL_CAP_SPARSE`) appends a tenth
    byte announcing what frame kinds this standby can apply — absent
    (the pre-ISSUE-15 9-byte hello) reads as 0, the dense-only stream."""
    hdr = encode_repl_header(clock, REPL_HELLO)
    if capabilities:
        hdr = np.concatenate(
            [hdr, np.frombuffer(struct.pack(">B", int(capabilities)),
                                np.uint8)])
    return encode_tensors(ACTION_REPL, [hdr])


def decode_repl_caps(blob) -> int:
    """Capability bits of a hello header blob: the optional 10th byte,
    0 when absent (a 9-byte pre-ISSUE-15 hello = dense-only standby)."""
    raw = bytes(memoryview(blob))
    return raw[9] if len(raw) >= 10 else 0


def repl_frame_templates(center: Sequence[np.ndarray]) -> List[np.ndarray]:
    """The fixed tensor schema of a full R delta/sync frame over ``center``
    (header blob + one f32 tensor per center leaf) — feed both ends'
    :class:`FlatFrameCodec` with this so primary sends and replica receives
    move through preallocated storage."""
    return [np.zeros(9, np.uint8)] + [np.zeros(c.shape, np.float32)
                                      for c in center]


def encoded_tensors_size(arrays: Sequence[np.ndarray]) -> int:
    """Exact wire size of ``encode_tensors(action, arrays)`` — kept next to
    the encoder so senders can pre-flight size limits without duplicating
    the frame layout."""
    return 5 + sum(8 + np.asarray(a).nbytes for a in arrays)


def max_request_payload(templates: Sequence[np.ndarray],
                        sparse_leaves: Sequence[int] = ()) -> int:
    """Largest VALID request payload a hub serving ``templates`` may
    receive: per tensor the larger of the f32 blob (``4*size``) and the
    int8 ``Q`` blob (``4 + size`` — bigger for scalar leaves), floored at
    the control-frame allowance so a ``T`` announce / ``M`` health report
    fits even when the center is tiny; with sparse tables, a sparse f32
    commit touching every row additionally carries one int64 id blob per
    table.  The ONE accounting both hubs receive against — the Python
    hub's handler bound and the value ``runtime/native.py`` hands
    ``dk_ps_create`` — so a garbage length prefix is rejected identically
    by either implementation."""
    arrays = [np.asarray(t) for t in templates]
    dense = 5 + sum(8 + max(w.nbytes, 4 + w.size) for w in arrays)
    bound = max(dense, CONTROL_PAYLOAD_MAX)
    if sparse_leaves:
        bound = max(bound, dense + sum(8 + 8 * arrays[i].shape[0]
                                       for i in sparse_leaves))
    return bound


def tensor_frame_len(templates: Sequence[np.ndarray]) -> int:
    """Full on-the-wire size (8-byte header included) of one tensor frame
    carrying exactly ``templates``' payloads — the ``payload_hint`` every
    PS/client socket is tuned with (:func:`configure_socket`).  Kept next
    to the layout so the hub's accounting, the codec's ``frame_len`` and
    socket-buffer sizing can never drift apart.  Under the sharded hub
    each shard connection is hinted with ITS tensor subset, so N shard
    connections cost roughly one model's worth of kernel buffers in
    total, not N models' worth."""
    return 8 + encoded_tensors_size(templates)


class FlatFrameCodec:
    """Zero-copy tensor framing for a FIXED schema (the PS hot path).

    Both directions of the pull/commit exchange move frames whose layout
    is fully determined by the tensor templates; only the action byte and
    the tensor payloads vary per message.  So the codec derives all
    offsets once at construction:

    - **send** (:meth:`pack` + :meth:`send_packed`, or :meth:`send`): one
      frame buffer holds the prewritten frame length, tensor count, and
      per-tensor length prefixes; per message the action byte is stamped
      and each tensor is memcpy'd into its slot through a writable numpy
      view, then the whole frame leaves in a single
      ``sendall(memoryview)``.  Zero allocations, zero intermediate
      ``bytes`` — this replaces the per-tensor ``tobytes()`` + ``join``
      of the generic encoder.
    - **recv_into**: the frame is scatter-read with ``recv_into``
      directly into caller-provided preallocated arrays; prefixes land in
      a small reusable scratch and are validated against the schema.

    Wire bytes are IDENTICAL to :func:`encode_tensors`, so either end may
    be a generic peer (including the C++ hub).  Not thread-safe: one
    codec per connection/direction owner.  After any mid-frame exception
    the stream is desynchronized — drop the connection."""

    def __init__(self, templates: Sequence[np.ndarray]):
        self.templates = [np.asarray(t) for t in templates]
        self.payload_len = 5 + sum(8 + t.nbytes for t in self.templates)
        self.frame_len = 8 + self.payload_len
        self._tx = bytearray(self.frame_len)
        mv = memoryview(self._tx)
        struct.pack_into(">Q", self._tx, 0, self.payload_len)
        struct.pack_into(">I", self._tx, 9, len(self.templates))
        self._tx_slots: List[np.ndarray] = []
        pos = 13
        for t in self.templates:
            struct.pack_into(">Q", self._tx, pos, t.nbytes)
            pos += 8
            self._tx_slots.append(np.frombuffer(mv[pos:pos + t.nbytes],
                                                dtype=t.dtype))
            pos += t.nbytes
        self._tx_mv = mv
        self._scratch = memoryview(bytearray(13))

    def pack(self, action: bytes, arrays: Sequence[np.ndarray]) -> None:
        """Stamp ``action`` and memcpy each tensor into its frame slot.
        Split from :meth:`send_packed` so a server can pack under its
        center lock and send after releasing it."""
        if len(arrays) != len(self.templates):
            raise ValueError(f"got {len(arrays)} tensors, schema has "
                             f"{len(self.templates)}")
        self._tx[8:9] = action
        for slot, tmpl, a in zip(self._tx_slots, self.templates, arrays):
            a = np.asarray(a)
            if a.dtype != tmpl.dtype or a.size != tmpl.size:
                raise ValueError(f"tensor {a.dtype}[{a.size}] does not match "
                                 f"schema {tmpl.dtype}[{tmpl.size}]")
            slot[...] = a.reshape(-1)

    def send_packed(self, sock: socket.socket) -> None:
        sock.sendall(self._tx_mv)
        if obs.enabled():
            obs.counter("net_tx_frames_total").inc()
            obs.counter("net_tx_bytes_total").inc(self.frame_len)

    def send(self, sock: socket.socket, action: bytes,
             arrays: Sequence[np.ndarray]) -> None:
        self.pack(action, arrays)
        self.send_packed(sock)

    def recv_into(self, sock: socket.socket,
                  out: Sequence[np.ndarray]) -> bytes:
        """Scatter-receive one frame of this schema directly into ``out``
        (preallocated, C-contiguous, template-shaped) and return the
        action byte.  Any schema mismatch raises ``ValueError`` with the
        stream desynchronized — callers drop the connection."""
        if len(out) != len(self.templates):
            raise ValueError(f"got {len(out)} output slots, schema has "
                             f"{len(self.templates)}")
        for tmpl, dst in zip(self.templates, out):
            if dst.nbytes != tmpl.nbytes:
                raise ValueError(f"output slot of {dst.nbytes} bytes does "
                                 f"not match schema ({tmpl.nbytes} bytes)")
        # out now mirrors the schema exactly, so the shared core's
        # layout-vs-out validation IS the schema validation (and
        # limit=payload_len rejects any differently-sized frame outright)
        return _scatter_recv_into(sock, out, self._scratch,
                                  limit=self.payload_len)


class VarFrameEncoder:
    """:class:`FlatFrameCodec`'s zero-intermediate-bytes packing for frames
    whose blob count/sizes vary per message — the sparse pull/commit plane
    (actions ``S``/``V``/``U``/``X``), where each frame's row blobs are
    sized by whatever the batch touched.

    One grow-once tx buffer: per message the header, action, count and
    per-blob length prefixes are stamped in and each blob is memcpy'd into
    place, then the whole frame leaves in a single ``sendall`` — no
    per-blob ``tobytes()``, no ``join``.  Wire bytes are IDENTICAL to
    :func:`encode_tensors`, so generic peers decode these frames with the
    ordinary :func:`decode_tensor_views` path.  Not thread-safe (one
    encoder per connection owner); :meth:`pack`'s returned view aliases
    the buffer and is valid until the next pack."""

    def __init__(self, initial: int = 4096):
        self._tx = bytearray(int(initial))
        self.frame_len = 0  # of the most recent pack

    def pack(self, action: bytes, arrays: Sequence[np.ndarray]) -> memoryview:
        arrays = [np.ascontiguousarray(a) for a in arrays]
        payload = 5 + sum(8 + a.nbytes for a in arrays)
        total = 8 + payload
        if len(self._tx) < total:
            self._tx = bytearray(total)
        struct.pack_into(">Q", self._tx, 0, payload)
        self._tx[8:9] = action
        struct.pack_into(">I", self._tx, 9, len(arrays))
        mv = memoryview(self._tx)
        pos = 13
        for a in arrays:
            struct.pack_into(">Q", self._tx, pos, a.nbytes)
            pos += 8
            if a.nbytes:
                mv[pos:pos + a.nbytes] = memoryview(a).cast("B")
            pos += a.nbytes
        self.frame_len = total
        return mv[:total]

    def send(self, sock: socket.socket, action: bytes,
             arrays: Sequence[np.ndarray]) -> int:
        """Pack and send one frame; returns its full on-the-wire length."""
        frame = self.pack(action, arrays)
        sock.sendall(frame)
        if obs.enabled():
            obs.counter("net_tx_frames_total").inc()
            obs.counter("net_tx_bytes_total").inc(self.frame_len)
        return self.frame_len


def check_row_ids(ids: np.ndarray, rows: int, leaf: int) -> np.ndarray:
    """Validate one table's canonical wire row-id array: in-bounds,
    strictly ascending (sorted AND unique — what makes the fancy-indexed
    ``center[ids] += grads`` apply exact).  The ONE validation contract
    both hub implementations enforce — peers present canonical ids, the
    hub REJECTS rather than repairs (repairing would hide a desynced
    caller).  Returns ``ids`` unchanged (callers pass zero-copy views)."""
    if ids.size:
        if ids[0] < 0 or ids[-1] >= rows:
            raise ValueError(f"sparse leaf {leaf}: row ids outside "
                             f"[0, {rows})")
        if ids.size > 1 and not (np.diff(ids) > 0).all():
            raise ValueError(f"sparse leaf {leaf}: row ids must be "
                             f"sorted and unique")
    return ids


def normalize_row_ids(ids, rows: int) -> np.ndarray:
    """Canonical wire form of one sparse table's touched-row set: flat
    int64, sorted, unique, bounds-checked against the table's ``rows``.
    The sorted-unique contract is what makes the hub's fancy-indexed
    ``center[ids] += grads`` apply exact (duplicate ids would drop all
    but one addend)."""
    arr = np.unique(np.asarray(ids).ravel().astype(ROW_ID_DTYPE, copy=False))
    if arr.size and (arr[0] < 0 or arr[-1] >= rows):
        raise ValueError(f"row ids outside [0, {rows}): "
                         f"[{arr[0]}, {arr[-1]}]")
    return arr


# -- int8 commit compression (action Q blobs) ---------------------------------

def quantize_q_blob(delta: np.ndarray) -> Tuple[bytes, np.ndarray]:
    """One tensor -> (wire blob, float32 quantization residual).

    Blob = big-endian f32 scale + int8 values; residual = what rounding
    dropped, for the caller's error-feedback accumulator.  An all-zero
    delta keeps scale 1.0 so dequantization never divides by zero."""
    d = np.ascontiguousarray(delta, dtype=np.float32)
    amax = float(np.max(np.abs(d))) if d.size else 0.0
    scale = amax / 127.0 if amax > 0.0 else 1.0
    q = np.clip(np.rint(d / scale), -127, 127).astype(np.int8)
    residual = d - q.astype(np.float32) * np.float32(scale)
    return struct.pack(">f", scale) + q.tobytes(), residual


def dequantize_q_blob(blob: bytes, size: int) -> np.ndarray:
    """Inverse of :func:`quantize_q_blob`: flat float32 array of ``size``."""
    if len(blob) != 4 + size:
        raise ProtocolError(f"Q blob of {len(blob)} bytes != 4 + {size}")
    (scale,) = struct.unpack(">f", blob[:4])
    return np.frombuffer(blob, dtype=np.int8, offset=4).astype(np.float32) * np.float32(scale)


def send_tensors(sock: socket.socket, action: bytes, arrays: Sequence[np.ndarray]) -> None:
    send_frame(sock, encode_tensors(action, arrays))


def recv_tensors(sock: socket.socket, templates: Optional[Sequence[np.ndarray]] = None,
                 limit: int = MAX_FRAME,
                 out: Optional[Sequence[np.ndarray]] = None) -> Tuple[bytes, List[np.ndarray]]:
    """Receive an (action, tensors) frame.

    With ``templates`` (the out-of-band schema) the frame is scatter-read
    with ``recv_into`` DIRECTLY into the result arrays — freshly allocated
    from the templates, or the caller's preallocated ``out`` — so the
    payload is written exactly once, by the kernel, at its destination (no
    intermediate frame buffer, no per-blob slice copies).  A frame that
    does not match the template layout raises ``ValueError`` with the
    stream desynchronized — drop the connection.

    Without templates, raw ``uint8`` copies are returned (the
    control-plane path: tolerant of any tensor count/size)."""
    if templates is None and out is None:
        action, blobs = decode_tensors(recv_frame(sock, limit=limit))
        return action, [np.frombuffer(b, dtype=np.uint8) for b in blobs]
    if out is None:
        out = [np.empty(np.asarray(t).shape, np.asarray(t).dtype)
               for t in templates]
    action = _scatter_recv_into(sock, out, memoryview(bytearray(13)),
                                limit=limit)
    return action, list(out)
