"""Framed socket transport — reference parity for ``distkeras/networking.py``.

The reference framed **pickled** objects with a length prefix over TCP
(``send_data``/``recv_data``; SURVEY.md §2.12).  Pickle executes arbitrary
code at load time, so this re-design keeps the framing but replaces the
payload encodings with two safe forms:

- **JSON frames** (:func:`send_json` / :func:`recv_json`) for control-plane
  messages (job submission, PS handshakes).
- **Tensor frames** (:func:`send_tensors` / :func:`recv_tensors`) for the
  gradient plane: a 1-byte action tag + raw tensor byte blobs.  Dtype and
  shape travel out-of-band (both ends hold the model template), keeping the
  hot path a straight ``memcpy`` — this exact layout is also what the C++
  hub (``native/ps_server.cpp``) parses.

Wire format (all integers big-endian):

    frame        := u64 payload_len, payload
    json payload := utf-8 JSON bytes
    tensor payload := u8 action, u32 num_tensors,
                      num_tensors * (u64 nbytes, raw bytes)

Actions: ``P`` pull request, ``C`` commit, ``Q`` int8-compressed commit,
``B`` bye, ``W`` weights reply, ``A`` ack.

``Q`` commits carry each tensor as a 4-byte big-endian float32 scale
followed by the int8-quantized values (symmetric per-tensor:
``q = round(d / scale)``, ``scale = max|d| / 127``) — 4x fewer wire
bytes than ``C``.  The hub dequantizes and applies the SAME scaling
rules as a plain commit; workers keep the quantization residual and add
it to the next window's delta (error feedback), so the committed sum
tracks the true delta sum and compression does not bias training (the
property ``tests/test_runtime.py`` pins).  The reference always shipped
full-precision pickled weight lists (SURVEY §2.12); this is the
DCN-bandwidth headroom lever for the genuinely-async PS topology.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu import observability as obs

MAX_FRAME = 1 << 34  # 16 GiB sanity bound on a single frame

ACTION_PULL = b"P"
ACTION_COMMIT = b"C"
ACTION_QCOMMIT = b"Q"
ACTION_BYE = b"B"
ACTION_WEIGHTS = b"W"
ACTION_ACK = b"A"


def determine_host_address() -> str:
    """Best-effort routable address of this host (reference:
    ``networking.determine_host_address``).  Uses a connected UDP socket so
    no traffic is actually sent."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def connect(host: str, port: int, disable_nagle: bool = True, timeout: Optional[float] = None) -> socket.socket:
    """TCP connect (reference: ``networking.connect``); Nagle off by default —
    the PS exchange is request/response and latency-bound."""
    sock = socket.create_connection((host, port), timeout=timeout)
    if disable_nagle:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError(f"peer closed mid-frame ({got}/{n} bytes)")
        got += r
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">Q", len(payload)) + payload)
    # count only after sendall returned: a frame dropped by a dying peer
    # must not inflate the tx accounting (mirrors the rx side's contract)
    if obs.enabled():
        obs.counter("net_tx_frames_total").inc()
        obs.counter("net_tx_bytes_total").inc(8 + len(payload))


def recv_frame(sock: socket.socket, limit: int = MAX_FRAME) -> bytes:
    """Receive one frame; ``limit`` bounds the declared payload size BEFORE
    any allocation happens, so an untrusted peer can't force a huge
    ``bytearray`` with an 8-byte header (servers pass a small limit until
    the peer has authenticated)."""
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if n > limit:
        raise ValueError(f"frame of {n} bytes exceeds limit={limit}")
    payload = _recv_exact(sock, n)
    # count only after the body fully arrived: a peer dying mid-frame must
    # not inflate the byte accounting by data that never landed
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return payload


# -- control plane: JSON frames -----------------------------------------------

def send_json(sock: socket.socket, obj: Dict[str, Any]) -> None:
    send_frame(sock, json.dumps(obj).encode("utf-8"))


def recv_json(sock: socket.socket, limit: int = MAX_FRAME) -> Dict[str, Any]:
    return json.loads(recv_frame(sock, limit=limit).decode("utf-8"))


# -- gradient plane: action + raw tensor frames -------------------------------

def encode_tensors(action: bytes, arrays: Sequence[np.ndarray]) -> bytes:
    parts = [action, struct.pack(">I", len(arrays))]
    for a in arrays:
        raw = np.ascontiguousarray(a).tobytes()
        parts.append(struct.pack(">Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def decode_tensors(payload: bytes) -> Tuple[bytes, List[bytes]]:
    action = payload[0:1]
    (count,) = struct.unpack(">I", payload[1:5])
    blobs: List[bytes] = []
    off = 5
    for _ in range(count):
        (nbytes,) = struct.unpack(">Q", payload[off:off + 8])
        off += 8
        blobs.append(payload[off:off + nbytes])
        off += nbytes
    if off != len(payload):
        raise ValueError(f"tensor frame has {len(payload) - off} trailing bytes")
    return action, blobs


def encoded_tensors_size(arrays: Sequence[np.ndarray]) -> int:
    """Exact wire size of ``encode_tensors(action, arrays)`` — kept next to
    the encoder so senders can pre-flight size limits without duplicating
    the frame layout."""
    return 5 + sum(8 + np.asarray(a).nbytes for a in arrays)


# -- int8 commit compression (action Q blobs) ---------------------------------

def quantize_q_blob(delta: np.ndarray) -> Tuple[bytes, np.ndarray]:
    """One tensor -> (wire blob, float32 quantization residual).

    Blob = big-endian f32 scale + int8 values; residual = what rounding
    dropped, for the caller's error-feedback accumulator.  An all-zero
    delta keeps scale 1.0 so dequantization never divides by zero."""
    d = np.ascontiguousarray(delta, dtype=np.float32)
    amax = float(np.max(np.abs(d))) if d.size else 0.0
    scale = amax / 127.0 if amax > 0.0 else 1.0
    q = np.clip(np.rint(d / scale), -127, 127).astype(np.int8)
    residual = d - q.astype(np.float32) * np.float32(scale)
    return struct.pack(">f", scale) + q.tobytes(), residual


def dequantize_q_blob(blob: bytes, size: int) -> np.ndarray:
    """Inverse of :func:`quantize_q_blob`: flat float32 array of ``size``."""
    if len(blob) != 4 + size:
        raise ValueError(f"Q blob of {len(blob)} bytes != 4 + {size}")
    (scale,) = struct.unpack(">f", blob[:4])
    return np.frombuffer(blob, dtype=np.int8, offset=4).astype(np.float32) * np.float32(scale)


def send_tensors(sock: socket.socket, action: bytes, arrays: Sequence[np.ndarray]) -> None:
    send_frame(sock, encode_tensors(action, arrays))


def recv_tensors(sock: socket.socket, templates: Optional[Sequence[np.ndarray]] = None,
                 limit: int = MAX_FRAME) -> Tuple[bytes, List[np.ndarray]]:
    """Receive an (action, tensors) frame.  With ``templates``, each blob is
    reinterpreted with the template's dtype/shape (the out-of-band schema);
    without, raw ``uint8`` arrays are returned."""
    action, blobs = decode_tensors(recv_frame(sock, limit=limit))
    if templates is None:
        return action, [np.frombuffer(b, dtype=np.uint8) for b in blobs]
    if len(blobs) != len(templates):
        raise ValueError(f"got {len(blobs)} tensors, template has {len(templates)}")
    out = []
    for blob, tmpl in zip(blobs, templates):
        t = np.asarray(tmpl)
        arr = np.frombuffer(blob, dtype=t.dtype)
        if arr.size != t.size:
            raise ValueError(f"tensor size {arr.size} != template size {t.size}")
        out.append(arr.reshape(t.shape))
    return action, out
