"""Framed socket transport — reference parity for ``distkeras/networking.py``.

The reference framed **pickled** objects with a length prefix over TCP
(``send_data``/``recv_data``; SURVEY.md §2.12).  Pickle executes arbitrary
code at load time, so this re-design keeps the framing but replaces the
payload encodings with two safe forms:

- **JSON frames** (:func:`send_json` / :func:`recv_json`) for control-plane
  messages (job submission, PS handshakes).
- **Tensor frames** (:func:`send_tensors` / :func:`recv_tensors`) for the
  gradient plane: a 1-byte action tag + raw tensor byte blobs.  Dtype and
  shape travel out-of-band (both ends hold the model template), keeping the
  hot path a straight ``memcpy`` — this exact layout is also what the C++
  hub (``native/ps_server.cpp``) parses.

Wire format (all integers big-endian):

    frame        := u64 payload_len, payload
    json payload := utf-8 JSON bytes
    tensor payload := u8 action, u32 num_tensors,
                      num_tensors * (u64 nbytes, raw bytes)

Actions: ``P`` pull request, ``C`` commit, ``Q`` int8-compressed commit,
``B`` bye, ``W`` weights reply, ``A`` ack.

Two implementations move tensor frames:

- the **generic path** (:func:`send_tensors` / :func:`recv_tensors`) builds
  and parses frames ad hoc — control plane, tests, peers without a shared
  schema;
- the **flat path** (:class:`FlatFrameCodec`, :func:`recv_frame_into`,
  :func:`decode_tensor_views`) moves the SAME bytes through preallocated
  storage for connections with a fixed tensor schema (the PS pull/commit
  hot loop): the send frame is built once with every constant byte
  prewritten and per message only the action byte and tensor payloads are
  stamped in (one ``memcpy`` per tensor, zero intermediate ``bytes``),
  while receives scatter straight into the caller's arrays with
  ``recv_into`` — the payload is written exactly once, by the kernel, at
  its final destination.  Wire bytes are identical between the two paths,
  so the C++ hub and pre-existing peers interoperate unchanged.

``Q`` commits carry each tensor as a 4-byte big-endian float32 scale
followed by the int8-quantized values (symmetric per-tensor:
``q = round(d / scale)``, ``scale = max|d| / 127``) — 4x fewer wire
bytes than ``C``.  The hub dequantizes and applies the SAME scaling
rules as a plain commit; workers keep the quantization residual and add
it to the next window's delta (error feedback), so the committed sum
tracks the true delta sum and compression does not bias training (the
property ``tests/test_runtime.py`` pins).  The reference always shipped
full-precision pickled weight lists (SURVEY §2.12); this is the
DCN-bandwidth headroom lever for the genuinely-async PS topology.
"""

from __future__ import annotations

import json
import mmap
import os
import socket
import struct
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu import observability as obs

MAX_FRAME = 1 << 34  # 16 GiB sanity bound on a single frame

ACTION_PULL = b"P"
ACTION_COMMIT = b"C"
ACTION_QCOMMIT = b"Q"
ACTION_BYE = b"B"
ACTION_WEIGHTS = b"W"
ACTION_ACK = b"A"
ACTION_PING = b"H"  # client heartbeat-on-idle; hub replies with an ack
# trace-context announce: one JSON blob (job_id/worker_id/span_id); the hub
# remembers the context for this connection's spans and replies with an
# action-T frame carrying one 8-byte big-endian blob = the hub's monotonic
# clock in ns (the NTP-style sample the client's offset estimate is built
# from).  Sent only when distributed tracing is configured, so pre-T hubs
# never see it (the PR 3/4 convention: wire bytes of every pre-existing
# frame are unchanged, new frames are opt-in).
ACTION_TRACE = b"T"
# health-report push (live fleet health plane, ISSUE 8): a worker
# periodically sends one M frame carrying a single JSON blob — its
# compact per-worker metric report (windows, rolling window wall,
# reconnect/failover totals) — which the hub folds into the process
# HealthCollector and acks (the ack coalesces into later receives like a
# commit ack, so reports ride the pipelined FIFO instead of their own
# round trip).  Opt-in like ``T``: no M frame ever moves unless the
# trainer sets ``health_interval_s``, so pre-M peers interoperate
# byte-identically.
ACTION_HEALTH = b"M"
# receive-bound allowance for control-plane frames (the single-JSON-blob
# payloads of actions T and M, whose size derives from report contents,
# not from the model): the hub receives against
# max(largest tensor frame, CONTROL_PAYLOAD_MAX), so a verbose health
# report fits even on a tiny center while a garbage length prefix still
# cannot conjure more than ~64 KiB
CONTROL_PAYLOAD_MAX = 64 * 1024
# hub-to-hub replication feed (hot-standby HA): a replica hub announces
# itself to its primary with an R "hello" frame (one 9-byte header blob);
# the primary replies on the same connection with one R full-sync frame
# (header + the whole center at one clock) and thereafter streams one R
# delta frame per APPLIED commit (header + the post-aggregation scaled
# delta), sent BEFORE the committing worker's ack leaves — see
# ``encode_repl_header``.  Opt-in like ``T``: no R frame ever moves unless
# a replica connects, so pre-R peers interoperate byte-identically.
ACTION_REPL = b"R"

# R-frame header kinds (first blob, 9 bytes big-endian: u64 clock, u8 kind)
REPL_DELTA = 0  # primary->replica: blobs[1:] = scaled applied delta
REPL_SYNC = 1   # primary->replica: blobs[1:] = full center at `clock`
REPL_HELLO = 2  # replica->primary: no tensor blobs; `clock` = replica's clock
# sparse row-delta frame (hyperscale embedding tier, ISSUE 15): blobs[1:]
# carry the applied commit in the U-commit layout — per center leaf in
# template order, one full f32 delta blob for dense leaves and TWO blobs
# (int64 row ids, f32 [k, dim] scaled row deltas) for sparse leaves — so
# replication cost is proportional to the touched rows, not the model.
# The standby applies ``center[ids] += delta`` behind the same clock
# fence as a dense delta.  A primary sends these ONLY to replicas whose
# hello announced REPL_CAP_SPARSE (attach-time capability): a legacy
# standby keeps receiving the dense-materialized REPL_DELTA stream, so
# an old-generation standby attached to a new primary is never handed a
# frame kind it cannot parse
REPL_SPARSE = 3

# hello capability bits (optional 10th byte of the hello header blob —
# a 9-byte hello reads as capabilities 0, and a pre-ISSUE-15 primary
# slices the first 9 bytes off a 10-byte hello, so both directions of
# version skew degrade to the dense stream instead of a torn one)
REPL_CAP_SPARSE = 1

# row-sparse embedding traffic (ISSUE 9): a worker whose model declares
# EmbeddingTable leaves (shape [rows, dim], registered as ``sparse_leaves``
# on both ends) exchanges only the rows a batch touches —
#
#   ``S`` sparse pull request: one int64 sorted-unique row-id blob per
#         sparse table (ascending leaf order); dense leaves need no
#         request payload, they always ride the reply whole.
#   ``V`` sparse weights reply: one blob per CENTER LEAF in template
#         order — the full leaf (f32) for dense leaves, the requested
#         ``[k, dim]`` row block (f32) for sparse leaves.
#   ``U`` sparse f32 commit: per leaf in template order — one full f32
#         delta blob for dense leaves, TWO blobs (int64 row ids, f32
#         ``[k, dim]`` row grads) for sparse leaves.
#   ``X`` sparse int8 commit: same layout with every value blob carried
#         as a ``Q`` blob (be-f32 scale + int8 values; the row block is
#         quantized as one unit).
#
# Row ids are int64 in native byte order — the same raw-tensor-bytes
# convention every other blob uses — sorted and unique, so the hub's
# ``center[ids] += rows`` apply is race-free under its lock.  Opt-in like
# ``T``/``M``/``R``: no S/V/U/X frame ever moves unless BOTH ends declare
# sparse tables, so every pre-existing frame stays byte-identical and
# un-upgraded peers interoperate unchanged.
ACTION_SPARSE_PULL = b"S"
ACTION_SPARSE_WEIGHTS = b"V"
ACTION_SPARSE_COMMIT = b"U"
ACTION_SPARSE_QCOMMIT = b"X"

# reconnect-storm backpressure (ISSUE 10): an ADAPTIVE client announces
# every reconnect with a ``G`` frame (one 8-byte big-endian blob — the
# hub-paced waits it has ALREADY taken this reconnect episode) as the
# FIRST frame on the fresh connection; the hub replies with a ``Y`` frame
# carrying a retry-after hint in milliseconds (one 8-byte big-endian
# blob).  Hint 0 means proceed; a positive hint asks the client to close,
# wait that long, and redial — the hub hands each member of a thundering
# herd a LATER slot instead of absorbing the whole herd at once, and an
# announcer that already waited its slot (blob > 0) is admitted, so every
# client waits at most once per storm.  Opt-in like ``T``/``M``: no G
# frame ever moves unless the client was constructed with
# ``adaptive=True``, so every pre-existing frame stays byte-identical and
# un-upgraded clients keep plain exponential backoff.
ACTION_RECONNECT = b"G"
ACTION_RETRY = b"Y"

# shared-memory transport attach (zero-copy same-host path, ISSUE 18): a
# client constructed with ``shm=True`` sends one ``Z`` request (one blob:
# u8 version, u64 big-endian ring-capacity hint) right after its optional
# ``T`` announce; the hub replies with a ``Z`` frame carrying TWO path
# blobs (client->hub ring file, hub->client ring file) or ZERO blobs (a
# decline — different host, shm disabled, unsupported version).  On an
# offer the client mmaps both rings and sends one ``Z`` confirm over TCP
# (one blob: ``b"\x01"`` attached / ``b"\x00"`` abort); only after the
# hub reads an attached confirm do BOTH ends switch the very next frame
# onto the rings — the TCP FIFO makes the switch point exact, so the
# stream is never torn (``analysis/protocol_model.py`` walks this
# three-step handshake exhaustively).  The rings carry the SAME framed
# bytes as the socket, so trajectories are bit-identical and every
# recording-socket pin keeps holding.  Opt-in like ``T``/``M``/``G``: no
# Z frame ever moves unless the client asked for shm, so every
# pre-existing frame stays byte-identical and un-upgraded peers
# interoperate unchanged; a legacy hub closing on the unknown action
# reads as a decline and the client redials plain TCP.
ACTION_SHM = b"Z"

SHM_VERSION = 1  # bumped only if the ring layout changes incompatibly

ROW_ID_DTYPE = np.dtype(np.int64)


class ProtocolError(ValueError):
    """A frame violated the wire contract: garbage/oversized length prefix,
    truncated payload, tensor layout that does not match the schema.  After
    one of these the stream is desynchronized — callers must drop (and may
    re-establish) the connection.  Subclasses ``ValueError`` so every
    pre-existing ``except ValueError`` stays correct; the distinct type
    lets resilience layers (PSClient reconnect, hub eviction) treat
    malformed bytes as a connection fault rather than a caller bug."""


def determine_host_address() -> str:
    """Best-effort routable address of this host (reference:
    ``networking.determine_host_address``).  Uses a connected UDP socket so
    no traffic is actually sent."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


MIN_SOCKET_BUF = 64 << 10   # floor for SO_SNDBUF/SO_RCVBUF requests
MAX_SOCKET_BUF = 8 << 20    # cap — beyond one large frame, memory not speed


def configure_socket(sock: socket.socket, payload_hint: Optional[int] = None,
                     nodelay: bool = True, quickack: bool = False) -> None:
    """Hot-path tuning applied to BOTH ends of every PS/client connection.

    - ``TCP_NODELAY``: the exchange is strictly request/response, so Nagle
      buys nothing and its interaction with delayed acks can park the
      13-byte ack/pull frames for tens of milliseconds — longer than an
      entire training window.
    - ``SO_SNDBUF``/``SO_RCVBUF`` sized to ``payload_hint`` (one full
      weights/commit frame, clamped to [64 KiB, 8 MiB]): a pipelined
      sender must be able to park a whole commit in the kernel and return
      to compute instead of blocking in ``sendall`` at the default buffer
      size.  Best-effort — the kernel may clamp further.  Without a hint
      the kernel defaults stand (control-plane connections don't need
      frame-sized buffers).
    - ``TCP_QUICKACK`` (opt-in, Linux-only, best-effort): the hub sets it
      on accepted connections so its coalesced 13-byte acks leave
      immediately instead of riding the delayed-ack timer — acks are the
      one latency-critical tiny send left on the pipelined commit path.
      Purely a kernel-timing knob: wire BYTES are unchanged (pinned by a
      recording-socket test), and platforms without the option silently
      keep delayed acks."""
    if nodelay:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    if quickack:
        try:
            sock.setsockopt(socket.IPPROTO_TCP,
                            getattr(socket, "TCP_QUICKACK"), 1)
        except (AttributeError, OSError):
            pass  # non-Linux / kernel policy; delayed acks still correct
    if payload_hint is None:
        return
    size = max(MIN_SOCKET_BUF, min(int(payload_hint) + 4096, MAX_SOCKET_BUF))
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, size)
        except OSError:
            pass  # kernel policy may forbid resizing; defaults still work


def connect(host: str, port: int, disable_nagle: bool = True,
            timeout: Optional[float] = None,
            payload_hint: Optional[int] = None) -> socket.socket:
    """TCP connect (reference: ``networking.connect``); Nagle off by default —
    the PS exchange is request/response and latency-bound.  ``payload_hint``
    sizes the kernel buffers to the frame this connection will move."""
    sock = socket.create_connection((host, port), timeout=timeout)
    configure_socket(sock, payload_hint=payload_hint, nodelay=disable_nagle)
    return sock


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` completely from the socket (zero-copy receive)."""
    got, n = 0, view.nbytes
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError(f"peer closed mid-frame ({got}/{n} bytes)")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">Q", len(payload)) + payload)
    # count only after sendall returned: a frame dropped by a dying peer
    # must not inflate the tx accounting (mirrors the rx side's contract)
    if obs.enabled():
        obs.counter("net_tx_frames_total").inc()
        obs.counter("net_tx_bytes_total").inc(8 + len(payload))


def recv_frame(sock: socket.socket, limit: int = MAX_FRAME) -> bytes:
    """Receive one frame; ``limit`` bounds the declared payload size BEFORE
    any allocation happens, so an untrusted peer can't force a huge
    ``bytearray`` with an 8-byte header (servers pass a small limit until
    the peer has authenticated)."""
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if n > limit:
        raise ProtocolError(f"frame of {n} bytes exceeds limit={limit}")
    payload = _recv_exact(sock, n)
    # count only after the body fully arrived: a peer dying mid-frame must
    # not inflate the byte accounting by data that never landed
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return payload


def recv_frame_into(sock: socket.socket, buf: bytearray,
                    limit: int = MAX_FRAME) -> memoryview:
    """Receive one frame into the reusable ``buf`` (grown once to the
    largest frame seen, then steady-state zero-allocation), returning a
    memoryview of exactly the payload bytes.  The view aliases ``buf`` —
    it is valid only until the next call.  This is the long-lived-
    connection receive: the PS hub's handler loop reads every request
    through one of these per connection."""
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if n > limit:
        raise ProtocolError(f"frame of {n} bytes exceeds limit={limit}")
    if len(buf) < n:
        try:
            buf.extend(bytes(n - len(buf)))
        except BufferError:
            # live views of the previous frame pin the caller's buffer
            # (bytearray cannot resize with exports outstanding); receive
            # this oversized frame into a fresh buffer instead — the
            # caller's steady-state buffer is simply not grown this time
            buf = bytearray(n)
    mv = memoryview(buf)[:n]
    _recv_exact_into(sock, mv)
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return mv


def send_raw_frame(sock: socket.socket, frame: bytes) -> None:
    """Send an already-framed byte string (8-byte header included) — for
    prebuilt constant frames (acks, pull requests) on the hot path."""
    sock.sendall(frame)
    if obs.enabled():
        obs.counter("net_tx_frames_total").inc()
        obs.counter("net_tx_bytes_total").inc(len(frame))


# -- control plane: JSON frames -----------------------------------------------

def send_json(sock: socket.socket, obj: Dict[str, Any]) -> None:
    send_frame(sock, json.dumps(obj).encode("utf-8"))


def recv_json(sock: socket.socket, limit: int = MAX_FRAME) -> Dict[str, Any]:
    return json.loads(recv_frame(sock, limit=limit).decode("utf-8"))


# -- gradient plane: action + raw tensor frames -------------------------------

def encode_tensors(action: bytes, arrays: Sequence[np.ndarray]) -> bytes:
    parts = [action, struct.pack(">I", len(arrays))]
    for a in arrays:
        raw = np.ascontiguousarray(a).tobytes()
        parts.append(struct.pack(">Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def decode_tensors(payload: bytes) -> Tuple[bytes, List[bytes]]:
    action = payload[0:1]
    (count,) = struct.unpack(">I", payload[1:5])
    blobs: List[bytes] = []
    off = 5
    for _ in range(count):
        (nbytes,) = struct.unpack(">Q", payload[off:off + 8])
        off += 8
        blobs.append(payload[off:off + nbytes])
        off += nbytes
    if off != len(payload):
        raise ProtocolError(f"tensor frame has {len(payload) - off} trailing bytes")
    return action, blobs


def decode_tensor_views(payload) -> Tuple[bytes, List[memoryview]]:
    """:func:`decode_tensors` without the copies: blobs come back as
    memoryview slices into ``payload`` (pass the ``recv_frame_into`` view
    directly).  The views alias the receive buffer — decode/apply them
    before the next frame lands."""
    mv = payload if isinstance(payload, memoryview) else memoryview(payload)
    action = bytes(mv[0:1])
    (count,) = struct.unpack(">I", mv[1:5])
    blobs: List[memoryview] = []
    off = 5
    for _ in range(count):
        (nbytes,) = struct.unpack(">Q", mv[off:off + 8])
        off += 8
        if off + nbytes > len(mv):
            raise ProtocolError("tensor frame truncated mid-blob")
        blobs.append(mv[off:off + nbytes])
        off += nbytes
    if off != len(mv):
        raise ProtocolError(f"tensor frame has {len(mv) - off} trailing bytes")
    return action, blobs


def _scatter_recv_into(sock: socket.socket, out: Sequence[np.ndarray],
                       scratch: memoryview, limit: int) -> bytes:
    """The one scatter-receive core (shared by ``FlatFrameCodec.recv_into``
    and the templated ``recv_tensors`` path, so their frame validation can
    never drift apart): read one tensor frame whose layout must match
    ``out`` exactly — prefixes land in the 13-byte ``scratch``, payloads
    land in ``out`` via ``recv_into`` — and return the action byte.  Any
    mismatch raises ``ValueError`` with the stream desynchronized."""
    _recv_exact_into(sock, scratch[:8])
    (n,) = struct.unpack(">Q", scratch[:8])
    if n > limit:
        raise ProtocolError(f"frame of {n} bytes exceeds limit={limit}")
    expected = 5 + sum(8 + a.nbytes for a in out)
    if n != expected:
        raise ProtocolError(f"tensor frame of {n} payload bytes does not match "
                         f"the expected layout ({expected} bytes)")
    _recv_exact_into(sock, scratch[:5])
    action = bytes(scratch[:1])
    (count,) = struct.unpack(">I", scratch[1:5])
    if count != len(out):
        raise ProtocolError(f"frame has {count} tensors, expected {len(out)}")
    for dst in out:
        _recv_exact_into(sock, scratch[:8])
        (nbytes,) = struct.unpack(">Q", scratch[:8])
        if nbytes != dst.nbytes or not dst.flags.c_contiguous:
            raise ProtocolError(f"tensor of {nbytes} bytes does not match its "
                             f"output slot ({dst.nbytes} bytes, contiguous)")
        if nbytes:
            # zero-byte blobs are legal (an all-hit hot-tier pull, an
            # untouched per-table id set) and an empty ndarray cannot be
            # cast to a flat memoryview
            _recv_exact_into(sock, memoryview(dst).cast("B"))
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return action


def empty_tensor_frame(action: bytes) -> bytes:
    """The complete 13-byte frame of a tensor-less message (pull request,
    ack, bye) — header included, built once and reused via
    :func:`send_raw_frame`."""
    return struct.pack(">Q", 5) + action + struct.pack(">I", 0)


def recv_action(sock: socket.socket) -> bytes:
    """Receive a frame known to carry zero tensors (the ack/control leg of
    the pipelined client) and return its action byte."""
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if n != 5:
        raise ProtocolError(f"expected a tensor-less frame, got {n}-byte payload")
    payload = _recv_exact(sock, 5)
    (count,) = struct.unpack(">I", payload[1:5])
    if count != 0:
        raise ProtocolError(f"expected zero tensors, frame declares {count}")
    if obs.enabled():
        obs.counter("net_rx_frames_total").inc()
        obs.counter("net_rx_bytes_total").inc(8 + n)
    return payload[0:1]


# -- trace-context announce (action T) ----------------------------------------

def encode_context_payload(context_json: bytes) -> bytes:
    """The client->hub trace-context announce payload: an action-``T``
    tensor frame whose single blob is the UTF-8 JSON encoding of the
    announcing worker's :class:`~distkeras_tpu.observability.distributed.
    TraceContext`."""
    return encode_tensors(ACTION_TRACE, [np.frombuffer(context_json, np.uint8)])


def encode_health_payload(report_json: bytes) -> bytes:
    """The worker->hub health-report payload (action ``M``): a tensor
    frame whose single blob is the UTF-8 JSON report the
    :class:`~distkeras_tpu.observability.health.HealthCollector`
    ingests."""
    return encode_tensors(ACTION_HEALTH,
                          [np.frombuffer(report_json, np.uint8)])


def encode_time_payload(t_ns: int) -> bytes:
    """The hub->client ``T`` reply payload: one 8-byte big-endian blob
    carrying the hub's monotonic clock in nanoseconds."""
    return ACTION_TRACE + struct.pack(">I", 1) + struct.pack(">Q", 8) \
        + struct.pack(">Q", t_ns)


def decode_time_payload(blobs: Sequence) -> int:
    """Inverse of :func:`encode_time_payload` given the decoded blob list."""
    if not blobs:
        raise ProtocolError("T reply carries no timestamp blob")
    raw = bytes(memoryview(blobs[0]))[:8]
    if len(raw) != 8:
        raise ProtocolError(f"T timestamp blob has {len(raw)} bytes, want 8")
    (t_ns,) = struct.unpack(">Q", raw)
    return t_ns


def encode_admission_payload(t_ns: int, admitted: bool,
                             reason: str = "") -> bytes:
    """The hub->client ``T`` reply to a job-scoped announce (ISSUE 19):
    a tensor frame whose single blob is the UTF-8 JSON admission verdict
    ``{"t", "admitted", "reason"}``.  Only sent to a client that put a
    ``job_ns`` key on its announce — a plain trace announce keeps the
    8-byte :func:`encode_time_payload` reply, byte-identical to HEAD."""
    doc = json.dumps({"t": int(t_ns), "admitted": bool(admitted),
                      "reason": reason}).encode("utf-8")
    return encode_tensors(ACTION_TRACE, [np.frombuffer(doc, np.uint8)])


def decode_admission_payload(blobs: Sequence) -> Tuple[int, bool, str]:
    """Inverse of :func:`encode_admission_payload` given the decoded blob
    list: ``(t_ns, admitted, reason)``."""
    if not blobs:
        raise ProtocolError("T admission reply carries no blob")
    try:
        doc = json.loads(bytes(memoryview(blobs[0])).decode("utf-8"))
        return int(doc["t"]), bool(doc["admitted"]), str(doc.get("reason", ""))
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as ex:
        raise ProtocolError(f"malformed T admission reply: {ex}")


# -- reconnect backpressure (actions G / Y) -----------------------------------

def encode_reconnect_payload(waits_taken: int) -> bytes:
    """The adaptive client's reconnect announce (action ``G``): a tensor
    frame whose single blob is the number of hub-paced waits this client
    has already taken in the CURRENT reconnect episode, as an 8-byte
    big-endian integer.  The hub hands slot hints only to announcers at
    0 — a client that already waited is admitted, so a shed herd spreads
    exactly once instead of looping on ever-later slots."""
    return encode_tensors(
        ACTION_RECONNECT,
        [np.frombuffer(struct.pack(">Q", int(waits_taken)), np.uint8)])


def encode_retry_payload(retry_after_ms: int) -> bytes:
    """The hub's ``Y`` reply payload: one 8-byte big-endian blob carrying
    the retry-after hint in milliseconds (0 = proceed now)."""
    return encode_tensors(
        ACTION_RETRY,
        [np.frombuffer(struct.pack(">Q", int(retry_after_ms)), np.uint8)])


def decode_retry_payload(blobs: Sequence) -> int:
    """Inverse of :func:`encode_retry_payload` given the decoded blobs."""
    if not blobs:
        raise ProtocolError("Y reply carries no retry-after blob")
    raw = bytes(memoryview(blobs[0]))[:8]
    if len(raw) != 8:
        raise ProtocolError(f"Y retry-after blob has {len(raw)} bytes, want 8")
    (ms,) = struct.unpack(">Q", raw)
    return ms


def decode_reconnect_payload(blobs: Sequence) -> int:
    """Inverse of :func:`encode_reconnect_payload` -> waits already taken
    (tolerant: a malformed blob reads as 0 — backpressure must not take
    down a reconnecting worker, it just gets a slot like a fresh one)."""
    try:
        raw = bytes(memoryview(blobs[0]))[:8]
        (attempt,) = struct.unpack(">Q", raw)
        return attempt
    except (IndexError, struct.error, TypeError):
        return 0


# -- replication feed (action R) ----------------------------------------------

def encode_repl_header(clock: int, kind: int) -> np.ndarray:
    """The 9-byte R-frame header blob (u64 clock, u8 kind) as a uint8
    array — blob 0 of every replication frame, sized so the header rides
    the same fixed-schema :class:`FlatFrameCodec` as the tensor payload."""
    return np.frombuffer(struct.pack(">QB", int(clock), int(kind)), np.uint8)


def decode_repl_header(blob) -> Tuple[int, int]:
    """Inverse of :func:`encode_repl_header` -> ``(clock, kind)``."""
    raw = bytes(memoryview(blob))[:9]
    if len(raw) != 9:
        raise ProtocolError(f"R header blob has {len(raw)} bytes, want 9")
    clock, kind = struct.unpack(">QB", raw)
    return int(clock), int(kind)


def encode_repl_hello(clock: int, capabilities: int = 0) -> bytes:
    """The replica->primary handshake payload: an action-``R`` frame whose
    single blob is the hello header (the replica's current clock rides
    along for observability; the primary always full-syncs regardless).
    Nonzero ``capabilities`` (:data:`REPL_CAP_SPARSE`) appends a tenth
    byte announcing what frame kinds this standby can apply — absent
    (the pre-ISSUE-15 9-byte hello) reads as 0, the dense-only stream."""
    hdr = encode_repl_header(clock, REPL_HELLO)
    if capabilities:
        hdr = np.concatenate(
            [hdr, np.frombuffer(struct.pack(">B", int(capabilities)),
                                np.uint8)])
    return encode_tensors(ACTION_REPL, [hdr])


def decode_repl_caps(blob) -> int:
    """Capability bits of a hello header blob: the optional 10th byte,
    0 when absent (a 9-byte pre-ISSUE-15 hello = dense-only standby)."""
    raw = bytes(memoryview(blob))
    return raw[9] if len(raw) >= 10 else 0


def repl_frame_templates(center: Sequence[np.ndarray]) -> List[np.ndarray]:
    """The fixed tensor schema of a full R delta/sync frame over ``center``
    (header blob + one f32 tensor per center leaf) — feed both ends'
    :class:`FlatFrameCodec` with this so primary sends and replica receives
    move through preallocated storage."""
    return [np.zeros(9, np.uint8)] + [np.zeros(c.shape, np.float32)
                                      for c in center]


def encoded_tensors_size(arrays: Sequence[np.ndarray]) -> int:
    """Exact wire size of ``encode_tensors(action, arrays)`` — kept next to
    the encoder so senders can pre-flight size limits without duplicating
    the frame layout."""
    return 5 + sum(8 + np.asarray(a).nbytes for a in arrays)


def max_request_payload(templates: Sequence[np.ndarray],
                        sparse_leaves: Sequence[int] = ()) -> int:
    """Largest VALID request payload a hub serving ``templates`` may
    receive: per tensor the larger of the f32 blob (``4*size``) and the
    int8 ``Q`` blob (``4 + size`` — bigger for scalar leaves), floored at
    the control-frame allowance so a ``T`` announce / ``M`` health report
    fits even when the center is tiny; with sparse tables, a sparse f32
    commit touching every row additionally carries one int64 id blob per
    table.  The ONE accounting both hubs receive against — the Python
    hub's handler bound and the value ``runtime/native.py`` hands
    ``dk_ps_create`` — so a garbage length prefix is rejected identically
    by either implementation."""
    arrays = [np.asarray(t) for t in templates]
    dense = 5 + sum(8 + max(w.nbytes, 4 + w.size) for w in arrays)
    bound = max(dense, CONTROL_PAYLOAD_MAX)
    if sparse_leaves:
        bound = max(bound, dense + sum(8 + 8 * arrays[i].shape[0]
                                       for i in sparse_leaves))
    return bound


def tensor_frame_len(templates: Sequence[np.ndarray]) -> int:
    """Full on-the-wire size (8-byte header included) of one tensor frame
    carrying exactly ``templates``' payloads — the ``payload_hint`` every
    PS/client socket is tuned with (:func:`configure_socket`).  Kept next
    to the layout so the hub's accounting, the codec's ``frame_len`` and
    socket-buffer sizing can never drift apart.  Under the sharded hub
    each shard connection is hinted with ITS tensor subset, so N shard
    connections cost roughly one model's worth of kernel buffers in
    total, not N models' worth."""
    return 8 + encoded_tensors_size(templates)


class FlatFrameCodec:
    """Zero-copy tensor framing for a FIXED schema (the PS hot path).

    Both directions of the pull/commit exchange move frames whose layout
    is fully determined by the tensor templates; only the action byte and
    the tensor payloads vary per message.  So the codec derives all
    offsets once at construction:

    - **send** (:meth:`pack` + :meth:`send_packed`, or :meth:`send`): one
      frame buffer holds the prewritten frame length, tensor count, and
      per-tensor length prefixes; per message the action byte is stamped
      and each tensor is memcpy'd into its slot through a writable numpy
      view, then the whole frame leaves in a single
      ``sendall(memoryview)``.  Zero allocations, zero intermediate
      ``bytes`` — this replaces the per-tensor ``tobytes()`` + ``join``
      of the generic encoder.
    - **recv_into**: the frame is scatter-read with ``recv_into``
      directly into caller-provided preallocated arrays; prefixes land in
      a small reusable scratch and are validated against the schema.

    Wire bytes are IDENTICAL to :func:`encode_tensors`, so either end may
    be a generic peer (including the C++ hub).  Not thread-safe: one
    codec per connection/direction owner.  After any mid-frame exception
    the stream is desynchronized — drop the connection."""

    def __init__(self, templates: Sequence[np.ndarray]):
        self.templates = [np.asarray(t) for t in templates]
        self.payload_len = 5 + sum(8 + t.nbytes for t in self.templates)
        self.frame_len = 8 + self.payload_len
        self._tx = bytearray(self.frame_len)
        mv = memoryview(self._tx)
        struct.pack_into(">Q", self._tx, 0, self.payload_len)
        struct.pack_into(">I", self._tx, 9, len(self.templates))
        self._tx_slots: List[np.ndarray] = []
        pos = 13
        for t in self.templates:
            struct.pack_into(">Q", self._tx, pos, t.nbytes)
            pos += 8
            self._tx_slots.append(np.frombuffer(mv[pos:pos + t.nbytes],
                                                dtype=t.dtype))
            pos += t.nbytes
        self._tx_mv = mv
        self._scratch = memoryview(bytearray(13))

    def pack(self, action: bytes, arrays: Sequence[np.ndarray]) -> None:
        """Stamp ``action`` and memcpy each tensor into its frame slot.
        Split from :meth:`send_packed` so a server can pack under its
        center lock and send after releasing it."""
        if len(arrays) != len(self.templates):
            raise ValueError(f"got {len(arrays)} tensors, schema has "
                             f"{len(self.templates)}")
        self._tx[8:9] = action
        for slot, tmpl, a in zip(self._tx_slots, self.templates, arrays):
            a = np.asarray(a)
            if a.dtype != tmpl.dtype or a.size != tmpl.size:
                raise ValueError(f"tensor {a.dtype}[{a.size}] does not match "
                                 f"schema {tmpl.dtype}[{tmpl.size}]")
            slot[...] = a.reshape(-1)

    def send_packed(self, sock: socket.socket) -> None:
        sock.sendall(self._tx_mv)
        if obs.enabled():
            obs.counter("net_tx_frames_total").inc()
            obs.counter("net_tx_bytes_total").inc(self.frame_len)

    def send(self, sock: socket.socket, action: bytes,
             arrays: Sequence[np.ndarray]) -> None:
        self.pack(action, arrays)
        self.send_packed(sock)

    def recv_into(self, sock: socket.socket,
                  out: Sequence[np.ndarray]) -> bytes:
        """Scatter-receive one frame of this schema directly into ``out``
        (preallocated, C-contiguous, template-shaped) and return the
        action byte.  Any schema mismatch raises ``ValueError`` with the
        stream desynchronized — callers drop the connection."""
        if len(out) != len(self.templates):
            raise ValueError(f"got {len(out)} output slots, schema has "
                             f"{len(self.templates)}")
        for tmpl, dst in zip(self.templates, out):
            if dst.nbytes != tmpl.nbytes:
                raise ValueError(f"output slot of {dst.nbytes} bytes does "
                                 f"not match schema ({tmpl.nbytes} bytes)")
        # out now mirrors the schema exactly, so the shared core's
        # layout-vs-out validation IS the schema validation (and
        # limit=payload_len rejects any differently-sized frame outright)
        return _scatter_recv_into(sock, out, self._scratch,
                                  limit=self.payload_len)


class VarFrameEncoder:
    """:class:`FlatFrameCodec`'s zero-intermediate-bytes packing for frames
    whose blob count/sizes vary per message — the sparse pull/commit plane
    (actions ``S``/``V``/``U``/``X``), where each frame's row blobs are
    sized by whatever the batch touched.

    One grow-once tx buffer: per message the header, action, count and
    per-blob length prefixes are stamped in and each blob is memcpy'd into
    place, then the whole frame leaves in a single ``sendall`` — no
    per-blob ``tobytes()``, no ``join``.  Wire bytes are IDENTICAL to
    :func:`encode_tensors`, so generic peers decode these frames with the
    ordinary :func:`decode_tensor_views` path.  Not thread-safe (one
    encoder per connection owner); :meth:`pack`'s returned view aliases
    the buffer and is valid until the next pack."""

    def __init__(self, initial: int = 4096):
        self._tx = bytearray(int(initial))
        self.frame_len = 0  # of the most recent pack

    def pack(self, action: bytes, arrays: Sequence[np.ndarray]) -> memoryview:
        arrays = [np.ascontiguousarray(a) for a in arrays]
        payload = 5 + sum(8 + a.nbytes for a in arrays)
        total = 8 + payload
        if len(self._tx) < total:
            self._tx = bytearray(total)
        struct.pack_into(">Q", self._tx, 0, payload)
        self._tx[8:9] = action
        struct.pack_into(">I", self._tx, 9, len(arrays))
        mv = memoryview(self._tx)
        pos = 13
        for a in arrays:
            struct.pack_into(">Q", self._tx, pos, a.nbytes)
            pos += 8
            if a.nbytes:
                mv[pos:pos + a.nbytes] = memoryview(a).cast("B")
            pos += a.nbytes
        self.frame_len = total
        return mv[:total]

    def send(self, sock: socket.socket, action: bytes,
             arrays: Sequence[np.ndarray]) -> int:
        """Pack and send one frame; returns its full on-the-wire length."""
        frame = self.pack(action, arrays)
        sock.sendall(frame)
        if obs.enabled():
            obs.counter("net_tx_frames_total").inc()
            obs.counter("net_tx_bytes_total").inc(self.frame_len)
        return self.frame_len


def check_row_ids(ids: np.ndarray, rows: int, leaf: int) -> np.ndarray:
    """Validate one table's canonical wire row-id array: in-bounds,
    strictly ascending (sorted AND unique — what makes the fancy-indexed
    ``center[ids] += grads`` apply exact).  The ONE validation contract
    both hub implementations enforce — peers present canonical ids, the
    hub REJECTS rather than repairs (repairing would hide a desynced
    caller).  Returns ``ids`` unchanged (callers pass zero-copy views)."""
    if ids.size:
        if ids[0] < 0 or ids[-1] >= rows:
            raise ValueError(f"sparse leaf {leaf}: row ids outside "
                             f"[0, {rows})")
        if ids.size > 1 and not (np.diff(ids) > 0).all():
            raise ValueError(f"sparse leaf {leaf}: row ids must be "
                             f"sorted and unique")
    return ids


def normalize_row_ids(ids, rows: int) -> np.ndarray:
    """Canonical wire form of one sparse table's touched-row set: flat
    int64, sorted, unique, bounds-checked against the table's ``rows``.
    The sorted-unique contract is what makes the hub's fancy-indexed
    ``center[ids] += grads`` apply exact (duplicate ids would drop all
    but one addend)."""
    arr = np.unique(np.asarray(ids).ravel().astype(ROW_ID_DTYPE, copy=False))
    if arr.size and (arr[0] < 0 or arr[-1] >= rows):
        raise ValueError(f"row ids outside [0, {rows}): "
                         f"[{arr[0]}, {arr[-1]}]")
    return arr


# -- int8 commit compression (action Q blobs) ---------------------------------

def quantize_q_blob(delta: np.ndarray) -> Tuple[bytes, np.ndarray]:
    """One tensor -> (wire blob, float32 quantization residual).

    Blob = big-endian f32 scale + int8 values; residual = what rounding
    dropped, for the caller's error-feedback accumulator.  An all-zero
    delta keeps scale 1.0 so dequantization never divides by zero."""
    d = np.ascontiguousarray(delta, dtype=np.float32)
    amax = float(np.max(np.abs(d))) if d.size else 0.0
    scale = amax / 127.0 if amax > 0.0 else 1.0
    q = np.clip(np.rint(d / scale), -127, 127).astype(np.int8)
    residual = d - q.astype(np.float32) * np.float32(scale)
    return struct.pack(">f", scale) + q.tobytes(), residual


def dequantize_q_blob(blob: bytes, size: int) -> np.ndarray:
    """Inverse of :func:`quantize_q_blob`: flat float32 array of ``size``."""
    if len(blob) != 4 + size:
        raise ProtocolError(f"Q blob of {len(blob)} bytes != 4 + {size}")
    (scale,) = struct.unpack(">f", blob[:4])
    return np.frombuffer(blob, dtype=np.int8, offset=4).astype(np.float32) * np.float32(scale)


def send_tensors(sock: socket.socket, action: bytes, arrays: Sequence[np.ndarray]) -> None:
    send_frame(sock, encode_tensors(action, arrays))


def recv_tensors(sock: socket.socket, templates: Optional[Sequence[np.ndarray]] = None,
                 limit: int = MAX_FRAME,
                 out: Optional[Sequence[np.ndarray]] = None) -> Tuple[bytes, List[np.ndarray]]:
    """Receive an (action, tensors) frame.

    With ``templates`` (the out-of-band schema) the frame is scatter-read
    with ``recv_into`` DIRECTLY into the result arrays — freshly allocated
    from the templates, or the caller's preallocated ``out`` — so the
    payload is written exactly once, by the kernel, at its destination (no
    intermediate frame buffer, no per-blob slice copies).  A frame that
    does not match the template layout raises ``ValueError`` with the
    stream desynchronized — drop the connection.

    Without templates, raw ``uint8`` copies are returned (the
    control-plane path: tolerant of any tensor count/size)."""
    if templates is None and out is None:
        action, blobs = decode_tensors(recv_frame(sock, limit=limit))
        return action, [np.frombuffer(b, dtype=np.uint8) for b in blobs]
    if out is None:
        out = [np.empty(np.asarray(t).shape, np.asarray(t).dtype)
               for t in templates]
    action = _scatter_recv_into(sock, out, memoryview(bytearray(13)),
                                limit=limit)
    return action, list(out)


# -- zero-copy shared-memory transport (action Z, ISSUE 18) -------------------
#
# Same-host workers can move the EXACT framed byte stream of a TCP
# connection through a pair of mmap-backed SPSC byte rings instead of the
# kernel socket stack.  Each direction gets its own ring file; each ring
# has exactly one producer and one consumer, so the only shared mutable
# state is two monotonically increasing byte counters (head: total bytes
# written, tail: total bytes read) plus two closed flags.  The counters
# are aligned 8-byte words in the header page, written with single
# aligned stores (atomic on every platform the repo targets; the C++ hub
# maps the same offsets as ``std::atomic`` with acquire/release), and
# each side only ever WRITES its own counter — the classic SPSC ticket
# protocol, no lock, no futex.  Waits are busy-then-park: a short spin
# (the common case — the peer is actively draining) escalating to short
# sleeps, so an idle ring costs no CPU.
#
# Ring file layout (native-endian — both ends share the host):
#
#     offset    0  u64  magic (SHM_RING_MAGIC — layout version 1)
#     offset    8  u64  capacity (power of two, data-region bytes)
#     offset   64  u64  head   — producer-owned, total bytes written
#     offset  128  u64  tail   — consumer-owned, total bytes read
#     offset  192  u32  producer_closed
#     offset  196  u32  consumer_closed
#     offset 4096  data region (capacity bytes, indexed mod capacity)
#
# head/tail live on their own cache lines so producer and consumer never
# false-share, and the data region starts on a page boundary.

SHM_RING_MAGIC = 0x646B2D72696E6731  # "dk-ring1"
SHM_RING_HEADER = 4096
SHM_RING_DEFAULT_CAPACITY = 1 << 20
# u64-index offsets into the header page (memoryview cast "Q")
_SHM_Q_MAGIC = 0
_SHM_Q_CAPACITY = 1
_SHM_Q_HEAD = 8      # byte 64
_SHM_Q_TAIL = 16     # byte 128
# u32-index offsets (memoryview cast "I")
_SHM_I_PRODUCER_CLOSED = 48  # byte 192
_SHM_I_CONSUMER_CLOSED = 49  # byte 196


class ShmFrameRing:
    """One direction of the zero-copy transport: an mmap-backed SPSC byte
    ring carrying the SAME framed bytes the socket would (so bit-identity
    with TCP is structural, not re-proven per message).  Exactly one
    producer and one consumer; this object takes ONE of the two roles.

    ``write``/``read_into`` mirror ``sendall``/``recv_into`` semantics —
    write moves every byte or raises, read returns whatever contiguous
    run is available (possibly fewer bytes than asked) and 0 only when
    the producer closed with the ring drained, so the socket receive
    helpers treat a dead ring peer exactly like a closed socket.  A full
    ring parks the producer (counted in ``ps.shm_ring_full_waits``); a
    deadline overrun raises ``socket.timeout`` so reconnect/heartbeat
    paths built for sockets keep working unchanged."""

    _SPIN = 200          # busy iterations before the first sleep
    _PARK_MIN = 10e-6    # first sleep
    _PARK_MAX = 1e-3     # sleep ceiling while parked

    def __init__(self, path: str, mm: mmap.mmap, role: str):
        if role not in ("producer", "consumer"):
            raise ValueError(f"role must be 'producer' or 'consumer', "
                             f"got {role!r}")
        self.path = path
        self.role = role
        self._mm = mm
        self._q = memoryview(mm).cast("Q")
        self._i = memoryview(mm).cast("I")
        if self._q[_SHM_Q_MAGIC] != SHM_RING_MAGIC:
            self._release()
            raise ProtocolError(f"{path}: bad shm ring magic")
        self.capacity = int(self._q[_SHM_Q_CAPACITY])
        if self.capacity <= 0 or self.capacity & (self.capacity - 1):
            self._release()
            raise ProtocolError(f"{path}: ring capacity {self.capacity} "
                                f"is not a power of two")
        self._mask = self.capacity - 1
        self._data = memoryview(mm)[SHM_RING_HEADER:
                                    SHM_RING_HEADER + self.capacity]

    # -- construction ---------------------------------------------------------

    @classmethod
    def create(cls, path: str, role: str,
               capacity: int = SHM_RING_DEFAULT_CAPACITY) -> "ShmFrameRing":
        """Create and map a fresh ring file (the hub side of the attach
        handshake).  ``capacity`` is rounded up to a power of two."""
        cap = 1
        while cap < max(int(capacity), mmap.PAGESIZE):
            cap <<= 1
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, SHM_RING_HEADER + cap)
            mm = mmap.mmap(fd, SHM_RING_HEADER + cap)
        finally:
            os.close(fd)
        q = memoryview(mm).cast("Q")
        q[_SHM_Q_CAPACITY] = cap
        # magic is stamped LAST: an opener seeing it sees a complete header
        q[_SHM_Q_MAGIC] = SHM_RING_MAGIC
        del q
        return cls(path, mm, role)

    @classmethod
    def open(cls, path: str, role: str) -> "ShmFrameRing":
        """Map an existing ring file (the client side of the handshake)."""
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            if size < SHM_RING_HEADER + mmap.PAGESIZE:
                raise ProtocolError(f"{path}: ring file too small ({size} B)")
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        return cls(path, mm, role)

    # -- the SPSC protocol ----------------------------------------------------

    def _park(self, spins: int, started: float,
              timeout: Optional[float]) -> int:
        """One wait step while the ring is full/empty; returns the updated
        spin count.  Raises ``socket.timeout`` past the deadline."""
        if timeout is not None and time.monotonic() - started >= timeout:
            raise socket.timeout("timed out waiting on shm ring")
        if spins < self._SPIN:
            return spins + 1
        time.sleep(min(self._PARK_MIN * (1 << min(spins - self._SPIN, 7)),
                       self._PARK_MAX))
        return spins + 1

    def write(self, data, timeout: Optional[float] = None) -> None:
        """Move ALL of ``data`` into the ring (``sendall`` semantics)."""
        src = memoryview(data).cast("B") if not isinstance(data, memoryview) \
            else data.cast("B")
        off, n = 0, len(src)
        head = int(self._q[_SHM_Q_HEAD])
        spins, started, parked = 0, time.monotonic(), False
        while off < n:
            if self._i[_SHM_I_CONSUMER_CLOSED]:
                raise ConnectionError("shm ring consumer closed")
            free = self.capacity - (head - int(self._q[_SHM_Q_TAIL]))
            if free == 0:
                if not parked and obs.enabled():
                    obs.counter("ps.shm_ring_full_waits").inc()
                parked = True
                spins = self._park(spins, started, timeout)
                continue
            pos = head & self._mask
            k = min(n - off, free, self.capacity - pos)
            self._data[pos:pos + k] = src[off:off + k]
            off += k
            head += k
            # publish AFTER the payload bytes are in place: the consumer
            # never reads past head, so it can never see torn data
            self._q[_SHM_Q_HEAD] = head
            spins, parked = 0, False

    def read_into(self, view, timeout: Optional[float] = None) -> int:
        """Fill ``view`` with whatever contiguous bytes are available
        (``recv_into`` semantics: may return fewer than asked; returns 0
        only when the producer closed and the ring is drained)."""
        dst = memoryview(view)
        if dst.nbytes == 0:
            return 0
        dst = dst.cast("B")
        tail = int(self._q[_SHM_Q_TAIL])
        spins, started = 0, time.monotonic()
        while True:
            avail = int(self._q[_SHM_Q_HEAD]) - tail
            if avail:
                break
            if self._i[_SHM_I_PRODUCER_CLOSED]:
                # re-check head once: close flag may land after final bytes
                if int(self._q[_SHM_Q_HEAD]) - tail == 0:
                    return 0
                continue
            spins = self._park(spins, started, timeout)
        pos = tail & self._mask
        k = min(dst.nbytes, avail, self.capacity - pos)
        dst[:k] = self._data[pos:pos + k]
        self._q[_SHM_Q_TAIL] = tail + k
        return k

    @property
    def pending(self) -> int:
        """Bytes written but not yet read (either role may ask)."""
        return int(self._q[_SHM_Q_HEAD]) - int(self._q[_SHM_Q_TAIL])

    # -- lifecycle ------------------------------------------------------------

    def mark_closed(self) -> None:
        """Raise BOTH closed flags without unmapping — the shutdown-style
        wakeup: parked peers (local threads and the process across the
        ring alike) observe the flag on their next wait iteration and
        fall out with EOF/``ConnectionError`` instead of sleeping on."""
        try:
            self._i[_SHM_I_PRODUCER_CLOSED] = 1
            self._i[_SHM_I_CONSUMER_CLOSED] = 1
        except ValueError:
            pass  # already unmapped

    def _release(self) -> None:
        self._q = self._i = self._data = None
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass  # an in-flight view pins the map; the OS reclaims at exit

    def close(self) -> None:
        """Raise this role's closed flag and unmap.  Idempotent."""
        try:
            if self.role == "producer":
                self._i[_SHM_I_PRODUCER_CLOSED] = 1
            else:
                self._i[_SHM_I_CONSUMER_CLOSED] = 1
        except (TypeError, ValueError):
            pass  # already closed
        self._release()

    def unlink(self) -> None:
        """Remove the ring file (creator-side cleanup); map stays valid."""
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ShmEndpoint:
    """A socket-shaped duplex endpoint over two :class:`ShmFrameRing`\\ s
    (one per direction) — the object that replaces ``PSClient.sock`` /
    the hub's per-connection socket after a successful Z attach.  Every
    transport helper in this module only touches ``sendall`` /
    ``recv_into`` / ``settimeout`` / ``shutdown`` / ``close``, so the
    swap is invisible to the framing layer and the bytes that move are
    identical to what the socket would have carried.

    The original TCP socket is retained (unread, unwritten) purely as a
    liveness anchor: closing the endpoint closes it too, so a peer death
    is observable by the OS even if the dead process never set its ring
    closed flag."""

    def __init__(self, sock: socket.socket, tx_ring: ShmFrameRing,
                 rx_ring: ShmFrameRing):
        self.sock = sock
        self.tx_ring = tx_ring
        self.rx_ring = rx_ring
        self._timeout = sock.gettimeout()

    def sendall(self, data) -> None:
        self.tx_ring.write(data, timeout=self._timeout)
        if obs.enabled():
            obs.counter("ps.shm_frames_total").inc()

    def recv_into(self, view, nbytes: int = 0) -> int:
        mv = memoryview(view)
        if nbytes:
            mv = mv.cast("B")[:nbytes]
        return self.rx_ring.read_into(mv, timeout=self._timeout)

    def recv(self, n: int) -> bytes:
        buf = bytearray(n)
        got = self.recv_into(memoryview(buf), n)
        return bytes(buf[:got])

    def settimeout(self, timeout: Optional[float]) -> None:
        self._timeout = timeout
        try:
            self.sock.settimeout(timeout)
        except OSError:
            pass

    def gettimeout(self) -> Optional[float]:
        return self._timeout

    def fileno(self) -> int:
        return self.sock.fileno()

    def shutdown(self, how: int = socket.SHUT_RDWR) -> None:
        """Wake every parked reader/writer on both rings (both processes)
        and sever the anchor socket — the eviction path's guarantee that
        nothing stays asleep holding a dead connection."""
        self.tx_ring.mark_closed()
        self.rx_ring.mark_closed()
        try:
            self.sock.shutdown(how)
        except OSError:
            pass

    def close(self) -> None:
        self.tx_ring.close()
        self.rx_ring.close()
        try:
            self.sock.close()
        except OSError:
            pass


# -- the Z attach handshake payloads ------------------------------------------

def encode_shm_request(capacity_hint: int = SHM_RING_DEFAULT_CAPACITY) -> bytes:
    """Step 1, client->hub: one blob = u8 layout version + u64 big-endian
    ring-capacity hint (the hub may round it; the mapped header is
    authoritative)."""
    blob = struct.pack(">BQ", SHM_VERSION, int(capacity_hint))
    return encode_tensors(ACTION_SHM, [np.frombuffer(blob, np.uint8)])


def decode_shm_request(blobs: Sequence) -> Tuple[int, int]:
    """Inverse of :func:`encode_shm_request` -> (version, capacity_hint)."""
    if not blobs:
        raise ProtocolError("Z request carries no header blob")
    raw = bytes(memoryview(blobs[0]))[:9]
    if len(raw) != 9:
        raise ProtocolError(f"Z request blob has {len(raw)} bytes, want 9")
    version, hint = struct.unpack(">BQ", raw)
    return int(version), int(hint)


def encode_shm_offer(c2h_path: str, h2c_path: str) -> bytes:
    """Step 2, hub->client (accept): TWO utf-8 path blobs — the
    client->hub ring file, then the hub->client ring file.  Both already
    exist and are fully initialized when this frame leaves."""
    return encode_tensors(ACTION_SHM, [
        np.frombuffer(c2h_path.encode("utf-8"), np.uint8),
        np.frombuffer(h2c_path.encode("utf-8"), np.uint8)])


def encode_shm_decline() -> bytes:
    """Step 2, hub->client (decline): zero blobs — the connection simply
    stays pure TCP, byte-identical to a hub with shm disabled."""
    return encode_tensors(ACTION_SHM, [])


def decode_shm_offer(blobs: Sequence) -> Optional[Tuple[str, str]]:
    """Inverse of the step-2 reply: ``(c2h_path, h2c_path)`` on an offer,
    ``None`` on a decline."""
    if not blobs:
        return None
    if len(blobs) != 2:
        raise ProtocolError(f"Z offer carries {len(blobs)} blobs, want 2")
    return (bytes(memoryview(blobs[0])).decode("utf-8"),
            bytes(memoryview(blobs[1])).decode("utf-8"))


def encode_shm_confirm(attached: bool) -> bytes:
    """Step 3, client->hub over TCP: one 1-byte blob — ``b"\\x01"`` the
    client mapped both rings and its NEXT frame rides them, ``b"\\x00"``
    mapping failed, stay on TCP.  Because TCP is FIFO, the hub reading
    this frame knows exactly which transport every subsequent frame uses
    — the stream can never tear."""
    return encode_tensors(ACTION_SHM, [
        np.frombuffer(b"\x01" if attached else b"\x00", np.uint8)])


def decode_shm_confirm(blobs: Sequence) -> bool:
    """Inverse of :func:`encode_shm_confirm`."""
    if not blobs or len(bytes(memoryview(blobs[0]))) != 1:
        raise ProtocolError("Z confirm carries no status byte")
    return bytes(memoryview(blobs[0]))[0] == 1


# -- batched socket receive (remote-worker path, ISSUE 18) --------------------

_LIBC = None
_MMSG_TYPES = None


def _libc():
    global _LIBC
    if _LIBC is None:
        import ctypes
        _LIBC = ctypes.CDLL(None, use_errno=True)
    return _LIBC


def batched_io_available() -> bool:
    """Runtime guard (the ``require_tool`` idiom, but for a libc symbol):
    True when ``recvmmsg`` is resolvable, so the batched receive path can
    drain a commit storm with one syscall per batch.  When False — or on
    any runtime failure — :class:`BatchedReceiver` silently degrades to
    plain nonblocking ``recv_into`` drains, which still amortize the
    parse but not the syscall."""
    try:
        return hasattr(_libc(), "recvmmsg")
    except OSError:
        return False


def _mmsg_types():
    """The ctypes mirror of ``struct mmsghdr`` (built once)."""
    global _MMSG_TYPES
    if _MMSG_TYPES is None:
        import ctypes

        class IoVec(ctypes.Structure):
            _fields_ = [("iov_base", ctypes.c_void_p),
                        ("iov_len", ctypes.c_size_t)]

        class MsgHdr(ctypes.Structure):
            _fields_ = [("msg_name", ctypes.c_void_p),
                        ("msg_namelen", ctypes.c_uint),
                        ("msg_iov", ctypes.POINTER(IoVec)),
                        ("msg_iovlen", ctypes.c_size_t),
                        ("msg_control", ctypes.c_void_p),
                        ("msg_controllen", ctypes.c_size_t),
                        ("msg_flags", ctypes.c_int)]

        class MMsgHdr(ctypes.Structure):
            _fields_ = [("msg_hdr", MsgHdr), ("msg_len", ctypes.c_uint)]

        _MMSG_TYPES = (ctypes, IoVec, MMsgHdr)
    return _MMSG_TYPES


class BatchedReceiver:
    """Frame-granular batched receive for one hub connection: one blocking
    ``recv_into`` pulls whatever the kernel has (typically MANY pipelined
    frames from a committing worker), opportunistic nonblocking drains
    top the buffer up, and subsequent frames are parsed straight out of
    the buffer with zero syscalls.  The per-batch frame count lands in
    the ``ps_recv_batch_depth`` histogram — the bench tripwire that the
    batching actually batches.

    ``recv_frame_into`` mirrors :func:`recv_frame_into`'s contract: the
    returned memoryview aliases the internal buffer and is valid only
    until the next call.  Strictly single-reader (the hub's per-
    connection handler thread)."""

    def __init__(self, sock: socket.socket, frame_hint: int, depth: int = 8):
        self.sock = sock
        self.depth = max(1, int(depth))
        self._buf = bytearray(max(int(frame_hint) + 8, 4096) * self.depth)
        self._head = 0   # parse offset
        self._tail = 0   # fill offset
        self._batch_frames = 0  # frames served since the last blocking fill

    def pending(self) -> int:
        """Bytes buffered but not yet parsed — must be 0 at any transport
        handoff (R replication attach, Z shm switch), else frames meant
        for the next owner were already consumed here."""
        return self._tail - self._head

    def _compact(self) -> None:
        if self._head:
            rem = self._tail - self._head
            self._buf[:rem] = self._buf[self._head:self._tail]
            self._head, self._tail = 0, rem

    def _drain_nonblocking(self) -> None:
        """Top the buffer up without blocking — one ``recvmmsg`` when libc
        has it, else a ``MSG_DONTWAIT`` recv loop — so a storm of queued
        frames is consumed in as few syscalls as the kernel allows."""
        if self.depth > 1 and batched_io_available():
            try:
                self._recvmmsg_drain()
                return
            except OSError:
                pass  # fall through to the plain-recv drain
        while self._tail < len(self._buf):
            try:
                n = self.sock.recv_into(
                    memoryview(self._buf)[self._tail:], 0, socket.MSG_DONTWAIT)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # let the next blocking read surface the real error
            if n == 0:
                return  # EOF surfaces on the next blocking read
            self._tail += n

    def _recvmmsg_drain(self) -> None:
        """One nonblocking ``recvmmsg`` over the free buffer space, carved
        into ``depth`` iovec segments.  On a stream socket a segment may
        come back short while a later one still fills, so received runs
        are compacted back into one contiguous stream before parsing."""
        ctypes, IoVec, MMsgHdr = _mmsg_types()
        room = len(self._buf) - self._tail
        seg = max(room // self.depth, 1)
        k = min(self.depth, room // seg)
        if k <= 0 or room <= 0:
            return
        base = ctypes.addressof(ctypes.c_char.from_buffer(self._buf,
                                                          self._tail))
        iovs = (IoVec * k)()
        msgs = (MMsgHdr * k)()
        for i in range(k):
            iovs[i].iov_base = base + i * seg
            iovs[i].iov_len = seg if i < k - 1 else room - (k - 1) * seg
            msgs[i].msg_hdr.msg_iov = ctypes.pointer(iovs[i])
            msgs[i].msg_hdr.msg_iovlen = 1
        r = _libc().recvmmsg(self.sock.fileno(), msgs, k,
                             socket.MSG_DONTWAIT, None)
        if r <= 0:
            return  # EAGAIN/EOF/error — the next blocking read decides
        pos = self._tail
        for i in range(r):
            ln = int(msgs[i].msg_len)
            start = self._tail + i * seg
            if start != pos and ln:
                self._buf[pos:pos + ln] = self._buf[start:start + ln]
            pos += ln
        self._tail = pos

    def _fill_blocking(self) -> None:
        """One blocking read (honors the socket timeout), then drain."""
        self._compact()
        if obs.enabled() and self._batch_frames:
            obs.histogram("ps_recv_batch_depth").observe(self._batch_frames)
        self._batch_frames = 0
        n = self.sock.recv_into(memoryview(self._buf)[self._tail:])
        if n == 0:
            raise ConnectionError("peer closed between frames")
        self._tail += n
        self._drain_nonblocking()

    def _ensure(self, need: int) -> None:
        while self._tail - self._head < need:
            if self._head + need > len(self._buf):
                self._compact()
            if self._head + need > len(self._buf):
                # one frame larger than the whole batch buffer: grow once
                self._buf.extend(bytes(self._head + need - len(self._buf)))
            self._fill_blocking()

    def recv_frame_into(self, limit: int = MAX_FRAME) -> memoryview:
        """Parse one frame out of the batch buffer (refilling as needed)
        and return its payload view — drop-in for the hub handler's
        :func:`recv_frame_into` call, same validation, same counters."""
        self._ensure(8)
        (n,) = struct.unpack_from(">Q", self._buf, self._head)
        if n > limit:
            raise ProtocolError(f"frame of {n} bytes exceeds limit={limit}")
        self._ensure(8 + n)
        start = self._head + 8
        self._head += 8 + n
        self._batch_frames += 1
        if obs.enabled():
            obs.counter("net_rx_frames_total").inc()
            obs.counter("net_rx_bytes_total").inc(8 + n)
        return memoryview(self._buf)[start:start + n]
