"""Parameter-server hub + worker client — reference parity for
``distkeras/parameter_servers.py`` (SURVEY.md §2.11, §3.4).

The reference ran a driver-side thread that bound a TCP socket, accepted
one connection per Spark worker, and dispatched pickled ``'pull'`` /
``'commit'`` messages under a single mutex.  This re-design keeps that
architecture — it is the *genuinely asynchronous* execution option for the
DOWNPOUR/EASGD family (SURVEY §7 "hard parts", option b), used when worker
processes drive their own chips over DCN — with three changes:

- the wire protocol is raw tensor frames, not pickle
  (:mod:`distkeras_tpu.runtime.networking`) — moved through the zero-copy
  flat path (preallocated frames, ``recv_into`` scatter receives), with
  a pipelined client (prefetched pulls, coalesced acks) for the async
  trainers' hot loop;
- the center is a flat ``float32`` weight list (the pytree structure stays
  with the trainer), so commits are pure vectorized numpy adds;
- the same protocol is implemented by a C++ hub
  (:mod:`distkeras_tpu.runtime.native`) that applies commits without the
  GIL; this Python hub is the portable fallback and the executable spec;
- co-located workers may skip the wire entirely: ``pull_direct`` /
  ``commit_direct`` (and :class:`InprocPSClient` over them) run the same
  center logic under the same lock — the ``transport="inproc"`` path,
  trajectory-identical to sockets (ARCHITECTURE.md "Async transport").

Server classes mirror the reference's:
``SocketParameterServer`` (base, pull/commit loop),
``DeltaParameterServer`` (unscaled adds — DOWNPOUR, elastic),
``ADAGParameterServer`` (delta / num_workers),
``DynSGDParameterServer`` (delta / (staleness + 1) with a global clock).
"""

from __future__ import annotations

import socket
import threading
from collections import deque
from typing import Any, Deque, List, Optional, Sequence, Tuple

import time

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.runtime import networking as net


class SocketParameterServer:
    """Hub-and-spoke PS: one handler thread per worker connection, one lock
    around the center variable — the reference's concurrency model
    (SURVEY §3.4), minus pickle and minus the GIL-heavy payload decode.

    Telemetry (``distkeras_tpu.observability``, off by default): pull/
    commit counts and payload bytes (``ps_pulls_total``,
    ``ps_commits_total``, ``ps_pull_bytes_total``,
    ``ps_commit_bytes_total``), per-RPC handler latency
    (``ps_rpc_seconds{rpc=...}``) and the per-connection staleness gauge
    ``ps_staleness{conn=N}`` (N is the hub's accept ordinal modulo 256 —
    workers carry no identity on the wire, and the wrap bounds label
    cardinality under elastic connection churn) — the commit clock the paper lineage's
    staleness analysis (arXiv:1611.04581) is about, now a live signal
    instead of a number internal to DynSGD's scaling rule.  Instruments
    are looked up per RPC while telemetry is on (a dict get next to a
    socket exchange) so a mid-run ``obs.reset()`` cannot orphan them, and
    nothing is registered at all while telemetry is off."""

    def __init__(self, weights: Sequence[np.ndarray], host: str = "0.0.0.0", port: int = 0):
        self.center: List[np.ndarray] = [np.array(w, dtype=np.float32) for w in weights]
        self.host = host
        self.port = int(port)
        self.num_updates = 0
        self._clock = 0  # total commits applied (DynSGD's global clock)
        self._lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._handlers: List[threading.Thread] = []
        self._conns: List[socket.socket] = []  # live worker connections
        self._conn_lock = threading.Lock()
        self._running = False
        self._center_bytes = sum(w.nbytes for w in self.center)
        # full flat-frame size of a pull reply / f32 commit (header, action,
        # count, per-tensor prefixes, payload) — the socket-buffer hint
        self._frame_bytes = 13 + sum(8 + w.nbytes for w in self.center)
        self._conn_seq = 0  # connection ordinal -> staleness gauge label

    # -- lifecycle (reference: ParameterServer.start/stop) ---------------------
    def start(self) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(128)
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._running = False
        if self._listener is not None:
            try:
                # shutdown BEFORE close: close() alone does not wake a
                # thread blocked in accept() on Linux, so every stop()
                # silently burned the full join timeout and leaked the
                # accept thread.  shutdown() fails the pending accept
                # immediately (same idiom as the C++ hub's stop())
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # not listening / already gone; close still applies
            try:
                self._listener.close()
            except OSError:
                pass
        # sever live worker connections (matching the C++ hub): a blocked
        # handler wakes with EOF and exits, and the worker's next receive
        # surfaces a clean ConnectionError instead of hanging on a hub
        # that will never reply — the fault-injection behavior
        # tests/test_runtime.py pins
        with self._conn_lock:
            for conn in list(self._conns):
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        for t in self._handlers:
            t.join(timeout=5)

    def get_weights(self) -> List[np.ndarray]:
        with self._lock:
            return [w.copy() for w in self.center]

    # -- serving loop (reference: SocketParameterServer.run) -------------------
    def _accept_loop(self) -> None:
        assert self._listener is not None
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                break  # listener closed by stop()
            # registration races stop(): linearize on _conn_lock — either
            # this append lands before stop()'s sever loop (which then
            # shuts the conn down), or we observe _running False here and
            # close it ourselves.  Without the re-check a conn accepted in
            # the gap would spawn a handler that blocks in recv forever,
            # resurrecting the leaked-handler stall stop() just fixed
            with self._conn_lock:
                if not self._running:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    break
                self._conns.append(conn)
            # Nagle off + kernel buffers sized to one full weights/commit
            # frame: the pipelined client parks a commit in the send buffer
            # and returns to compute instead of blocking in sendall
            net.configure_socket(conn, payload_hint=self._frame_bytes)
            # ordinal wraps at a fixed slot count so the staleness gauge's
            # label cardinality stays bounded even under elastic-run
            # connection churn (ordinals already restart at 0 per hub,
            # so slots only conflate workers past 256 live connections)
            conn_idx = self._conn_seq % 256
            self._conn_seq += 1
            t = threading.Thread(target=self._handle_connection,
                                 args=(conn, conn_idx), daemon=True)
            t.start()
            # prune finished handlers as connections churn: a long-lived
            # hub under elastic reconnects must not accumulate one dead
            # Thread object per connection ever accepted
            self._handlers = [h for h in self._handlers if h.is_alive()]
            self._handlers.append(t)

    def _decode_delta(self, blobs) -> List[np.ndarray]:
        """f32 commit: reinterpret each wire blob in place (zero-copy views
        into the connection's receive buffer, consumed before the next
        frame overwrites it)."""
        if len(blobs) != len(self.center):
            raise ValueError(f"commit has {len(blobs)} tensors, center has {len(self.center)}")
        out = []
        for blob, c in zip(blobs, self.center):
            arr = np.frombuffer(blob, dtype=c.dtype)
            if arr.size != c.size:
                raise ValueError(f"commit tensor size {arr.size} != center size {c.size}")
            out.append(arr.reshape(c.shape))
        return out

    def _decode_qdelta(self, blobs) -> List[np.ndarray]:
        """int8 commit (action Q): per-tensor f32 scale + int8 values."""
        if len(blobs) != len(self.center):
            raise ValueError(f"commit has {len(blobs)} tensors, center has {len(self.center)}")
        return [net.dequantize_q_blob(blob, c.size).reshape(c.shape)
                for blob, c in zip(blobs, self.center)]

    def _handle_connection(self, conn: socket.socket, conn_idx: int = 0) -> None:
        last_pull_clock = 0
        # per-connection reusable storage: the receive buffer grows once to
        # the largest frame this worker sends (a commit), the reply codec
        # holds one prepacked weights frame, the ack is a 13-byte constant
        # — steady-state the handler loop allocates nothing
        rx = bytearray(self._frame_bytes)
        reply = net.FlatFrameCodec(self.center)
        ack = net.empty_tensor_frame(net.ACTION_ACK)
        try:
            while True:
                # raw receive: pull/bye carry zero tensors, commit carries
                # len(center) — decode against the center only on commit
                payload = net.recv_frame_into(conn, rx)
                action, blobs = net.decode_tensor_views(payload)
                telemetry = obs.enabled()
                t0 = time.perf_counter() if telemetry else 0.0
                if action == net.ACTION_PULL:
                    with self._lock:
                        # pack the center STRAIGHT into the reply frame (one
                        # memcpy per tensor) under the lock; the send happens
                        # after release so a slow peer can't hold the center
                        reply.pack(net.ACTION_WEIGHTS, self.center)
                        last_pull_clock = self._clock
                    reply.send_packed(conn)
                    if telemetry:
                        obs.counter("ps_pulls_total").inc()
                        obs.counter("ps_pull_bytes_total").inc(self._center_bytes)
                        obs.histogram("ps_rpc_seconds", rpc="pull").observe(
                            time.perf_counter() - t0)
                elif action in (net.ACTION_COMMIT, net.ACTION_QCOMMIT):
                    delta = (self._decode_delta(blobs)
                             if action == net.ACTION_COMMIT
                             else self._decode_qdelta(blobs))
                    with self._lock:
                        staleness = self._clock - last_pull_clock
                        self.apply_commit(delta, staleness)
                        self.num_updates += 1
                        self._clock += 1
                    net.send_raw_frame(conn, ack)
                    if telemetry:
                        obs.counter("ps_commits_total").inc()
                        obs.counter("ps_commit_bytes_total").inc(
                            sum(b.nbytes for b in blobs))
                        obs.histogram("ps_rpc_seconds", rpc="commit").observe(
                            time.perf_counter() - t0)
                        # per-connection staleness: commits the hub applied
                        # between this worker's last pull and its commit —
                        # the quantity DynSGD scales by, now visible for
                        # EVERY hub flavor.  Created lazily so a hub with
                        # telemetry off never registers per-connection state
                        obs.gauge("ps_staleness",
                                  conn=str(conn_idx)).set(staleness)
                        obs.histogram("ps_commit_staleness").observe(staleness)
                elif action == net.ACTION_BYE:
                    break
                else:
                    raise ValueError(f"unknown action {action!r}")
        except (ConnectionError, ValueError, OSError):
            pass  # worker vanished mid-exchange; reference behavior: drop it
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # forget the socket so stop() never shuts down an unrelated
            # descriptor that reuses this slot
            with self._conn_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    # -- in-process transport (transport="inproc") -----------------------------
    # Co-located workers skip sockets and framing entirely and call the
    # SAME center logic the handlers run, under the same lock.  The pair
    # below is the whole inproc wire protocol: pull_direct is the 'P'
    # branch minus the frame, commit_direct is the 'C' branch minus the
    # decode.  The C++ hub exposes the same pair (runtime/native.py), so
    # InprocPSClient works against either hub.

    def pull_direct(self) -> Tuple[List[np.ndarray], int]:
        """Snapshot (center copy, clock at snapshot) — the caller passes the
        clock back with its commit, exactly like a socket worker's
        connection state does."""
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        with self._lock:
            snapshot = [w.copy() for w in self.center]
            clock = self._clock
        if telemetry:
            obs.counter("ps_pulls_total").inc()
            obs.histogram("ps_rpc_seconds", rpc="pull.inproc").observe(
                time.perf_counter() - t0)
        return snapshot, clock

    def commit_direct(self, delta: Sequence[np.ndarray], last_pull_clock: int) -> None:
        """Apply one commit with the staleness implied by ``last_pull_clock``
        (the value returned by the matching :meth:`pull_direct`)."""
        if len(delta) != len(self.center):
            raise ValueError(f"commit has {len(delta)} tensors, center has {len(self.center)}")
        for d, c in zip(delta, self.center):
            if np.asarray(d).size != c.size:
                raise ValueError(f"commit tensor size {np.asarray(d).size} != "
                                 f"center size {c.size}")
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        # dtype/shape normalization outside the lock (no-op views for the
        # trainers' float32 payloads)
        arrays = [np.asarray(d, np.float32).reshape(c.shape)
                  for d, c in zip(delta, self.center)]
        with self._lock:
            staleness = self._clock - last_pull_clock
            self.apply_commit(arrays, staleness)
            self.num_updates += 1
            self._clock += 1
        if telemetry:
            obs.counter("ps_commits_total").inc()
            obs.histogram("ps_rpc_seconds", rpc="commit.inproc").observe(
                time.perf_counter() - t0)
            obs.histogram("ps_commit_staleness").observe(staleness)

    # -- commit rules ----------------------------------------------------------
    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:  # pragma: no cover
        raise NotImplementedError


class DeltaParameterServer(SocketParameterServer):
    """Unscaled delta adds: ``center += delta``.  Reference
    ``DeltaParameterServer`` — serves DOWNPOUR (accumulated gradients) and
    the elastic family (workers pre-scale by alpha)."""

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        for c, d in zip(self.center, delta):
            c += d


class ADAGParameterServer(SocketParameterServer):
    """ADAG normalization: ``center += delta / num_workers`` (reference
    ``ADAGParameterServer.handle_commit``, SURVEY §2.6)."""

    def __init__(self, weights: Sequence[np.ndarray], num_workers: int, **kwargs):
        super().__init__(weights, **kwargs)
        self.num_workers = int(num_workers)

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        inv = 1.0 / self.num_workers
        for c, d in zip(self.center, delta):
            c += d * inv


class DynSGDParameterServer(SocketParameterServer):
    """Staleness-aware scaling: ``center += delta / (staleness + 1)`` where
    staleness = commits applied since this worker's last pull (reference
    ``DynSGDParameterServer.handle_commit``, SURVEY §2.7)."""

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        inv = 1.0 / (staleness + 1.0)
        for c, d in zip(self.center, delta):
            c += d * inv


def _quantize_commit(delta: Sequence[np.ndarray],
                     residual: List[np.ndarray]) -> List[np.ndarray]:
    """Advance the int8 error-feedback chain one commit: quantize each
    delta WITH its carried residual, store the new residual in place, and
    return the wire blobs (uint8 arrays: be-f32 scale + int8 values).

    The one implementation both transports call — the socket client frames
    the blobs as an action-``Q`` message, the inproc client dequantizes
    them right back — so the quantize/residual math can never fork between
    transports (the bit-parity property ``tests/test_transport.py`` pins)."""
    blobs = []
    for i, d in enumerate(delta):
        carried = np.asarray(d, np.float32) + residual[i]
        blob, residual[i] = net.quantize_q_blob(carried)
        blobs.append(np.frombuffer(blob, dtype=np.uint8))
    return blobs


class PSClient:
    """Worker-side connection: ``pull()`` / ``commit(delta)`` (reference:
    ``NetworkWorker.pull/commit``, SURVEY §2.10) — plus the pipelined
    fire-and-forget API the async hot path runs on
    (``pull_nowait`` / ``wait_weights`` / ``commit_nowait`` / ``drain``).

    Framing is the zero-copy flat path (:class:`~.networking.FlatFrameCodec`):
    commits leave through one preallocated frame buffer (one memcpy per
    tensor, single ``sendall``), pulls scatter-receive with ``recv_into``
    into one of two reusable landing buffers — double-buffered because the
    caller may still be consuming pull *k* while the prefetched pull *k+1*
    streams in.  Arrays returned by ``pull``/``wait_weights`` therefore
    alias client-owned storage that is REUSED two pulls later; copy
    anything that must outlive that.

    Pipelining: the nowait methods send a request and record the expected
    reply in a FIFO; replies are consumed lazily, in wire order, by
    ``wait_weights``/``drain`` — commit acks coalesce into the next
    weights receive instead of costing their own blocking round trip.  At
    most ``max_inflight`` commits ride unacknowledged (enforced by
    consuming replies before sending more: wire back-pressure, not an
    unbounded queue).  After any mid-frame error the stream is
    desynchronized — the connection is single-use, callers drop it.

    ``compress="int8"`` sends commits as action-``Q`` frames — symmetric
    per-tensor int8 with a float32 scale (4x fewer wire bytes) — keeping
    the quantization residual client-side and folding it into the next
    commit (error feedback: the sum of dequantized commits tracks the sum
    of true deltas, so compression does not bias the center).  The
    residual chain advances at QUANTIZATION time: pipelined commits have
    no per-commit ack to gate on, and a dead connection is fatal to the
    worker anyway (nothing reconnects and retries a half-sent commit).
    Pulls always stay full precision: weight error hits the model
    directly, while delta rounding error is recycled.

    Telemetry (client side): ``ps.commit_bytes`` wire bytes,
    ``ps.pull_latency_ms`` / ``ps.commit_latency_ms`` send-to-reply-
    consumed latencies, ``ps.pull_stall_ms`` time actually BLOCKED waiting
    for weights (the post-overlap stall the trainer pays),
    ``ps.serialize_ms`` frame-pack time, ``ps.inflight_depth`` unacked
    commits."""

    def __init__(self, host: str, port: int, templates: Sequence[np.ndarray],
                 timeout: Optional[float] = 60.0,
                 compress: Optional[str] = None,
                 max_inflight: int = 2):
        if compress not in (None, "int8"):
            raise ValueError(f"unknown compress {compress!r}; use None or 'int8'")
        self.templates = [np.asarray(t, dtype=np.float32) for t in templates]
        self.compress = compress
        self._residual = ([np.zeros(t.shape, np.float32) for t in self.templates]
                          if compress else None)
        self._codec = net.FlatFrameCodec(self.templates)
        # int8 commits have their own fixed layout (4-byte scale + one int8
        # per element), so they get their own preallocated frame
        self._q_codec = (net.FlatFrameCodec(
            [np.zeros(4 + t.size, np.uint8) for t in self.templates])
            if compress == "int8" else None)
        self.max_inflight = max(1, int(max_inflight))
        self._pending: Deque[Tuple[bytes, float]] = deque()  # expected replies, wire order
        self._pull_frame = net.empty_tensor_frame(net.ACTION_PULL)
        self._pull_bufs = ([np.empty_like(t) for t in self.templates],
                          [np.empty_like(t) for t in self.templates])
        self._flip = 0
        # weights replies consumed off the wire but not yet claimed by
        # wait_weights (commit_nowait pre-drains them — see below); two
        # landing buffers bound this queue at two entries
        self._ready: Deque[List[np.ndarray]] = deque()
        self.sock = net.connect(host, port, timeout=timeout,
                                payload_hint=self._codec.frame_len)

    # -- pipelined API ---------------------------------------------------------
    def pull_nowait(self) -> None:
        """Fire a pull request; the reply is consumed later by
        :meth:`wait_weights`.  Issue it while the device computes and the
        weights' wire time hides under the window."""
        outstanding = (sum(1 for kind, _ in self._pending
                           if kind == net.ACTION_WEIGHTS) + len(self._ready))
        if outstanding >= 2:
            raise RuntimeError("at most 2 pulls may be outstanding (two "
                               "landing buffers); claim one with "
                               "wait_weights() first")
        net.send_raw_frame(self.sock, self._pull_frame)
        self._pending.append((net.ACTION_WEIGHTS, time.perf_counter()))

    def commit_nowait(self, delta: Sequence[np.ndarray]) -> None:
        """Send a commit without waiting for its ack (coalesced into a later
        receive).  Blocks only when ``max_inflight`` commits are already
        unacknowledged."""
        # the span covers the work the client actually does per commit
        # (back-pressure + quantize/pack + send); the ack wait is measured
        # separately by ps.commit_latency_ms when the reply is consumed
        with obs.span("ps.commit", compress=self.compress or "none"):
            # deadlock avoidance: never start a potentially-blocking large
            # send while a weights reply may still be in flight — the hub
            # does not read while it writes, so two big sendalls in
            # opposite directions can fill both kernel buffers and stall
            # forever once frames outgrow the socket buffers.  Claim any
            # pending pull into its landing buffer first (wait_weights
            # hands it out later); the hub is then parked in recv when the
            # commit bytes arrive.  This receive time is pull wire-wait,
            # so it lands in ps.pull_stall_ms like any other pull block.
            if any(kind == net.ACTION_WEIGHTS for kind, _ in self._pending):
                t_drain = time.perf_counter() if obs.enabled() else 0.0
                while any(kind == net.ACTION_WEIGHTS
                          for kind, _ in self._pending):
                    self._consume_one()
                if t_drain:
                    obs.histogram("ps.pull_stall_ms").observe(
                        (time.perf_counter() - t_drain) * 1e3)
            while self._unacked() >= self.max_inflight:
                self._consume_one()
            telemetry = obs.enabled()
            t0 = time.perf_counter() if telemetry else 0.0
            if self.compress == "int8":
                codec, action = self._q_codec, net.ACTION_QCOMMIT
                arrays = _quantize_commit(delta, self._residual)
            else:
                codec, action = self._codec, net.ACTION_COMMIT
                arrays = [np.asarray(d, np.float32) for d in delta]
            codec.pack(action, arrays)
            if telemetry:
                obs.histogram("ps.serialize_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
                obs.counter("ps.commit_bytes").inc(codec.frame_len)
            codec.send_packed(self.sock)
            self._pending.append((net.ACTION_ACK, time.perf_counter()))
            if telemetry:
                obs.gauge("ps.inflight_depth").set(self._unacked())

    def wait_weights(self) -> List[np.ndarray]:
        """Hand out the oldest in-flight pull, consuming replies (and any
        commit acks queued ahead of it) as needed."""
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        while not self._ready:
            if not self._pending:
                raise ConnectionError("wait_weights() with no pull in flight")
            self._consume_one()
        if telemetry:
            obs.histogram("ps.pull_stall_ms").observe(
                (time.perf_counter() - t0) * 1e3)
        return self._ready.popleft()

    def drain(self) -> None:
        """Consume every outstanding reply — trailing commit acks at the end
        of a run, plus any prefetched pull that will go unused."""
        while self._pending:
            self._consume_one()
        self._ready.clear()
        if obs.enabled():
            obs.gauge("ps.inflight_depth").set(0)

    def _unacked(self) -> int:
        return sum(1 for kind, _ in self._pending if kind == net.ACTION_ACK)

    def _consume_one(self) -> None:
        kind, t_sent = self._pending.popleft()
        if kind == net.ACTION_ACK:
            reply = net.recv_action(self.sock)
            if reply != net.ACTION_ACK:
                raise ConnectionError(f"expected ack, got {reply!r}")
            if obs.enabled():
                obs.histogram("ps.commit_latency_ms").observe(
                    (time.perf_counter() - t_sent) * 1e3)
                obs.gauge("ps.inflight_depth").set(self._unacked())
        else:
            out = self._pull_bufs[self._flip]
            self._flip ^= 1
            reply = self._codec.recv_into(self.sock, out)
            if reply != net.ACTION_WEIGHTS:
                raise ConnectionError(f"expected weights reply, got {reply!r}")
            self._ready.append(out)
            if obs.enabled():
                obs.histogram("ps.pull_latency_ms").observe(
                    (time.perf_counter() - t_sent) * 1e3)

    # -- blocking API (control plane + non-pipelined callers) ------------------
    def pull(self) -> List[np.ndarray]:
        with obs.span("ps.pull"):
            self.pull_nowait()
            return self.wait_weights()

    def commit(self, delta: Sequence[np.ndarray]) -> None:
        self.commit_nowait(delta)
        self.drain()

    def close(self) -> None:
        try:
            net.send_raw_frame(self.sock, net.empty_tensor_frame(net.ACTION_BYE))
        except OSError:
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self) -> "PSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class InprocPSClient:
    """:class:`PSClient` surface over a co-located hub (``transport="inproc"``).

    Pull/commit call the SAME center logic the socket handlers run —
    ``pull_direct`` / ``commit_direct``, under the hub's lock — with no
    sockets, no framing, and no wire copies; the staleness clock rides the
    client object instead of a connection.  Works against the Python hubs
    and the C++ hub (both expose the direct pair).

    The nowait/wait methods execute EAGERLY at the exact program points
    the socket client would *send* at, so a deterministic (single-worker)
    schedule observes identical center states on both transports — the
    trajectory-parity property ``tests/test_transport.py`` pins.

    ``compress="int8"`` round-trips every commit through the same
    quantize/dequantize + error-feedback math the wire path uses, so
    compressed runs also stay trajectory-identical across transports."""

    def __init__(self, ps: Any, templates: Sequence[np.ndarray],
                 compress: Optional[str] = None):
        if compress not in (None, "int8"):
            raise ValueError(f"unknown compress {compress!r}; use None or 'int8'")
        self.ps = ps
        self.templates = [np.asarray(t, dtype=np.float32) for t in templates]
        self.compress = compress
        self._residual = ([np.zeros(t.shape, np.float32) for t in self.templates]
                          if compress else None)
        self._last_pull_clock = 0
        self._pulled: Optional[List[np.ndarray]] = None

    # -- pipelined API (eager) -------------------------------------------------
    def pull_nowait(self) -> None:
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        weights, clock = self.ps.pull_direct()
        self._last_pull_clock = clock
        self._pulled = weights
        if telemetry:
            obs.histogram("ps.pull_latency_ms").observe(
                (time.perf_counter() - t0) * 1e3)

    def wait_weights(self) -> List[np.ndarray]:
        if self._pulled is None:
            raise RuntimeError("wait_weights() with no pull in flight")
        pulled, self._pulled = self._pulled, None
        return pulled

    def commit_nowait(self, delta: Sequence[np.ndarray]) -> None:
        with obs.span("ps.commit", transport="inproc",
                      compress=self.compress or "none"):
            telemetry = obs.enabled()
            t0 = time.perf_counter() if telemetry else 0.0
            if self.compress == "int8":
                # same quantize + residual advance as the wire path, then
                # straight back through the dequantizer — what the hub
                # would have reconstructed from the Q frame
                blobs = _quantize_commit(delta, self._residual)
                arrays = [net.dequantize_q_blob(memoryview(b), t.size)
                          .reshape(t.shape)
                          for b, t in zip(blobs, self.templates)]
            else:
                arrays = [np.asarray(d, np.float32) for d in delta]
            self.ps.commit_direct(arrays, self._last_pull_clock)
            if telemetry:
                obs.histogram("ps.commit_latency_ms").observe(
                    (time.perf_counter() - t0) * 1e3)

    def drain(self) -> None:
        pass  # nothing rides in flight: commits apply synchronously

    # -- blocking API ----------------------------------------------------------
    def pull(self) -> List[np.ndarray]:
        with obs.span("ps.pull", transport="inproc"):
            self.pull_nowait()
            return self.wait_weights()

    def commit(self, delta: Sequence[np.ndarray]) -> None:
        self.commit_nowait(delta)

    def close(self) -> None:
        pass  # no connection; the hub's lifecycle belongs to the trainer

    def __enter__(self) -> "InprocPSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
