"""Parameter-server hub + worker client — reference parity for
``distkeras/parameter_servers.py`` (SURVEY.md §2.11, §3.4).

The reference ran a driver-side thread that bound a TCP socket, accepted
one connection per Spark worker, and dispatched pickled ``'pull'`` /
``'commit'`` messages under a single mutex.  This re-design keeps that
architecture — it is the *genuinely asynchronous* execution option for the
DOWNPOUR/EASGD family (SURVEY §7 "hard parts", option b), used when worker
processes drive their own chips over DCN — with three changes:

- the wire protocol is raw tensor frames, not pickle
  (:mod:`distkeras_tpu.runtime.networking`) — moved through the zero-copy
  flat path (preallocated frames, ``recv_into`` scatter receives), with
  a pipelined client (prefetched pulls, coalesced acks) for the async
  trainers' hot loop;
- the center is a flat ``float32`` weight list (the pytree structure stays
  with the trainer), so commits are pure vectorized numpy adds;
- the same protocol is implemented by a C++ hub
  (:mod:`distkeras_tpu.runtime.native`) that applies commits without the
  GIL; this Python hub is the portable fallback and the executable spec;
- co-located workers may skip the wire entirely: ``pull_direct`` /
  ``commit_direct`` (and :class:`InprocPSClient` over them) run the same
  center logic under the same lock — the ``transport="inproc"`` path,
  trajectory-identical to sockets (ARCHITECTURE.md "Async transport").

Server classes mirror the reference's:
``SocketParameterServer`` (base, pull/commit loop),
``DeltaParameterServer`` (unscaled adds — DOWNPOUR, elastic),
``ADAGParameterServer`` (delta / num_workers),
``DynSGDParameterServer`` (delta / (staleness + 1) with a global clock).

The hub also scales OUT (ISSUE 6, ARCHITECTURE.md "Sharded hub"): a
deterministic, size-balanced leaf->shard assignment (:func:`shard_plan`)
partitions the center across N hub shards — one hub, lock, listener and
commit clock per shard (:class:`ShardedParameterServer` owns the set) —
and :class:`ShardedPSClient` stripes every pull/commit across per-shard
connections reusing the same pipelined/zero-copy machinery per
connection.  ``num_shards=1`` is byte-identical to the single-hub wire.
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
import json
import os
import queue
import random
import socket
import threading
import warnings
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import time

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import distributed as dtrace
from distkeras_tpu.runtime import networking as net


class HubSnapshotter:
    """Periodic durability for a PS hub: every ``interval`` seconds (and
    once at stop) the hub's full recoverable state — center weights, commit
    clock, update count, algorithm extras — is written through
    :class:`distkeras_tpu.checkpoint.Checkpointer` (atomic tmp+rename, so a
    hub SIGKILLed mid-save leaves the previous snapshot intact).  A
    restarted hub calls :meth:`restore_latest` BEFORE serving: the center
    resumes from the last snapshot and the commit clock re-arms behind a
    fence (``restore_state`` on the hub) that neutralizes pre-restart stale
    clocks.  Works against any hub exposing ``snapshot_state()`` /
    ``restore_state()`` — the Python hubs here and the C++ hub wrapper
    (:mod:`distkeras_tpu.runtime.native`) both do.

    Telemetry: ``ps.snapshot_ms`` save-latency histogram,
    ``ps_snapshots_total`` counter."""

    def __init__(self, hub: Any, directory: str, interval: float = 30.0,
                 keep: int = 3):
        from distkeras_tpu.checkpoint import Checkpointer

        self.hub = hub
        self.interval = float(interval)
        self.checkpointer = Checkpointer(directory, keep=keep)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes the periodic loop against the final stop() snapshot
        self._save_lock = threading.Lock()
        self._next_step = (self.checkpointer.latest_step() or 0) + 1

    def restore_latest(self) -> bool:
        """Load the newest readable snapshot into the hub; ``True`` if one
        was restored.  Corrupt/partial snapshots (killed mid-write by
        something stronger than the atomic rename — disk truncation, a
        torn copy) are skipped with a warning, falling back to the next
        older one."""
        templates = self.hub.get_weights()
        for step in reversed(self.checkpointer.all_steps()):
            try:
                trees = self.checkpointer.restore({"center": templates}, step=step)
                meta = self.checkpointer.metadata(step=step).get("metadata", {})
            except Exception as e:
                warnings.warn(f"skipping unreadable PS snapshot step {step}: "
                              f"{type(e).__name__}: {e}")
                continue
            self.hub.restore_state(trees["center"], meta)
            # under the save lock: restore normally runs once at start,
            # but it is public API — racing a live snapshot loop must
            # not lose a step advance (guarded-by contract, ISSUE 14)
            with self._save_lock:
                self._next_step = max(self._next_step, step + 1)
            return True
        return False

    def save_now(self) -> None:
        with self._save_lock, obs.span("ps.snapshot"):
            t0 = time.perf_counter()
            center, state = self.hub.snapshot_state()
            self.checkpointer.save(
                self._next_step, {"center": center},
                metadata={"kind": "ps-hub-snapshot", **state})
            self._next_step += 1
            if obs.enabled():
                obs.histogram("ps.snapshot_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
                obs.counter("ps_snapshots_total").inc()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.save_now()
            except Exception as e:  # a full disk must not kill the hub
                warnings.warn(f"PS snapshot failed: {type(e).__name__}: {e}")

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self, final_snapshot: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if final_snapshot:
            try:
                self.save_now()
            except Exception as e:
                warnings.warn(f"final PS snapshot failed: {type(e).__name__}: {e}")


class ReplicationFeed:
    """Primary-side hot-standby stream (ISSUE 7): every APPLIED commit —
    the post-aggregation scaled delta plus the commit clock — is framed as
    an opt-in action-``R`` message and written to each attached replica
    connection BEFORE the committing worker's ack leaves.  A commit the
    worker saw acknowledged is therefore already in the kernel's send
    queue toward the replica, which the kernel flushes even if the primary
    process is SIGKILLed right after — the "replica center >= last
    primary-acked clock" guarantee the failover drills pin (a dead HOST
    additionally needs replica acks; out of scope, see ARCHITECTURE.md
    "High availability").

    Created lazily on the first replica handshake, so a hub nobody
    replicates pays nothing (``active()`` is one attribute read on the
    commit path).  ``attach`` full-syncs the new replica (whole center +
    clock, one R frame) under the publish lock, so the sync and the delta
    stream can never interleave inconsistently: deltas at or below the
    sync clock are skipped per connection, later deltas all flow.  Adds
    commute, so cross-thread publish-order inversions only reorder
    float additions (same tolerance class as async SGD itself).

    A replica that stops draining stalls commits at most
    ``REPLICA_SEND_TIMEOUT`` seconds, then is detached (warned + counted)
    — availability of the primary wins over completeness of a sick
    replica's feed.

    Telemetry: ``ps_replicas_connected`` gauge, ``ps.replicate_ms`` send
    latency, ``ps_replication_lag`` gauge (commits applied but not yet
    streamed at publish time — bounded by construction, measured so an
    operator sees it), ``ps_replica_disconnects_total``."""

    REPLICA_SEND_TIMEOUT = 30.0

    def __init__(self, hub: "SocketParameterServer"):
        self.hub = hub
        self._lock = threading.Lock()  # serializes attach + publish
        # [socket, conn ordinal, attach-time SYNC clock, sparse-capable]
        # per replica.  The sync clock is IMMUTABLE after attach: it only
        # filters deltas the full sync already covered.  It must never
        # advance on sends — concurrent handlers publish out of clock
        # order (apply under the hub lock, publish under this one), and a
        # moving watermark would skip (lose) the lower-clock delta behind
        # a higher one.  The capability flag is likewise attach-time
        # immutable (the hello announced it): a sparse commit streams as
        # one REPL_SPARSE row-delta frame to capable replicas and as the
        # dense-materialized REPL_DELTA to legacy ones — never a frame
        # kind the peer cannot parse
        self._conns: List[List[Any]] = []
        self._codec = net.FlatFrameCodec(net.repl_frame_templates(hub.center))
        # sparse row-delta frames vary per commit (row blobs sized by the
        # touched set), so they ride a grow-once variable encoder
        self._sp_enc = net.VarFrameEncoder()
        # cumulative row-delta bytes actually published (the `RΔ` series
        # distkeras-top renders from the hub pseudo-worker's metrics)
        self.repl_sparse_bytes = 0

    def active(self) -> bool:
        # racy read by design (publish re-checks under the lock): the
        # commit hot path must not take the feed lock when nobody listens
        return bool(self._conns)

    def _set_gauge(self) -> None:
        if obs.enabled():
            obs.gauge("ps_replicas_connected",
                      **self.hub._mlabels).set(len(self._conns))

    def attach(self, conn: socket.socket, conn_idx: int,
               capabilities: int = 0) -> None:
        """Handshake a replica connection: full-sync it (center + clock,
        captured under the hub lock) and register it for the delta
        stream.  Registration happens BEFORE the center snapshot: a commit
        applying after the registration sees ``active()`` and publishes
        (blocking on this lock until the sync is out, then skipped iff the
        sync already covered it), while a commit applying before it is in
        the snapshot — snapshotting first instead would let a commit slip
        into the gap unpublished AND unsynced.  ``capabilities`` is the
        hello's attach-time announcement (:data:`networking.
        REPL_CAP_SPARSE`): it decides the frame kinds this replica is
        ever sent."""
        conn.settimeout(self.REPLICA_SEND_TIMEOUT)
        sparse_ok = bool(capabilities & net.REPL_CAP_SPARSE)
        with self._lock:
            entry: List[Any] = [conn, conn_idx, -1, sparse_ok]
            self._conns.append(entry)
            try:
                with self.hub._lock:
                    # pack the center STRAIGHT into the sync frame under
                    # the lock (one memcpy per tensor — the pull handler's
                    # idiom); the send happens after release so a slow
                    # replica can't hold the center
                    clock = self.hub._clock
                    self._codec.pack(
                        net.ACTION_REPL,
                        [net.encode_repl_header(clock, net.REPL_SYNC)]
                        + list(self.hub.center))
                self._codec.send_packed(conn)  # lint: blocking-ok full-sync must serialize with the delta stream; stall bounded by REPLICA_SEND_TIMEOUT
            except BaseException:
                self._conns.remove(entry)
                raise
            entry[2] = clock
        if obs.enabled():
            obs.counter("ps_replicas_attached_total",
                        **self.hub._mlabels).inc()
            self._set_gauge()

    def _densify(self, scaled: Sequence[Any]) -> List[np.ndarray]:
        """Center-shaped materialization of a (possibly row-sparse) scaled
        commit — the dense-``R`` fallback frame a legacy replica applies.
        Scattering ``full[ids] = g`` makes the standby's ``center +=
        full`` perform the touched rows' float additions exactly as the
        primary's ``center[ids] += g`` did (idle rows add 0.0)."""
        out: List[np.ndarray] = []
        for c, p in zip(self.hub.center, scaled):
            if isinstance(p, tuple):
                ids, g = p
                full = np.zeros_like(c)
                if ids.size:
                    full[ids] = g
                out.append(full)
            else:
                out.append(np.asarray(p, np.float32))
        return out

    def _sparse_blobs(self, header: np.ndarray,
                      scaled: Sequence[Any]) -> List[np.ndarray]:
        """Blob list of one REPL_SPARSE frame: header + the U-commit
        layout (dense leaves whole, sparse leaves as (ids, rows))."""
        blobs: List[np.ndarray] = [header]
        for p in scaled:
            if isinstance(p, tuple):
                blobs.append(np.ascontiguousarray(p[0], net.ROW_ID_DTYPE))
                blobs.append(np.ascontiguousarray(p[1], np.float32))
            else:
                blobs.append(np.ascontiguousarray(p, np.float32))
        return blobs

    def publish(self, clock: int, scaled: Sequence[Any]) -> None:
        """Stream one applied commit to every attached replica; returns
        once the frame is written (kernel-owned) everywhere — the caller
        acks its worker only after.  ``scaled`` is per-leaf parts aligned
        with the center: full arrays for dense leaves, ``(ids, scaled row
        deltas)`` tuples for row-sparse leaves of a sparse commit.  Row-
        sparse parts stream as ONE REPL_SPARSE frame to sparse-capable
        replicas (cost ∝ touched rows) and are densified — outside the
        center lock, only when a legacy replica is actually attached —
        into the pre-ISSUE-15 REPL_DELTA frame for the rest."""
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        has_rows = any(isinstance(p, tuple) for p in scaled)
        sp_sent = 0
        sp_frame_len = 0
        with self._lock:
            if not self._conns:
                return
            packed = False
            sp_frame: Optional[memoryview] = None
            dead = []
            for entry in self._conns:
                conn, conn_idx, sync_clock, sparse_ok = entry
                if sync_clock >= clock:
                    continue  # already covered by this replica's full sync
                try:
                    if has_rows and sparse_ok:
                        if sp_frame is None:
                            sp_frame = self._sp_enc.pack(
                                net.ACTION_REPL, self._sparse_blobs(
                                    net.encode_repl_header(
                                        clock, net.REPL_SPARSE), scaled))
                        conn.sendall(sp_frame)  # lint: blocking-ok send-before-ack IS the zero-loss replication contract; stall bounded by REPLICA_SEND_TIMEOUT, then detach
                        sp_sent += 1
                        if telemetry:
                            obs.counter("net_tx_frames_total").inc()
                            obs.counter("net_tx_bytes_total").inc(
                                self._sp_enc.frame_len)
                    else:
                        if not packed:
                            self._codec.pack(
                                net.ACTION_REPL,
                                [net.encode_repl_header(clock,
                                                        net.REPL_DELTA)]
                                + (self._densify(scaled) if has_rows
                                   else list(scaled)))
                            packed = True
                        self._codec.send_packed(conn)  # lint: blocking-ok send-before-ack IS the zero-loss replication contract; stall bounded by REPLICA_SEND_TIMEOUT, then detach
                except (OSError, ValueError) as e:
                    dead.append((entry, e))
            for entry, e in dead:
                self._detach_locked(entry, e)
            if sp_sent:
                # counted (and frame_len snapshotted) under the feed lock:
                # a concurrent publish repacks the shared encoder the
                # moment we release it
                sp_frame_len = self._sp_enc.frame_len
                self.repl_sparse_bytes += sp_sent * sp_frame_len
                repl_sparse_total = self.repl_sparse_bytes
        if sp_sent and telemetry:
            # bytes the row-delta framing saved vs the dense-R frame each
            # capable replica would otherwise have been sent
            obs.counter("ps.repl_sparse_bytes_saved",
                        **self.hub._mlabels).inc(
                sp_sent * max(0, self._codec.frame_len - sp_frame_len))
        if sp_sent:
            # the live collector's cumulative RΔ series (rate = bytes/s
            # in distkeras-top), under the hub pseudo-worker key like
            # replication_lag below
            self.hub._observe_health(
                f"hub{'' if self.hub.shard_id is None else self.hub.shard_id}",
                "repl_sparse_bytes_total", repl_sparse_total, any_shard=True)
        # commits the hub applied while this publish waited its turn:
        # the feed's real-time backlog (clock reads race commits by
        # design — it is a gauge, not an invariant)
        lag = max(0, self.hub._clock - clock)
        if telemetry:
            obs.histogram("ps.replicate_ms", **self.hub._mlabels).observe(
                (time.perf_counter() - t0) * 1e3)
            obs.gauge("ps_replication_lag", **self.hub._mlabels).set(lag)
        # and into the live collector under the hub's own pseudo-worker
        # key, so the replication-lag-growth detector sees it as a moving
        # series.  NOT behind the registry flag: the health plane has its
        # own opt-in (a worker reporting health activates it), and the
        # fold self-guards to a few None checks when the plane is off.
        # any_shard: the KEY carries the shard — every shard's lag is
        # its own series, and shard N's must not be gated on shard 0
        self.hub._observe_health(
            f"hub{'' if self.hub.shard_id is None else self.hub.shard_id}",
            "replication_lag", lag, any_shard=True)

    def _detach_locked(self, entry: List[Any], cause: BaseException) -> None:
        conn, conn_idx = entry[0], entry[1]
        self._conns.remove(entry)
        warnings.warn(f"replica connection {conn_idx} dropped from the "
                      f"replication feed: {type(cause).__name__}: {cause}")
        try:
            conn.close()
        except OSError:
            pass
        with self.hub._conn_lock:
            if conn in self.hub._conns:
                self.hub._conns.remove(conn)
        if obs.enabled():
            obs.counter("ps_replica_disconnects_total",
                        **self.hub._mlabels).inc()
            self._set_gauge()


# -- adaptive aggregation (ISSUE 10) -------------------------------------------
# The monitoring stack (PR 5/8) can name every async pathology — per-worker
# staleness, stragglers, reconnect storms — but nothing ACTS on any of it.
# The pieces below close that loop hub-side: queued commits merge
# Adasum-style ("Scaling Distributed Training with Adaptive Summation",
# arXiv:2006.02924) instead of applying sequentially, per-worker commit
# scales follow the live staleness series (the DynSGD response of
# arXiv:1611.04581, re-based on the fleet), and reconnect storms are shed
# with retry-after hints instead of absorbed as a thundering herd.  All of
# it rides ``adaptive=True``; the default-off path is byte-identical to the
# pre-adaptive hub.
#
# A "commit" here is a per-leaf parts list aligned with the center: a full
# ndarray for a dense leaf, an ``(ids, grads)`` pair (sorted-unique int64
# ids, ``[k, dim]`` f32 grads) for a sparse leaf — the ONE representation
# the merge rule, the combiner and the replication materialization all
# share, so dense and sparse-row commits compose under the same math.


def _adasum_dot(a_parts: Sequence[Any], b_parts: Sequence[Any]) -> float:
    """Inner product of two commits in the center's flat vector space.
    Sparse x sparse pairs contribute only their intersecting rows."""
    total = 0.0
    for a, b in zip(a_parts, b_parts):
        if isinstance(a, tuple) and isinstance(b, tuple):
            ids_a, ga = a
            ids_b, gb = b
            common, ia, ib = np.intersect1d(ids_a, ids_b,
                                            assume_unique=True,
                                            return_indices=True)
            if common.size:
                total += float(np.dot(ga[ia].ravel(), gb[ib].ravel()))
        elif isinstance(a, tuple) or isinstance(b, tuple):
            raise ValueError("adasum needs matching per-leaf representations"
                             " (dense vs sparse); densify mixed batches "
                             "first")
        else:
            total += float(np.dot(np.asarray(a).ravel(),
                                  np.asarray(b).ravel()))
    return total


def _adasum_normsq(parts: Sequence[Any]) -> float:
    total = 0.0
    for p in parts:
        flat = (p[1] if isinstance(p, tuple) else np.asarray(p)).ravel()
        total += float(np.dot(flat, flat))
    return total


def _scale_parts(parts: Sequence[Any], scale: np.float32) -> List[Any]:
    """One commit scaled by a float32 scalar (sparse rows scale in their
    compact form — idle rows stay implicit zeros)."""
    return [(p[0], p[1] * scale) if isinstance(p, tuple)
            else np.asarray(p) * scale
            for p in parts]


def adasum_pair(a_parts: Sequence[Any], b_parts: Sequence[Any]) -> List[Any]:
    """Adasum combine (arXiv:2006.02924) of two commits:

        merged = (1 - <a,b> / 2|a|^2) * a  +  (1 - <a,b> / 2|b|^2) * b

    — the plain sum when the two are orthogonal (independent progress
    adds), the average when they are parallel (the same step must not
    apply twice), and a smooth interpolation in between that never blows
    the magnitude up.  A zero-norm side passes the other through
    unchanged.  Symmetric in its arguments (the commutativity property
    ``tests/test_adaptive.py`` pins); sparse leaves merge on the union of
    their touched rows, so idle rows cost nothing."""
    na = _adasum_normsq(a_parts)
    nb = _adasum_normsq(b_parts)
    if na == 0.0:
        return list(b_parts)
    if nb == 0.0:
        return list(a_parts)
    dot = _adasum_dot(a_parts, b_parts)
    alpha = np.float32(1.0 - dot / (2.0 * na))
    beta = np.float32(1.0 - dot / (2.0 * nb))
    merged: List[Any] = []
    for a, b in zip(a_parts, b_parts):
        if isinstance(a, tuple):
            ids_a, ga = a
            ids_b, gb = b
            ids = np.union1d(ids_a, ids_b)
            out = np.zeros((ids.size, ga.shape[1]), np.float32)
            if ids_a.size:
                out[np.searchsorted(ids, ids_a)] += alpha * ga
            if ids_b.size:
                out[np.searchsorted(ids, ids_b)] += beta * gb
            merged.append((ids, out))
        else:
            merged.append(alpha * np.asarray(a, np.float32)
                          + beta * np.asarray(b, np.float32))
    return merged


def _mixed_repr(commits: Sequence[Sequence[Any]]) -> bool:
    """True when any leaf is carried sparse ``(ids, grads)`` by one
    commit and dense by another — a full-delta control commit
    interleaving with sparse workers.  The combiner applies such a batch
    SEQUENTIALLY: densifying the sparse sides to merge them would
    materialize whole embedding tables under the center lock (the exact
    cost the row-sparse service exists to avoid)."""
    first = commits[0]
    return any(
        any(isinstance(c[i], tuple) != isinstance(first[i], tuple)
            for c in commits[1:])
        for i in range(len(first)))


def adasum_merge(commits: Sequence[Sequence[Any]]) -> List[Any]:
    """Balanced pairwise-tree Adasum reduction over a batch of commits —
    the one merge rule the adaptive hub applies to every queued batch,
    dense and sparse-row commits alike (per-leaf representations must
    match across the batch; the combiner applies rare mixed batches
    sequentially instead)."""
    items = [list(c) for c in commits]
    if not items:
        raise ValueError("adasum_merge of an empty batch")
    while len(items) > 1:
        nxt = [adasum_pair(items[i], items[i + 1])
               for i in range(0, len(items) - 1, 2)]
        if len(items) % 2:
            nxt.append(items[-1])
        items = nxt
    return items[0]


class AdaptiveRateController:
    """DynSGD-style per-worker commit scaling driven by LIVE health events
    (ISSUE 10; the degradation response of arXiv:1611.04581, re-based on
    the fleet instead of clock zero).

    The adaptive hub subscribes this controller to the process
    :class:`~distkeras_tpu.observability.health.HealthMonitor`
    (:meth:`~distkeras_tpu.observability.health.HealthMonitor.subscribe`
    — push, not polling); each staleness/straggler event naming a worker
    updates that worker's multiplicative commit scale — composed ON TOP
    of the algorithm's own ``commit_scale(staleness)`` — from the
    event's rolling-series evidence:

    - ``staleness_drift`` (rolling mean vs fleet median):
      ``(median + 1) / (mean + 1)``;
    - ``staleness_spike`` (latest vs own rolling baseline):
      ``(baseline + 1) / (staleness + 1)``;
    - ``straggler`` (window wall vs fleet median): ``1 / factor``.

    Scales clamp to ``[floor, 1.0]`` and EXPIRE after ``hold_s`` without
    a refreshing event — detector cooldowns re-fire while a condition
    persists, so a still-sick worker stays scaled and a recovered one
    drifts back to 1.0.  Verdicts are kept PER EVENT KIND (the applied
    scale is the min across a worker's unexpired kinds): a fresh event
    of one kind REPLACES that kind's verdict — so a worker that improves
    from severe to mild tracks the improving evidence — while a severe
    verdict from another detector keeps its own clock and is never
    silently extended by a weaker one.  ``scale_for`` is the commit
    path's one dict read under a short lock."""

    def __init__(self, floor: float = 0.1, hold_s: float = 30.0):
        self.floor = float(floor)
        self.hold_s = float(hold_s)
        self._lock = threading.Lock()
        # (worker, event kind) -> (scale, expires_monotonic)
        self._scales: Dict[Tuple[str, str], Tuple[float, float]] = {}

    def _propose(self, worker: str, kind: str, scale: float) -> None:
        scale = min(1.0, max(self.floor, float(scale)))
        with self._lock:
            self._scales[(worker, kind)] = (scale,
                                            time.monotonic() + self.hold_s)

    def on_event(self, event: Any) -> None:
        """:meth:`HealthMonitor.subscribe` callback.  Malformed evidence
        is ignored — adaptation must never take down the path that
        emitted the event."""
        worker = getattr(event, "worker", None)
        if worker is None:
            return
        ev = getattr(event, "evidence", None) or {}
        try:
            kind = event.kind
            if kind == "staleness_drift":
                self._propose(worker, kind,
                              (float(ev.get("fleet_median", 0.0)) + 1.0)
                              / (float(ev.get("staleness_mean", 0.0)) + 1.0))
            elif kind == "staleness_spike":
                self._propose(worker, kind,
                              (float(ev.get("baseline", 0.0)) + 1.0)
                              / (float(ev.get("staleness", 0.0)) + 1.0))
            elif kind == "straggler":
                self._propose(worker, kind,
                              1.0 / max(1.0, float(ev.get("factor", 1.0))))
        except (TypeError, ValueError):
            return

    def scale_for(self, worker: Any) -> float:
        """The live multiplicative scale for one worker: the min across
        its unexpired per-kind verdicts (1.0 when unknown, unattributed,
        or fully expired)."""
        if worker is None:
            return 1.0
        wkey = str(worker)
        now = time.monotonic()
        scale = 1.0
        with self._lock:
            for (w, kind), (s, expires) in list(self._scales.items()):
                if now >= expires:
                    del self._scales[(w, kind)]
                elif w == wkey:
                    scale = min(scale, s)
        return scale

    def snapshot(self) -> Dict[str, float]:
        """Live (unexpired) per-worker scales (min across kinds),
        JSON-safe."""
        now = time.monotonic()
        out: Dict[str, float] = {}
        with self._lock:
            for (w, _), (s, exp) in self._scales.items():
                if exp > now:
                    out[w] = min(out.get(w, 1.0), s)
        return out


class _AdaptiveCombiner:
    """Flat-combining commit application for an adaptive hub (ISSUE 10).

    A plain hub serializes commits behind the center lock: while one
    handler applies, the others block, and the fleet experiences the
    queue as added staleness.  With ``adaptive=True`` every commit is
    SUBMITTED here instead: each submitter enqueues its (parts, pull
    clock, worker) and races for the drain lock; the winner grabs
    everything queued at that instant as one BATCH, scales each member
    by its own ``commit_scale(staleness)`` x the per-worker adaptive
    rate, merges the batch pairwise Adasum-style (:func:`adasum_merge`)
    and applies the merged delta as ONE center update.  Losers find
    their entry already applied when they get the lock and return
    immediately — commits that would have queued are combined, and an
    uncontended hub degenerates to batches of one (whose apply is
    bit-identical to the plain path at scale 1).

    Clock semantics: a batch of K commits still advances the commit
    clock and ``num_updates`` by K — staleness bookkeeping, elastic
    denominators and the zero-acked-loss failover bound keep their
    meaning; all members of a batch see the same base clock (they apply
    simultaneously by construction).

    Replication: the batch's merged delta is materialized center-shaped
    and published as ONE ``R`` frame at the batch's final clock BEFORE
    any member is acked, so a standby's center tracks the primary bit
    for bit (its ``num_updates`` counts feed frames, not logical
    commits — the CLOCK remains the failover bound, as before)."""

    def __init__(self, hub: "SocketParameterServer",
                 rate: Optional[AdaptiveRateController] = None):
        self.hub = hub
        self.rate = rate
        self._qlock = threading.Lock()
        self._drain = threading.Lock()
        self._queue: List[Dict[str, Any]] = []
        self.batches_total = 0
        self.merged_total = 0  # commits folded into a larger batch
        self.max_batch = 0

    def commit(self, parts: Sequence[Any], last_pull_clock: int,
               worker: Any = None) -> Dict[str, Any]:
        """Submit one commit; returns its entry once APPLIED (and, when a
        replica is attached, published), carrying the staleness and
        scale it applied with.  The caller's buffers must stay valid
        until return — handler threads block right here, so wire views
        into their receive buffers are safe."""
        entry: Dict[str, Any] = {"parts": list(parts),
                                 "clock": int(last_pull_clock),
                                 "worker": worker, "done": False,
                                 "error": None,
                                 "staleness": 0, "fenced": False,
                                 "fence": 0, "scale": 1.0,
                                 "rate_scale": 1.0, "batch": 1}
        with self._qlock:
            self._queue.append(entry)
        with self._drain:
            # the drain lock's release/acquire orders a predecessor's
            # apply (and its done/error writes) before these reads.
            # Invariant: an entry is either still in the queue (we will
            # grab it below) or was grabbed by a predecessor, which
            # marked it done or error before releasing — so the batch we
            # grab always contains our own entry
            if not entry["done"] and entry["error"] is None:
                with self._qlock:
                    batch, self._queue = self._queue, []
                try:
                    self._apply_batch(batch)
                except BaseException as e:
                    # a failed batch must not strand its members: mark
                    # every un-applied entry so each submitter RAISES
                    # (its connection drops / its worker sees the error
                    # — never a false ack for a commit that was dropped)
                    for en in batch:
                        if not en["done"]:
                            en["error"] = e
                    raise
        err = entry["error"]
        if err is not None:
            raise err
        return entry

    def _apply_batch(self, batch: List[Dict[str, Any]]) -> None:
        hub = self.hub
        telemetry = obs.enabled()
        t0_ns = time.perf_counter_ns() if telemetry else 0
        with hub._lock:
            # the replicate decision is made UNDER the center lock, like
            # _apply_commit_locked's: a replica attaching concurrently
            # registers BEFORE snapshotting the center under this same
            # lock, so either its sync includes this batch or active()
            # is already True here and the batch is published — deciding
            # earlier could lose the batch delta to a replica whose sync
            # predates the apply
            feed = hub._feed
            replicate = feed is not None and feed.active()
            clock0 = hub._clock
            fence = hub._clock_fence
            scaled_all: List[List[Any]] = []
            for entry in batch:
                lpc = entry["clock"]
                if lpc < fence:
                    lpc = fence
                    entry["fenced"] = True
                    entry["fence"] = fence
                staleness = clock0 - lpc
                wscale = (self.rate.scale_for(entry["worker"])
                          if self.rate is not None else 1.0)
                scale = float(hub.commit_scale(staleness)) * wscale
                entry["staleness"] = staleness
                entry["scale"] = scale
                entry["rate_scale"] = wscale
                entry["batch"] = len(batch)
                scaled_all.append(
                    _scale_parts(entry["parts"], np.float32(scale)))
                if telemetry:
                    hub._touch_rows_locked(
                        (i, p[0]) for i, p in enumerate(entry["parts"])
                        if isinstance(p, tuple))
            if len(scaled_all) > 1 and not _mixed_repr(scaled_all):
                applied = [adasum_merge(scaled_all)]
            else:
                # batch of one — or the RARE mixed dense/sparse batch,
                # applied sequentially (plain queue-order semantics):
                # merging it would densify sparse sides under this lock
                applied = scaled_all
            if replicate and len(applied) > 1:
                # the RARE sequential (mixed dense/sparse) batch keeps the
                # pre-ISSUE-15 replica contract: ONE center-shaped delta
                # for the whole batch, applied exactly as published, so
                # primary and replica perform IDENTICAL float additions
                dense = [np.zeros_like(c) for c in hub.center]
                for parts in applied:
                    for full, p in zip(dense, parts):
                        if isinstance(p, tuple):
                            ids, g = p
                            if ids.size:
                                full[ids] += g
                        else:
                            full += p
                for c, full in zip(hub.center, dense):
                    c += full
                publish_parts = dense
            else:
                # ONE commit (uncontended, or the whole batch Adasum-
                # merged): apply in its native representation — sparse
                # leaves touch only their merged ROW UNION — and hand the
                # same parts to the feed, which frames them sparse for
                # capable replicas (cost ∝ touched rows) and densifies
                # only for legacy ones (_scale_parts/adasum own storage)
                publish_parts = applied[0] if replicate else None
                for parts in applied:
                    for c, p in zip(hub.center, parts):
                        if isinstance(p, tuple):
                            ids, g = p
                            if ids.size:
                                c[ids] += g
                        else:
                            c += p
            hub.num_updates += len(batch)
            hub._clock += len(batch)
            commit_clock = hub._clock
        if replicate:
            feed.publish(commit_clock, publish_parts)
        size = len(batch)
        self.batches_total += 1
        if size > self.max_batch:
            self.max_batch = size
        if size > 1:
            self.merged_total += size - 1
        if telemetry:
            obs.gauge("ps_merge_queue_depth", **hub._mlabels).set(size)
            obs.histogram("ps.merge_batch", **hub._mlabels).observe(size)
            if size > 1:
                obs.counter("ps_merged_commits_total",
                            **hub._mlabels).inc(size - 1)
            fenced = sum(1 for e in batch if e["fenced"])
            if fenced:
                obs.counter("ps_fenced_commits_total",
                            **hub._mlabels).inc(fenced)
            obs.TRACER.record_span("ps.merge", t0_ns,
                                   time.perf_counter_ns(), batch=size,
                                   **hub._shard_attrs)
        # live health plane: applied scale joins each worker's series and
        # the batch size joins the hub pseudo-worker's — distkeras-top's
        # SCALE / MQ columns and fleet_report["adaptive"] read these
        for entry in batch:
            if entry["rate_scale"] < 1.0:
                if telemetry:
                    obs.counter("ps_rate_scaled_commits_total",
                                **hub._mlabels).inc()
            if entry["worker"] is not None:
                hub._observe_health(entry["worker"], "adaptive_scale",
                                    entry["rate_scale"])
        hub._observe_health(
            f"hub{'' if hub.shard_id is None else hub.shard_id}",
            "merge_queue_depth", size, any_shard=True)
        for entry in batch:
            entry["done"] = True


class JobAdmissionError(net.ProtocolError):
    """The hub rejected a client's job-scoped announce (ISSUE 19): the
    shard's job slots or memory/throughput budget are exhausted.  A
    distinct type so callers can tell an admission verdict from a torn
    stream; it still subclasses ``ProtocolError``, so a mid-run
    re-announce rejection (reconnect landing on a full hub) rides the
    normal retry/rotate machinery instead of escaping uncaught."""

    def __init__(self, job: str, reason: str):
        super().__init__(f"job {job!r} admission rejected: {reason}")
        self.job = job
        self.reason = reason


class _JobState:
    """One admitted non-default job namespace (ISSUE 19): a private copy
    of the center (seeded from the hub's center at admission time) with
    its own commit clock.  Every field is guarded by the owning hub's
    center lock — job commits take the SAME lock as default-job commits,
    so fairness is lock-scheduling fairness, and a job's state can never
    tear against an admission or a snapshot cut.

    Deliberately OUTSIDE the adaptive combiner, replication feed and
    snapshot plane: isolation is the point of the namespace — one job's
    machinery must not move another job's latency — and HA/persistence
    for secondary jobs is future work (documented in MIGRATION.md)."""

    __slots__ = ("job", "center", "clock", "num_updates")

    def __init__(self, job: str, center: Sequence[np.ndarray]):
        self.job = job
        self.center = [np.array(w, dtype=np.float32) for w in center]
        self.clock = 0
        self.num_updates = 0


class SocketParameterServer:
    """Hub-and-spoke PS: one handler thread per worker connection, one lock
    around the center variable — the reference's concurrency model
    (SURVEY §3.4), minus pickle and minus the GIL-heavy payload decode.

    Telemetry (``distkeras_tpu.observability``, off by default): pull/
    commit counts and payload bytes (``ps_pulls_total``,
    ``ps_commits_total``, ``ps_pull_bytes_total``,
    ``ps_commit_bytes_total``), per-RPC handler latency
    (``ps_rpc_seconds{rpc=...}``) and the per-connection staleness gauge
    ``ps_staleness{conn=N}`` (N is the hub's accept ordinal modulo 256 —
    workers carry no identity on the wire, and the wrap bounds label
    cardinality under elastic connection churn) — the commit clock the paper lineage's
    staleness analysis (arXiv:1611.04581) is about, now a live signal
    instead of a number internal to DynSGD's scaling rule.  Instruments
    are looked up per RPC while telemetry is on (a dict get next to a
    socket exchange) so a mid-run ``obs.reset()`` cannot orphan them, and
    nothing is registered at all while telemetry is off."""

    # reconnect-storm backpressure tuning (adaptive hubs, ISSUE 10):
    # >= STORM_HELLOS reconnect hellos (action G) inside STORM_WINDOW_S
    # arm shedding for STORM_SHED_S; each hello during shedding is handed
    # the next RETRY_BASE_MS slot, capped at RETRY_CAP_MS — the herd is
    # spread over time instead of absorbed at once.  Instance attributes,
    # so tests and deployments can retune without subclassing
    STORM_HELLOS = 3
    STORM_WINDOW_S = 5.0
    STORM_SHED_S = 3.0
    RETRY_BASE_MS = 50
    RETRY_CAP_MS = 2000

    def __init__(self, weights: Sequence[np.ndarray], host: str = "0.0.0.0", port: int = 0,
                 idle_timeout: Optional[float] = 300.0,
                 snapshot_dir: Optional[str] = None,
                 snapshot_interval: float = 30.0,
                 snapshot_keep: int = 3,
                 restore: bool = False,
                 shard_id: Optional[int] = None,
                 replica_of: Optional[Tuple[str, int]] = None,
                 replica_feed_retries: int = 3,
                 replica_feed_backoff: float = 0.2,
                 sparse_leaves: Sequence[int] = (),
                 adaptive: bool = False,
                 shm_dir: Optional[str] = None,
                 recv_batch_depth: int = 0,
                 max_jobs: int = 4,
                 job_budget_bytes: Optional[int] = None):
        self.center: List[np.ndarray] = [np.array(w, dtype=np.float32) for w in weights]
        self.host = host
        self.port = int(port)
        self.num_updates = 0
        # sharded-hub identity (ISSUE 6): when this hub serves one shard of
        # a partitioned center, every span and metric it emits carries the
        # shard label so a slow shard is as nameable as a slow worker —
        # and so per-shard counters stay separate series that aggregators
        # can sum (bytes) or max (logical commits) without double-counting.
        # None (the default, and the whole num_shards=1 path) emits the
        # exact pre-sharding unlabeled series
        self.shard_id = None if shard_id is None else int(shard_id)
        self._shard_attrs = ({} if shard_id is None
                             else {"shard": int(shard_id)})
        self._mlabels = ({} if shard_id is None
                         else {"shard": str(int(shard_id))})
        self._clock = 0  # total commits applied (DynSGD's global clock)
        # restore-time fence: connections and inproc clients born before a
        # hub restart carry pull clocks from the PREVIOUS incarnation;
        # clamping them here re-bases their staleness at the restart point
        # instead of letting a pre-restart clock fake a huge (DynSGD) or
        # negative staleness
        self._clock_fence = 0
        self._lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._handlers: List[threading.Thread] = []
        self._conns: List[socket.socket] = []  # live worker connections
        self._conn_lock = threading.Lock()
        self._running = False
        self._center_bytes = sum(w.nbytes for w in self.center)
        # row-sparse embedding tables (ISSUE 9): leaf indices whose PS
        # traffic is row-sparse — pulled by row set (action S/V) and
        # committed as (row_ids, row_grads) pairs (action U/X) under the
        # SAME staleness clock and commit_scale rules as dense commits.
        # Empty (the default) keeps every path byte-for-byte pre-sparse;
        # a sparse-capable hub still serves the full dense P/C/Q exchange
        # too (initial syncs, control clients, un-upgraded workers)
        self.sparse_leaves = tuple(sorted({int(i) for i in sparse_leaves}))
        for i in self.sparse_leaves:
            if not 0 <= i < len(self.center):
                raise ValueError(f"sparse leaf index {i} out of range for "
                                 f"{len(self.center)} center leaves")
            if self.center[i].ndim != 2:
                raise ValueError(
                    f"sparse leaf {i} must be a [rows, dim] table, got "
                    f"shape {self.center[i].shape}")
        self._sparse_set = frozenset(self.sparse_leaves)
        # hyperscale row-touch telemetry (ISSUE 15): one exponentially-
        # decayed per-row touch counter array per sparse table, folded on
        # every sparse pull/commit UNDER the center lock (the ids are
        # already validated there) while telemetry is on.  Every
        # TOUCH_DECAY_EVERY folds the counters halve; the count of rows
        # still at or above TOUCH_HOT_MIN is then a decayed estimate of
        # the live hot set — the ``ps.sparse_hot_rows{table=}`` gauge an
        # operator sizes ``sparse_cache_rows`` from.  Cost when off: one
        # enabled() check per sparse request; memory: 4 bytes/row/table
        # (dim/4 of the table the hub already holds)
        self._sparse_touch: Dict[int, np.ndarray] = {
            i: np.zeros(self.center[i].shape[0], np.float32)
            for i in self.sparse_leaves}
        self._touch_folds = 0
        # full flat-frame size of a pull reply / f32 commit (header, action,
        # count, per-tensor prefixes, payload) — the socket-buffer hint.
        # A shard hub computes this from ITS center subset, so per-shard
        # connections get per-shard-sized kernel buffers
        self._frame_bytes = net.tensor_frame_len(self.center)
        # largest VALID payload a peer may declare — the handler receives
        # against this bound, so a garbage length prefix is a typed
        # ProtocolError instead of a 16 GiB bytearray.  The accounting is
        # SHARED with the C++ hub (net.max_request_payload), so both hub
        # implementations reject the exact same oversized prefixes
        self._max_payload = net.max_request_payload(self.center,
                                                    self.sparse_leaves)
        # zero-copy shm transport (ISSUE 18): a directory to create ring
        # files in arms the action-Z attach handshake — same-host clients
        # constructed with shm=True move their framed byte stream through
        # a pair of mmap SPSC rings instead of the kernel socket stack.
        # None (the default) declines every Z request, byte-identical to
        # a pre-Z hub from the client's point of view
        self.shm_dir = None if shm_dir is None else str(shm_dir)
        self._shm_seq = 0  # ring-file ordinal (under _conn_lock)
        # batched receive (ISSUE 18): >0 sizes a per-connection
        # BatchedReceiver to that many frames — a commit storm is drained
        # with one syscall per batch (recvmmsg when libc has it) instead
        # of one per frame.  0 (the default) keeps the per-frame
        # recv_frame_into path untouched
        self.recv_batch_depth = max(0, int(recv_batch_depth))
        # multi-job service (ISSUE 19): a session that puts a ``job_ns``
        # key on its T announce gets an admission-controlled private
        # center namespace (dense P/C/Q only).  Admission projects the
        # shard's memory cost — one center copy per job plus the decayed
        # hot-row working set from the PR-14 touch counters — against
        # ``job_budget_bytes`` (default 4x the center) and caps the job
        # count at ``max_jobs``.  A session that never announces a
        # job_ns rides the default namespace: the hub's own center,
        # byte-for-byte the pre-multi-job exchange
        self.max_jobs = max(0, int(max_jobs))
        self.job_budget_bytes = (4 * max(1, self._center_bytes)
                                 if job_budget_bytes is None
                                 else int(job_budget_bytes))
        self._jobs: Dict[str, _JobState] = {}  # under _lock
        self.jobs_admitted = 0
        self.jobs_rejected = 0
        self._conn_seq = 0  # connection ordinal -> staleness gauge label
        # half-open liveness: a peer that dies without FIN used to park its
        # handler in recv() forever.  With idle_timeout set, a connection
        # silent for that long (no pull/commit/heartbeat) is evicted
        self.idle_timeout = None if idle_timeout is None else float(idle_timeout)
        # live-worker membership (elastic denominators): a connection joins
        # on its first commit — pull-only peers (snapshot readers, final
        # center fetches) never count — is touched by every action, and
        # leaves on disconnect or idle eviction
        self._members: Dict[int, float] = {}
        self._member_lock = threading.Lock()
        self._member_seq = 0
        # hot-standby HA (ISSUE 7).  Primary side: the replication feed is
        # created lazily when a replica handshakes (action R), so an
        # unreplicated hub's commit path is byte-for-byte the pre-HA one.
        # Replica side: replica_of=(host, port) starts this hub in STANDBY
        # — it binds and serves pulls like any hub (clients can fail over
        # to it at any time) while a feed thread tracks the primary's
        # center; it PROMOTES itself (arming the PR-4 clock fence at its
        # current clock) when the feed is lost past the retry budget, or
        # immediately when a failed-over worker commits to it
        self._feed: Optional[ReplicationFeed] = None
        self._feed_lock = threading.Lock()
        # live health plane (ISSUE 8): bound lazily on the FIRST action-M
        # report — a hub no worker reports to never imports the health
        # module, and the commit path's only cost is one `is None` check
        self._health: Optional[Any] = None
        self._health_monitor: Optional[Any] = None
        self._health_mod: Optional[Any] = None  # cached module ref (peek path)
        # telemetry-driven adaptive aggregation (ISSUE 10), OFF by
        # default — the off path is byte-identical to the pre-adaptive
        # hub (no combiner, no health subscription, no new wire frames).
        # On: queued commits merge Adasum-style through the combiner,
        # per-worker commit scales follow live health events, and
        # reconnect hellos (action G) are answered with retry-after
        # hints while a reconnect storm is live
        self.adaptive = bool(adaptive)
        self._rate: Optional[AdaptiveRateController] = None
        self._combiner: Optional[_AdaptiveCombiner] = None
        self._health_unsub: Optional[Any] = None
        self._bp_lock = threading.Lock()
        self._hello_times: Deque[float] = deque()
        self._storm_until = 0.0
        self._retry_seq = 0
        self.backpressure_hints = 0  # nonzero hints issued (drills read it)
        if self.adaptive:
            self._rate = AdaptiveRateController()
            self._combiner = _AdaptiveCombiner(self, self._rate)
        self.replica_of = (None if replica_of is None
                           else (str(replica_of[0]), int(replica_of[1])))
        self.replica_feed_retries = int(replica_feed_retries)
        self.replica_feed_backoff = float(replica_feed_backoff)
        self._standby = self.replica_of is not None
        self.promoted = False
        # the replica's clock AT promotion — the number the zero
        # acked-commit-loss bound is checked against (reading num_updates
        # later is vacuous: post-failover commits inflate it)
        self.promoted_at_clock: Optional[int] = None
        self._synced = threading.Event()  # set on the first applied REPL_SYNC
        self._replica_stop = threading.Event()
        self._replica_thread: Optional[threading.Thread] = None
        self._replica_sock: Optional[socket.socket] = None
        self.snapshotter: Optional[HubSnapshotter] = None
        self._restore = bool(restore)
        if restore and snapshot_dir is None:
            # silently serving FRESH weights after an operator asked for a
            # restore would discard a job's progress without a sound
            raise ValueError("restore=True requires snapshot_dir")
        if snapshot_dir is not None:
            self.snapshotter = HubSnapshotter(self, snapshot_dir,
                                              interval=snapshot_interval,
                                              keep=snapshot_keep)

    # -- lifecycle (reference: ParameterServer.start/stop) ---------------------
    def start(self) -> None:
        if self.adaptive:
            # bind the health plane eagerly and SUBSCRIBE (ISSUE 10): the
            # per-commit staleness folds need a collector from commit
            # one, and the rate controller / storm shedding must hear
            # detector events the moment they fire — push, not polling
            from distkeras_tpu.observability import health as _health

            self._health_mod = _health
            if self._health is None:
                self._health = _health.collector()
            if self._health_monitor is None:
                self._health_monitor = _health.monitor()
            self._health_unsub = self._health_monitor.subscribe(
                self._on_health_event)
        if self._restore and self.snapshotter is not None:
            # load BEFORE binding: the first pull any worker lands must
            # already observe the restored center and fenced clock
            if not self.snapshotter.restore_latest():
                if self.snapshotter.checkpointer.all_steps():
                    # progress exists on disk but none of it is readable —
                    # binding anyway would hand workers a fresh center and
                    # silently discard the job; that needs a human
                    raise RuntimeError(
                        f"restore requested: snapshots exist in "
                        f"{self.snapshotter.checkpointer.directory} but none "
                        f"is readable (see warnings)")
                # no snapshot yet (first boot under a restart-with-restore
                # supervisor loop): serving initial weights is correct,
                # but say so
                warnings.warn("restore requested but no snapshot exists "
                              "yet; serving initial weights")
        if self.shm_dir is not None:
            os.makedirs(self.shm_dir, exist_ok=True)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(128)
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        if self.replica_of is not None:
            self._replica_stop.clear()
            self._replica_thread = threading.Thread(target=self._replica_loop,
                                                    daemon=True)
            self._replica_thread.start()
        if self.snapshotter is not None:
            self.snapshotter.start()

    def stop(self) -> None:
        self._shutdown(final_snapshot=True)

    def kill(self) -> None:
        """Crash-like teardown for chaos tests and recovery drills: sever
        everything WITHOUT a final snapshot — recovery must come from the
        last periodic snapshot, exactly as after a SIGKILL.  (From the
        workers' side this is indistinguishable from a killed process:
        connections reset mid-exchange, port goes dark.)"""
        self._shutdown(final_snapshot=False)

    def _shutdown(self, final_snapshot: bool) -> None:
        self._running = False
        if self._health_unsub is not None and self._health_monitor is not None:
            # a stopped hub must not keep reacting to a later run's events
            self._health_monitor.unsubscribe(self._health_unsub)
            self._health_unsub = None
        # stop tracking the primary BEFORE severing anything: a teardown
        # must never race the feed thread into a promotion
        self._replica_stop.set()
        sock = self._replica_sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if self.snapshotter is not None:
            # on stop(): final snapshot while the center is still intact
            # (commits may still be landing — snapshot_state copies under
            # the lock); on kill(): just halt the periodic thread
            self.snapshotter.stop(final_snapshot=final_snapshot)
        if self._listener is not None:
            try:
                # shutdown BEFORE close: close() alone does not wake a
                # thread blocked in accept() on Linux, so every stop()
                # silently burned the full join timeout and leaked the
                # accept thread.  shutdown() fails the pending accept
                # immediately (same idiom as the C++ hub's stop())
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # not listening / already gone; close still applies
            try:
                self._listener.close()
            except OSError:
                pass
        # sever live worker connections (matching the C++ hub): a blocked
        # handler wakes with EOF and exits, and the worker's next receive
        # surfaces a clean ConnectionError instead of hanging on a hub
        # that will never reply — the fault-injection behavior
        # tests/test_runtime.py pins
        with self._conn_lock:
            for conn in list(self._conns):
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        if self._replica_thread is not None:
            self._replica_thread.join(timeout=5)
            self._replica_thread = None
        for t in self._handlers:
            t.join(timeout=5)

    def get_weights(self) -> List[np.ndarray]:
        with self._lock:
            return [w.copy() for w in self.center]

    # -- durability (hub snapshots + clock fence) ------------------------------
    def snapshot_state(self) -> Tuple[List[np.ndarray], Dict[str, Any]]:
        """One atomic view of everything a restarted hub needs: (center
        copy, state dict).  The state rides the snapshot's JSON metadata,
        so it must stay JSON-typed."""
        with self._lock:
            return self._snapshot_state_locked()

    def _snapshot_state_locked(self) -> Tuple[List[np.ndarray], Dict[str, Any]]:
        """:meth:`snapshot_state` body, caller holds the center lock — the
        coordinated snapshot barrier holds EVERY shard's lock at once and
        reads each shard through this, so the N per-shard snapshots are
        one causal cut (no commit can land anywhere between the reads)."""
        center = [w.copy() for w in self.center]
        state = {"clock": int(self._clock),
                 "num_updates": int(self.num_updates)}
        state.update(self._algo_state())
        return center, state

    def _algo_state(self) -> Dict[str, Any]:
        """Subclass hook: algorithm state to persist alongside the center
        (called under the center lock)."""
        return {}

    def restore_state(self, center: Sequence[np.ndarray],
                      state: Dict[str, Any]) -> None:
        """Load a snapshot: center in place (buffer identity preserved — the
        frame-size accounting and any live codecs stay valid), clock
        resumed, and the clock FENCE armed at the restored clock so any
        pre-restart pull clock presented to :meth:`commit_direct` is
        clamped to the restart point."""
        if len(center) != len(self.center):
            raise ValueError(f"snapshot has {len(center)} tensors, center has "
                             f"{len(self.center)}")
        with self._lock:
            for c, w in zip(self.center, center):
                c[...] = np.asarray(w, np.float32).reshape(c.shape)
            self._clock = int(state.get("clock", 0))
            self._clock_fence = self._clock
            self.num_updates = int(state.get("num_updates", 0))

    # -- hot standby (replica side) --------------------------------------------
    def is_standby(self) -> bool:
        """True while this hub is a replica tracking its primary (not yet
        promoted): its center is feed-driven and commits will trigger
        promotion."""
        return self._standby

    def _standby_commit_gate(self) -> None:
        """Split-brain guard: a commit arriving while the feed socket is
        still CONNECTED must not flip the hub — one misdirected worker
        landing on the standby while the other workers keep committing to
        the healthy primary would cause permanent divergence.  The commit
        is refused, and the connected feed socket is severed as a probe: a
        live primary resyncs and the hub stays standby, a silently dead
        one (host loss, no FIN) now fails the feed loop's reconnects and
        promotes within its budget — after which the worker's retried
        commit (under its own reconnect budget) lands.

        When the feed is already DOWN (``_replica_sock is None`` — the
        loop observed a loss and is between reconnect attempts) the
        primary is presumed dead and the gate returns: the caller
        promotes immediately, fence armed before the commit's staleness
        is computed, instead of making the failed-over worker wait out
        ``replica_feed_retries``.  Called with ``_synced`` already
        checked."""
        sock = self._replica_sock
        if sock is None:
            return  # feed lost: caller promotes (first failed-over commit)
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        raise net.ProtocolError(
            "commit into a standby refused (not promoted yet; verifying "
            "the primary — retry)")

    def wait_synced(self, timeout: Optional[float] = None) -> bool:
        """Block until this replica has applied its first full sync from
        the primary (True), or ``timeout`` elapsed (False).  Callers that
        are about to COMMIT into a freshly-started standby — e.g. a
        trainer whose own hub is a ``replica_of`` — must wait here first:
        a commit into an unsynced standby promotes it over its fresh init
        weights, silently discarding the primary's state."""
        return self._synced.wait(timeout)

    def promote(self, reason: str = "manual") -> bool:
        """Promote a standby replica to primary: arm the clock fence at the
        current (replicated) clock — so pre-failover pull clocks presented
        after the switch are clamped to the promotion point, exactly the
        PR-4 restore semantics — and stop applying feed frames forever.
        Idempotent; returns True if this call performed the promotion."""
        with self._lock:
            if not self._standby or self.promoted:
                return False
            self.promoted = True
            self._standby = False
            self._clock_fence = self._clock
            clock = self._clock
            self.promoted_at_clock = clock
        t0_ns = time.perf_counter_ns()
        warnings.warn(f"replica hub promoting to primary at clock {clock}: "
                      f"{reason}")
        # the feed thread must stop (and never re-apply a late frame —
        # promoted is checked under the lock before every apply)
        self._replica_stop.set()
        sock = self._replica_sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if obs.enabled():
            obs.counter("ps_promotions_total", **self._mlabels).inc()
            obs.TRACER.record_span("ps.promote", t0_ns,
                                   time.perf_counter_ns(),
                                   clock=clock, reason=reason,
                                   **self._shard_attrs)
        # live health plane (ISSUE 8): a promotion IS a failover event —
        # record it through the process monitor so distkeras-top / the
        # punchcard health pull see it DURING the run, naming the promoted
        # standby.  Promotion is rare (never the hot path) and must not be
        # taken down by a health-pipeline hiccup
        try:
            from distkeras_tpu.observability import health as _health

            _health.monitor().emit(
                "failover", "critical", shard=self.shard_id,
                dedup=f"promote:{self.host}:{self.port}",
                promoted=f"{self.host}:{self.port}", clock=clock,
                reason=reason)
        except Exception:
            pass
        return True

    def _apply_repl_frame(self, clock: int, kind: int, blobs) -> None:
        """Apply one replication frame of ANY kind under the center lock —
        the sparse-capable standby's receive leg.  ``blobs`` are the
        frame's tensor blobs past the header (views into the feed's
        receive buffer, consumed before the next frame lands).  A
        REPL_SPARSE frame carries the U-commit layout: full f32 delta
        blobs for dense leaves, ``(ids, scaled rows)`` blob pairs for
        sparse leaves — applied ``center[ids] += rows`` behind the same
        clock fence semantics as a dense delta.  Malformed layouts raise
        ``ProtocolError`` (feed loss; the loop reconnects/promotes under
        its budget)."""
        with self._lock:
            if self.promoted:
                return  # late frame post-promotion: never lands
            if kind in (net.REPL_SYNC, net.REPL_DELTA):
                if len(blobs) != len(self.center):
                    raise net.ProtocolError(
                        f"replication frame has {len(blobs)} blobs, center "
                        f"has {len(self.center)}")
                for c, b in zip(self.center, blobs):
                    arr = np.frombuffer(b, np.float32)
                    if arr.size != c.size:
                        raise net.ProtocolError(
                            f"replication blob of {arr.size} values does "
                            f"not match its leaf ({c.size})")
                    if kind == net.REPL_SYNC:
                        c[...] = arr.reshape(c.shape)
                    else:
                        c += arr.reshape(c.shape)
                if kind == net.REPL_SYNC:
                    self._clock = clock
                    self.num_updates = clock
                    self._synced.set()
                else:
                    self._clock = max(self._clock, clock)
                    self.num_updates += 1
            elif kind == net.REPL_SPARSE:
                expected = len(self.center) + len(self.sparse_leaves)
                if len(blobs) != expected:
                    raise net.ProtocolError(
                        f"sparse replication frame has {len(blobs)} blobs, "
                        f"expected {expected}")
                it = iter(blobs)
                for i, c in enumerate(self.center):
                    if i in self._sparse_set:
                        ids = self._check_row_ids(
                            np.frombuffer(next(it), net.ROW_ID_DTYPE), i)
                        rows = np.frombuffer(next(it), np.float32)
                        if rows.size != ids.size * c.shape[1]:
                            raise net.ProtocolError(
                                f"sparse replication leaf {i}: {rows.size} "
                                f"values for {ids.size} rows of dim "
                                f"{c.shape[1]}")
                        if ids.size:
                            c[ids] += rows.reshape(ids.size, c.shape[1])
                    else:
                        arr = np.frombuffer(next(it), np.float32)
                        if arr.size != c.size:
                            raise net.ProtocolError(
                                f"replication blob of {arr.size} values "
                                f"does not match its leaf ({c.size})")
                        c += arr.reshape(c.shape)
                self._clock = max(self._clock, clock)
                self.num_updates += 1
            else:
                raise net.ProtocolError(f"unknown replication kind {kind}")

    def _replica_loop(self) -> None:
        """Track the primary: connect, handshake (action R hello), apply the
        full sync then every streamed delta under the center lock.  On feed
        loss, retry within ``replica_feed_retries`` (exponential backoff);
        once the budget is gone the primary is presumed dead and the
        replica promotes itself.  A worker commit arriving first wins the
        promotion race instead (see the commit paths)."""
        host, port = self.replica_of
        codec = net.FlatFrameCodec(net.repl_frame_templates(self.center))
        hdr = np.empty(9, np.uint8)
        bufs = [np.empty(c.shape, np.float32) for c in self.center]
        # a sparse-capable standby (this hub serves row-sparse tables)
        # announces REPL_CAP_SPARSE and receives through the generic
        # variable-frame path: the stream then mixes fixed-size
        # SYNC/DELTA frames with row-delta REPL_SPARSE frames whose blob
        # sizes vary per commit.  A dense hub keeps the pre-ISSUE-15
        # fixed-codec loop byte for byte
        sparse_feed = bool(self.sparse_leaves)
        caps = net.REPL_CAP_SPARSE if sparse_feed else 0
        # largest valid feed payload: a full sync frame plus, for sparse
        # frames, one worst-case id blob per table
        feed_limit = codec.payload_len + sum(
            8 + 8 * self.center[i].shape[0] for i in self.sparse_leaves)
        rx = bytearray(4096) if sparse_feed else None
        failures = 0
        warned_unsynced = False
        while not self._replica_stop.is_set():
            try:
                # a short connect timeout: _shutdown cannot interrupt a
                # thread blocked INSIDE connect (the socket object does
                # not exist yet), so this bounds how long a stopping
                # standby's feed thread can outlive it
                sock = net.connect(host, port, timeout=5.0,
                                   payload_hint=codec.frame_len)
                # the connect timeout must NOT linger as a recv timeout:
                # the feed is silent between commits (no heartbeat), and a
                # 30 s idle primary would otherwise read as feed loss —
                # tearing down and FULL-RESYNCING the center in a loop
                # while both hubs are healthy.  Block indefinitely instead;
                # a dead primary still surfaces as EOF/RST, teardown wakes
                # the recv via shutdown(), and a silent host death is
                # covered by commit-triggered promotion
                sock.settimeout(None)
            except OSError:
                sock = None
            if sock is not None and self._replica_stop.is_set():
                # teardown landed while connect was in flight: exit WITHOUT
                # the hello — a zombie handshake would trigger a spurious
                # full-center sync on whatever now owns that port
                try:
                    sock.close()
                except OSError:
                    pass
                return
            if sock is not None:
                self._replica_sock = sock
                try:
                    net.send_frame(sock, net.encode_repl_hello(
                        self._clock, capabilities=caps))
                    while not self._replica_stop.is_set():
                        if sparse_feed:
                            payload = net.recv_frame_into(sock, rx,
                                                          limit=feed_limit)
                            action, blobs = net.decode_tensor_views(payload)
                            if action != net.ACTION_REPL:
                                raise net.ProtocolError(
                                    f"replica feed expected R, got "
                                    f"{action!r}")
                            clock, kind = net.decode_repl_header(blobs[0])
                            self._apply_repl_frame(clock, kind, blobs[1:])
                            if self.promoted:
                                return  # late frame post-promotion
                            failures = 0
                            if obs.enabled():
                                obs.counter("ps_replica_frames_total",
                                            **self._mlabels).inc()
                                obs.gauge("ps_replica_clock",
                                          **self._mlabels).set(clock)
                            continue
                        action = codec.recv_into(sock, [hdr] + bufs)
                        if action != net.ACTION_REPL:
                            raise net.ProtocolError(
                                f"replica feed expected R, got {action!r}")
                        clock, kind = net.decode_repl_header(hdr)
                        with self._lock:
                            if self.promoted:
                                return  # late frame post-promotion: never lands
                            if kind == net.REPL_SYNC:
                                for c, b in zip(self.center, bufs):
                                    c[...] = b
                                self._clock = clock
                                self.num_updates = clock
                                self._synced.set()
                            elif kind == net.REPL_DELTA:
                                for c, b in zip(self.center, bufs):
                                    c += b
                                self._clock = max(self._clock, clock)
                                self.num_updates += 1
                            else:
                                raise net.ProtocolError(
                                    f"unknown replication kind {kind}")
                        failures = 0  # a live stream resets the loss budget
                        if obs.enabled():
                            obs.counter("ps_replica_frames_total",
                                        **self._mlabels).inc()
                            obs.gauge("ps_replica_clock",
                                      **self._mlabels).set(clock)
                except (OSError, ValueError, ConnectionError):
                    pass  # feed lost (or teardown severed it): fall through
                finally:
                    self._replica_sock = None
                    try:
                        sock.close()
                    except OSError:
                        pass
            if self._replica_stop.is_set() or self.promoted:
                return
            failures += 1
            if failures > self.replica_feed_retries:
                if self._synced.is_set():
                    self.promote(reason=f"primary {host}:{port} lost "
                                        f"({failures - 1} reconnect "
                                        f"attempts exhausted)")
                    return
                # never synced: there is nothing to take over — promoting
                # would serve fresh init weights as if they were the
                # job's.  Keep retrying (capped backoff) until the primary
                # appears; operators see one warning, not a storm
                if not warned_unsynced:
                    warnings.warn(
                        f"replica feed to {host}:{port} failing before any "
                        f"sync arrived; retrying until the primary appears "
                        f"(a never-synced standby does not promote)")
                    warned_unsynced = True
                failures = self.replica_feed_retries  # cap the backoff
            self._replica_stop.wait(
                self.replica_feed_backoff * (2.0 ** (failures - 1)))

    # -- elastic membership ----------------------------------------------------
    def _member_join(self, token: int) -> None:
        with self._member_lock:
            self._members[token] = time.monotonic()
        if obs.enabled():
            obs.gauge("ps_live_workers",
                      **self._mlabels).set(self.live_workers())

    def _member_touch(self, token: int) -> None:
        with self._member_lock:
            if token in self._members:
                self._members[token] = time.monotonic()

    def _member_leave(self, token: int) -> None:
        with self._member_lock:
            self._members.pop(token, None)
        if obs.enabled():
            obs.gauge("ps_live_workers",
                      **self._mlabels).set(self.live_workers())

    def live_workers(self) -> int:
        """Workers currently believed alive: joined (committed at least
        once), not departed, and — when ``idle_timeout`` is set — heard
        from within it (heartbeat-lapse detection for peers whose
        connection is technically open but silent)."""
        now = time.monotonic()
        with self._member_lock:
            if self.idle_timeout is None:
                return len(self._members)
            return sum(1 for last in self._members.values()
                       if now - last <= self.idle_timeout)

    # -- live health plane (ISSUE 8) -------------------------------------------
    def _ingest_health(self, report: Dict[str, Any]) -> None:
        """Fold one worker health report into the process-default
        :class:`~distkeras_tpu.observability.health.HealthCollector` and
        give the detectors a (rate-limited) chance to run.  Lazy binding:
        the health module only loads once a report actually arrives."""
        # bind collector and monitor INDEPENDENTLY: _observe_health's
        # any_shard path may have pre-bound _health (joining an active
        # plane) without a monitor — a combined check would then deref
        # None on the first wire report and tear down the connection
        if self._health is None or self._health_monitor is None:
            from distkeras_tpu.observability import health as _health

            if self._health is None:
                self._health = _health.collector()
            if self._health_monitor is None:
                self._health_monitor = _health.monitor()
        self._health.ingest(report, shard=self.shard_id)
        self._health_monitor.maybe_check()

    def _observe_health(self, worker: Any, metric: str, value: float,
                        any_shard: bool = False) -> None:
        """Hub-side signal fold (per-commit staleness, replication lag)
        into the SAME per-worker series the wire reports feed.  By
        default shard-0 only under a sharded hub — one logical commit
        lands on every shard, and the fleet view must count it once (the
        ``fleet_report`` convention); ``any_shard`` is for series whose
        KEY already carries the shard (the hub's own pseudo-worker)."""
        if worker is None:
            return
        if not any_shard and self.shard_id is not None and self.shard_id != 0:
            return
        if self._health is None:
            if not any_shard:
                return
            # wire reports only ever land on shard 0 (and on the facade's
            # shard-0 route), so a shard-N hub's _ingest_health never runs
            # — its own pseudo-worker series (replication lag) must join
            # an ALREADY-active plane here.  active_collector never
            # creates and is a lock-free global peek; the module ref is
            # cached on self so the plane-off cost per publish is two
            # attribute loads and a None check
            if self._health_mod is None:
                from distkeras_tpu.observability import health as _health

                self._health_mod = _health
            bound = self._health_mod.active_collector()
            if bound is None:
                return
            self._health = bound
        self._health.observe(str(worker), metric, float(value),
                             shard=self.shard_id)

    # -- adaptive reaction (ISSUE 10) ------------------------------------------
    def _on_health_event(self, event: Any) -> None:
        """:meth:`HealthMonitor.subscribe` callback (adaptive hubs only):
        staleness/straggler events drive the per-worker rate controller,
        and storm events arm reconnect backpressure — so a storm detected
        from worker health REPORTS sheds load even before this hub has
        seen a single reconnect hello itself."""
        if getattr(event, "kind", None) in ("reconnect_storm",
                                            "failover_storm"):
            now = time.monotonic()
            with self._bp_lock:
                if now >= self._storm_until:
                    self._retry_seq = 0
                self._storm_until = max(self._storm_until,
                                        now + self.STORM_SHED_S)
        if self._rate is not None:
            self._rate.on_event(event)

    def _commit_adaptive(self, parts: Sequence[Any], last_pull_clock: int,
                         worker: Any) -> Dict[str, Any]:
        """Route one commit through the combiner (clock, fence, scaling
        and replication ordering all live there) and give the detectors a
        rate-limited chance to run off the commit path — an adaptive run
        with no worker health reports still reacts to the hub's own
        staleness folds."""
        entry = self._combiner.commit(parts, last_pull_clock, worker=worker)
        mon = self._health_monitor
        if mon is not None:
            mon.maybe_check()
        return entry

    def _commit_one(self, parts: Sequence[Any], last_pull_clock: int,
                    worker: Any, sparse: bool,
                    telemetry: bool) -> Tuple[int, int]:
        """The ONE commit dispatch every commit path (dense/sparse x
        socket/inproc) runs: adaptive routes through the combiner (clock,
        fence, scaling, Adasum merge and replication ordering live
        there); plain runs the pre-adaptive sequence verbatim — fence
        clamp under the center lock, apply, advance clock, publish to
        the replicas BEFORE returning (so the caller's ack keeps the
        acked-commit-is-kernel-owned replication contract).  Returns
        ``(staleness, last_pull_clock)``, the clock re-based when the
        fence clamped it — a commit retried without a fresh pull must
        not carry a dead incarnation's (or pre-promotion) clock as
        staleness."""
        if self._combiner is not None:
            entry = self._commit_adaptive(parts, last_pull_clock, worker)
            if entry["fenced"]:
                last_pull_clock = entry["fence"]
            return entry["staleness"], last_pull_clock
        with self._lock:
            if last_pull_clock < self._clock_fence:
                last_pull_clock = self._clock_fence
                if telemetry:
                    obs.counter("ps_fenced_commits_total",
                                **self._mlabels).inc()
            staleness = self._clock - last_pull_clock
            scaled = (self._apply_sparse_commit_locked(parts, staleness)
                      if sparse else
                      self._apply_commit_locked(parts, staleness))
            self.num_updates += 1
            self._clock += 1
            commit_clock = self._clock
        if scaled is not None:
            self._feed.publish(commit_clock, scaled)
        return staleness, last_pull_clock

    # -- multi-job admission + job-scoped serving (ISSUE 19) -------------------

    def _job_working_set_bytes_locked(self) -> int:
        """The shard's decayed hot-row working set in bytes (caller holds
        the center lock): rows still at or above ``TOUCH_HOT_MIN`` in the
        PR-14 touch counters, times their row bytes.  This is the live
        per-job memory signal admission projects against the budget —
        a shard whose embedding hot set already fills memory must not
        also take on another job's center copy."""
        total = 0
        for leaf, touch in self._sparse_touch.items():
            hot = int(np.count_nonzero(touch >= self.TOUCH_HOT_MIN))
            total += hot * int(self.center[leaf].shape[1]) * 4
        return total

    def _admit_job(self, job: str) -> Tuple[bool, str, Optional[_JobState]]:
        """Admission-control one job-scoped announce.  Returns
        ``(admitted, reason, state)``; re-announcing an already-admitted
        job (a reconnecting worker) re-attaches to the existing
        namespace.  The verdict settles under the center lock BEFORE any
        pull/commit is served on the announcing connection
        (``FLEET_RULES.admission_before_attach``)."""
        job = str(job)
        reason = ""
        with self._lock:
            state = self._jobs.get(job)
            n_jobs = len(self._jobs)
            if state is None:
                if self._standby and not self.promoted:
                    reason = ("standby hubs hold no job namespaces "
                              "(admission is primary-only)")
                elif self.max_jobs <= 0:
                    reason = "multi-job serving is disabled (max_jobs=0)"
                elif n_jobs >= self.max_jobs:
                    reason = f"job slots exhausted ({n_jobs}/{self.max_jobs})"
                else:
                    ws = self._job_working_set_bytes_locked()
                    projected = self._center_bytes * (n_jobs + 1) + ws
                    if projected > self.job_budget_bytes:
                        reason = (
                            f"shard memory budget exceeded: projected "
                            f"{projected} bytes ({n_jobs + 1} job center "
                            f"copies + {ws}-byte hot working set) > "
                            f"budget {self.job_budget_bytes}")
                    else:
                        state = _JobState(job, self.center)
                        self._jobs[job] = state
                        self.jobs_admitted += 1
                        n_jobs += 1
            if state is None:
                self.jobs_rejected += 1
        if obs.enabled():
            if state is not None:
                obs.counter("ps_jobs_admitted_total", **self._mlabels).inc()
                obs.gauge("ps_active_jobs", **self._mlabels).set(n_jobs)
            else:
                obs.counter("ps_jobs_rejected_total", **self._mlabels).inc()
        return (state is not None), reason, state

    def _job_commit_one(self, state: _JobState, delta: Sequence[np.ndarray],
                        last_pull_clock: int) -> Tuple[int, int]:
        """Job-scoped twin of :meth:`_commit_one`: same staleness and
        ``commit_scale`` semantics (the hub flavor's rule — ADAG's
        membership-weighted denominator, DynSGD's ``1/(s+1)``) applied
        to the JOB's center under the SAME center lock.  No adaptive
        combiner, replication or snapshot participation — isolation is
        the contract (see :class:`_JobState`)."""
        with self._lock:
            staleness = state.clock - last_pull_clock
            scale = self.commit_scale(staleness)
            for c, d in zip(state.center, delta):
                if scale == 1.0:
                    c += d
                else:
                    c += d * scale
            state.num_updates += 1
            state.clock += 1
        return staleness, last_pull_clock

    def fleet_info(self) -> Dict[str, Any]:
        """The hub's membership/job surface (ISSUE 19) — one JSON-safe
        dict the fleet controller, ``distkeras-top`` and the launcher
        all read.  The native hub's wrapper maps its C++ stat keys onto
        the same shape, so callers never branch on hub implementation."""
        with self._lock:
            jobs = {name: {"clock": s.clock, "num_updates": s.num_updates}
                    for name, s in self._jobs.items()}
            clock = self._clock
            num_updates = self.num_updates
            admitted, rejected = self.jobs_admitted, self.jobs_rejected
        return {"live_workers": self.live_workers(), "jobs": jobs,
                "clock": clock, "num_updates": num_updates,
                "jobs_admitted": admitted, "jobs_rejected": rejected}

    def _retry_after_ms(self, waits_taken: int = 0) -> int:
        """Answer one reconnect hello (action ``G``): 0 = proceed now,
        else the caller's retry-after slot in milliseconds.  Every hub
        answers ``G`` (an adaptive client may dial any hub of this
        generation), but only an adaptive hub in a live storm hints
        nonzero — and only to announcers that have NOT already waited a
        slot this episode (``waits_taken == 0``), so the herd spreads
        exactly once and every member is admitted on its paced return.
        Storms arm two ways: the health monitor's storm detectors (via
        the subscription), and self-detection from the hello arrival
        rate here — a herd reconnecting after a network blip is shed
        even when no worker reports health."""
        if not self.adaptive:
            return 0
        now = time.monotonic()
        storm_started = False
        with self._bp_lock:
            if waits_taken <= 0:
                # only FRESH reconnects are storm evidence: a shed herd's
                # paced returns (waits_taken > 0) are the drain, not the
                # storm — counting them would re-arm shedding against
                # the next innocent lone reconnect
                self._hello_times.append(now)
            while self._hello_times and \
                    now - self._hello_times[0] > self.STORM_WINDOW_S:
                self._hello_times.popleft()
            if now >= self._storm_until \
                    and len(self._hello_times) >= self.STORM_HELLOS:
                self._storm_until = now + self.STORM_SHED_S
                self._retry_seq = 0
                storm_started = True
            if now < self._storm_until and waits_taken <= 0:
                self._retry_seq += 1
                hint = min(self.RETRY_CAP_MS,
                           self.RETRY_BASE_MS * self._retry_seq)
                # counted under the lock: concurrent handler threads
                # during a storm must not lose increments
                self.backpressure_hints += 1
            else:
                hint = 0
        if storm_started:
            # observable like any monitor-detected storm; the emit also
            # re-arms shedding through the subscription (idempotent)
            try:
                mon = self._health_monitor
                if mon is not None:
                    mon.emit("reconnect_storm", "critical",
                             shard=self.shard_id,
                             dedup=f"hub-hellos:{self.host}:{self.port}",
                             hellos=len(self._hello_times),
                             window_s=self.STORM_WINDOW_S)
            except Exception:
                pass
        if hint and obs.enabled():
            obs.counter("ps_backpressure_hints_total",
                        **self._mlabels).inc()
            obs.histogram("ps.retry_after_ms",
                          **self._mlabels).observe(hint)
        return hint

    # -- serving loop (reference: SocketParameterServer.run) -------------------
    def _accept_loop(self) -> None:
        assert self._listener is not None
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                break  # listener closed by stop()
            # registration races stop(): linearize on _conn_lock — either
            # this append lands before stop()'s sever loop (which then
            # shuts the conn down), or we observe _running False here and
            # close it ourselves.  Without the re-check a conn accepted in
            # the gap would spawn a handler that blocks in recv forever,
            # resurrecting the leaked-handler stall stop() just fixed
            with self._conn_lock:
                if not self._running:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    break
                self._conns.append(conn)
            # Nagle off + kernel buffers sized to one full weights/commit
            # frame — times the receive batch depth when batching is on,
            # so the kernel can actually hold the storm of frames one
            # recvmmsg batch will drain.  TCP_QUICKACK on the hub side:
            # the coalesced 13-byte acks are the one latency-critical
            # tiny send left, and they must not ride the delayed-ack
            # timer (wire bytes unchanged — pinned by recording-socket)
            net.configure_socket(
                conn,
                payload_hint=self._frame_bytes
                * max(1, self.recv_batch_depth),
                quickack=True)
            # ordinal wraps at a fixed slot count so the staleness gauge's
            # label cardinality stays bounded even under elastic-run
            # connection churn (ordinals already restart at 0 per hub,
            # so slots only conflate workers past 256 live connections)
            conn_idx = self._conn_seq % 256
            self._conn_seq += 1
            t = threading.Thread(target=self._handle_connection,
                                 args=(conn, conn_idx), daemon=True)
            t.start()
            # prune finished handlers as connections churn: a long-lived
            # hub under elastic reconnects must not accumulate one dead
            # Thread object per connection ever accepted
            self._handlers = [h for h in self._handlers if h.is_alive()]
            self._handlers.append(t)

    def _decode_delta(self, blobs) -> List[np.ndarray]:
        """f32 commit: reinterpret each wire blob in place (zero-copy views
        into the connection's receive buffer, consumed before the next
        frame overwrites it)."""
        if len(blobs) != len(self.center):
            raise ValueError(f"commit has {len(blobs)} tensors, center has {len(self.center)}")
        out = []
        for blob, c in zip(blobs, self.center):
            arr = np.frombuffer(blob, dtype=c.dtype)
            if arr.size != c.size:
                raise ValueError(f"commit tensor size {arr.size} != center size {c.size}")
            out.append(arr.reshape(c.shape))
        return out

    def _decode_qdelta(self, blobs) -> List[np.ndarray]:
        """int8 commit (action Q): per-tensor f32 scale + int8 values."""
        if len(blobs) != len(self.center):
            raise ValueError(f"commit has {len(blobs)} tensors, center has {len(self.center)}")
        return [net.dequantize_q_blob(blob, c.size).reshape(c.shape)
                for blob, c in zip(blobs, self.center)]

    # -- row-sparse embedding traffic (ISSUE 9) --------------------------------
    # decay cadence of the hot-set estimate: halve every N folds, count
    # rows still >= TOUCH_HOT_MIN.  Instance-tunable (tests retune)
    TOUCH_DECAY_EVERY = 64
    TOUCH_HOT_MIN = 1.0

    def _touch_rows_locked(self, pairs) -> None:
        """Fold touched rows into the decayed per-table counters (caller
        holds the center lock and checked ``obs.enabled()``).  ``pairs``
        yields ``(leaf, ids)``; on each decay tick the
        ``ps.sparse_hot_rows{table=}`` gauges refresh."""
        for leaf, ids in pairs:
            touch = self._sparse_touch.get(leaf)
            if touch is not None and ids.size:
                touch[ids] += np.float32(1.0)
        self._touch_folds += 1
        if self._touch_folds >= self.TOUCH_DECAY_EVERY:
            self._touch_folds = 0
            for leaf, touch in self._sparse_touch.items():
                touch *= np.float32(0.5)
                obs.gauge("ps.sparse_hot_rows", table=str(leaf),
                          **self._mlabels).set(
                    int(np.count_nonzero(touch >= self.TOUCH_HOT_MIN)))

    def _q_payload_bytes(self) -> int:
        """Payload bytes of a DENSE int8 (action Q) commit over this
        center — the like-for-like baseline ``ps.sparse_wire_bytes_saved``
        compares an X commit against."""
        return 5 + sum(8 + 4 + w.size for w in self.center)

    def _check_row_ids(self, ids: np.ndarray, leaf: int) -> np.ndarray:
        """Validate one table's wire row-id blob against this center's
        row count (the shared :func:`networking.check_row_ids`
        contract)."""
        return net.check_row_ids(ids, self.center[leaf].shape[0], leaf)

    def _decode_sparse_ids(self, blobs) -> List[np.ndarray]:
        """Action-``S`` request payload -> one validated id array per
        sparse table (ascending leaf order).  The arrays are views into
        the connection's receive buffer — consumed before the next frame
        lands, like every other wire view."""
        if len(blobs) != len(self.sparse_leaves):
            raise ValueError(f"sparse pull has {len(blobs)} id blobs, hub "
                             f"has {len(self.sparse_leaves)} sparse tables")
        return [self._check_row_ids(np.frombuffer(blob, net.ROW_ID_DTYPE), i)
                for blob, i in zip(blobs, self.sparse_leaves)]

    def _decode_sparse_commit(self, blobs, quantized: bool) -> List[Any]:
        """Action-``U``/``X`` payload -> per-leaf parts aligned with the
        center: a full delta array for dense leaves, an ``(ids, grads)``
        pair for sparse leaves."""
        expected = len(self.center) + len(self.sparse_leaves)
        if len(blobs) != expected:
            raise ValueError(f"sparse commit has {len(blobs)} blobs, "
                             f"expected {expected}")
        parts: List[Any] = []
        it = iter(blobs)
        for i, c in enumerate(self.center):
            if i in self._sparse_set:
                ids = self._check_row_ids(
                    np.frombuffer(next(it), net.ROW_ID_DTYPE), i)
                dim = c.shape[1]
                blob = next(it)
                if quantized:
                    grads = net.dequantize_q_blob(blob, ids.size * dim)
                else:
                    grads = np.frombuffer(blob, np.float32)
                    if grads.size != ids.size * dim:
                        raise ValueError(
                            f"sparse leaf {i}: {grads.size} grad values for "
                            f"{ids.size} rows of dim {dim}")
                parts.append((ids, grads.reshape(ids.size, dim)))
            else:
                blob = next(it)
                if quantized:
                    arr = net.dequantize_q_blob(blob, c.size).reshape(c.shape)
                else:
                    arr = np.frombuffer(blob, np.float32)
                    if arr.size != c.size:
                        raise ValueError(f"commit tensor size {arr.size} != "
                                         f"center size {c.size}")
                    arr = arr.reshape(c.shape)
                parts.append(arr)
        return parts

    def _apply_sparse_commit_locked(self, parts: Sequence[Any],
                                    staleness: int) -> Optional[List[np.ndarray]]:
        """Sparse analogue of :meth:`_apply_commit_locked` (caller holds
        the center lock): dense leaves apply exactly like a dense commit,
        sparse leaves apply only their touched rows —
        ``center[ids] += commit_scale(staleness) * grads`` — under the
        SAME staleness clock and scaling rule the dense paths and the
        replication feed already share.  When a replica is attached the
        applied scaled parts are returned for the feed IN ROW-SPARSE FORM
        (``(ids, scaled rows)`` tuples; owned copies): the feed streams
        them as one REPL_SPARSE row-delta frame to sparse-capable
        replicas and densifies — outside this lock, only if a legacy
        replica is attached — for the dense-``R`` fallback.  Returns
        None with no replica (the pre-HA in-place path)."""
        feed = self._feed
        replicate = feed is not None and feed.active()
        scale = np.float32(self.commit_scale(staleness))
        one = scale == np.float32(1.0)
        scaled: Optional[List[Any]] = [] if replicate else None
        for c, p in zip(self.center, parts):
            if isinstance(p, tuple):
                ids, grads = p
                g = grads if one else grads * scale
                if replicate:
                    # OWNED copies for the feed (wire ids/grads are views
                    # into the receive buffer) — `* scale` already owns
                    # except on the scale-1 fast path
                    scaled.append((np.array(ids, net.ROW_ID_DTYPE),
                                   np.array(g, np.float32) if one else g))
                if ids.size:
                    c[ids] += g
            else:
                arr = np.asarray(p, np.float32)
                g = arr if one else arr * scale
                if replicate:
                    g = np.array(g, np.float32) if one else g
                    scaled.append(g)
                c += g
        if obs.enabled():
            self._touch_rows_locked(
                (i, p[0]) for i, p in enumerate(parts)
                if isinstance(p, tuple))
        return scaled

    def _handle_connection(self, conn: socket.socket, conn_idx: int = 0) -> None:
        # connections born after a restore start AT the fence: their first
        # commit-before-pull is stale relative to the restart point, not to
        # clock zero of a previous incarnation
        with self._lock:
            last_pull_clock = self._clock_fence
        with self._member_lock:
            self._member_seq += 1
            member_token = self._member_seq
        joined = False
        # trace context announced via action T (None until the worker
        # announces): every span this handler records is tagged with it,
        # so hub-side work is attributable to the worker that caused it
        ctx_attrs: Dict[str, Any] = {}
        # multi-job (ISSUE 19): set when this connection's T announce
        # carried a job_ns key and the admission verdict settled.  A
        # rejected session is never served (FLEET_RULES.
        # reject_never_serves); an admitted one is routed to its job's
        # private center with its own pull clock
        job_state: Optional[_JobState] = None
        job_rejected = False
        job_pull_clock = 0
        # per-connection reusable storage: the receive buffer grows once to
        # the largest frame this worker sends (a commit), the reply codec
        # holds one prepacked weights frame, the ack is a 13-byte constant
        # — steady-state the handler loop allocates nothing
        rx = bytearray(self._frame_bytes)
        reply = net.FlatFrameCodec(self.center)
        # sparse replies vary per message (row blobs sized by the request),
        # so they ride a grow-once variable encoder instead of the fixed
        # codec; None on a dense hub — zero cost when sparse is off
        sp_enc = net.VarFrameEncoder() if self.sparse_leaves else None
        ack = net.empty_tensor_frame(net.ACTION_ACK)
        # batched receive (ISSUE 18): with a depth configured, frames are
        # parsed out of one big per-connection buffer that a single
        # blocking recv (plus nonblocking recvmmsg drains) refills — a
        # pipelined commit storm costs one syscall per BATCH.  The
        # receiver wraps the raw TCP socket only; it is retired (asserted
        # drained) at any transport handoff below
        receiver = (net.BatchedReceiver(conn, self._frame_bytes,
                                        self.recv_batch_depth)
                    if self.recv_batch_depth > 0 else None)
        # set when this connection turns out to be a replica handshake: the
        # socket's ownership moves to the replication feed and this thread
        # must exit WITHOUT closing it
        handoff = False
        if self.idle_timeout is not None:
            # per-recv liveness bound: a peer that dies without FIN (host
            # crash, cable pull) no longer parks this handler forever
            conn.settimeout(self.idle_timeout)
        try:
            while True:
                # raw receive: pull/bye carry zero tensors, commit carries
                # len(center) — decode against the center only on commit.
                # The bound is the largest VALID frame (an f32 commit), so
                # a garbage length prefix raises ProtocolError instead of
                # allocating whatever the 8 bytes happened to say
                try:
                    if receiver is not None:
                        payload = receiver.recv_frame_into(
                            limit=self._max_payload)
                    else:
                        payload = net.recv_frame_into(conn, rx,
                                                      limit=self._max_payload)
                except socket.timeout:
                    # silent past the liveness window (no heartbeat, no
                    # traffic): evict — half-open peers must not hold a
                    # handler thread and a membership slot forever
                    if obs.enabled():
                        obs.counter("ps_idle_evictions_total",
                                    **self._mlabels).inc()
                        with obs.span("ps.evict", conn=conn_idx,
                                      **self._shard_attrs, **ctx_attrs):
                            pass
                    break
                action, blobs = net.decode_tensor_views(payload)
                if joined:
                    self._member_touch(member_token)
                telemetry = obs.enabled()
                t0 = time.perf_counter() if telemetry else 0.0
                if action == net.ACTION_PULL:
                    if job_rejected:
                        raise net.ProtocolError(
                            "pull on a rejected job session refused "
                            "(the admission verdict was reject)")
                    if job_state is not None:
                        with obs.span("ps.handle_pull", conn=conn_idx,
                                      **self._shard_attrs, **ctx_attrs):
                            with self._lock:
                                reply.pack(net.ACTION_WEIGHTS,
                                           job_state.center)
                                job_pull_clock = job_state.clock
                            reply.send_packed(conn)
                        if telemetry:
                            obs.counter("ps_pulls_total",
                                        **self._mlabels).inc()
                            obs.counter("ps_pull_bytes_total",
                                        **self._mlabels).inc(
                                self._center_bytes)
                            obs.histogram("ps_rpc_seconds", rpc="pull",
                                          **self._mlabels).observe(
                                time.perf_counter() - t0)
                        continue
                    if self._standby and not self._synced.is_set():
                        # same rule as commits: seed weights must never be
                        # served as if they were the job's state — a
                        # failed-over worker's re-pull here would train a
                        # window on garbage before its commit is refused
                        raise net.ProtocolError(
                            "pull from a never-synced standby refused "
                            "(it holds no job state yet)")
                    with obs.span("ps.handle_pull", conn=conn_idx,
                                  **self._shard_attrs, **ctx_attrs):
                        with self._lock:
                            # pack the center STRAIGHT into the reply frame
                            # (one memcpy per tensor) under the lock; the
                            # send happens after release so a slow peer
                            # can't hold the center
                            reply.pack(net.ACTION_WEIGHTS, self.center)
                            last_pull_clock = self._clock
                        reply.send_packed(conn)
                    if telemetry:
                        obs.counter("ps_pulls_total", **self._mlabels).inc()
                        obs.counter("ps_pull_bytes_total",
                                    **self._mlabels).inc(self._center_bytes)
                        obs.histogram("ps_rpc_seconds", rpc="pull",
                                      **self._mlabels).observe(
                            time.perf_counter() - t0)
                elif action in (net.ACTION_COMMIT, net.ACTION_QCOMMIT):
                    if job_rejected:
                        raise net.ProtocolError(
                            "commit on a rejected job session refused "
                            "(the admission verdict was reject)")
                    delta = (self._decode_delta(blobs)
                             if action == net.ACTION_COMMIT
                             else self._decode_qdelta(blobs))
                    if job_state is not None:
                        if not joined:
                            joined = True
                            self._member_join(member_token)
                        with obs.span("ps.handle_commit", conn=conn_idx,
                                      **self._shard_attrs,
                                      **ctx_attrs) as sp:
                            staleness, job_pull_clock = self._job_commit_one(
                                job_state, delta, job_pull_clock)
                            net.send_raw_frame(conn, ack)
                            if getattr(sp, "attrs", None) is not None:
                                sp.attrs["staleness"] = staleness
                        self._observe_health(ctx_attrs.get("worker"),
                                             "staleness", staleness)
                        if telemetry:
                            obs.counter("ps_commits_total",
                                        **self._mlabels).inc()
                            obs.counter("ps_commit_bytes_total",
                                        **self._mlabels).inc(
                                sum(b.nbytes for b in blobs))
                            obs.histogram("ps_rpc_seconds", rpc="commit",
                                          **self._mlabels).observe(
                                time.perf_counter() - t0)
                            obs.gauge("ps_staleness", conn=str(conn_idx),
                                      **self._mlabels).set(staleness)
                            obs.histogram("ps_commit_staleness",
                                          **self._mlabels).observe(staleness)
                        continue
                    if self._standby:
                        if not self._synced.is_set():
                            # no sync ever landed: this standby holds
                            # fresh init weights, NOT the job's state —
                            # promoting would silently restart training
                            # from seed.  Refuse (drops the connection;
                            # the worker retries under its budget and
                            # fails LOUDLY if nothing recovers), matching
                            # the feed-loss path's never-synced rule
                            raise net.ProtocolError(
                                "commit into a never-synced standby "
                                "refused (it has no state to take over)")
                        self._standby_commit_gate()
                        # the feed is down too: the primary is presumed
                        # dead.  Promote NOW (fence armed before this
                        # commit's staleness is computed) — losing the
                        # race to the feed-loss detector is fine,
                        # promote() is idempotent
                        self.promote(reason="commit received while standby "
                                            "(worker failed over)")
                    if not joined:
                        # first commit = this peer is a WORKER (pull-only
                        # readers never join): membership drives the
                        # elastic denominators
                        joined = True
                        self._member_join(member_token)
                    with obs.span("ps.handle_commit", conn=conn_idx,
                                  **self._shard_attrs, **ctx_attrs) as sp:
                        # one shared dispatch (adaptive combiner or the
                        # pre-adaptive fence/apply/publish sequence);
                        # either way the commit is applied AND replicated
                        # before the ack below leaves
                        staleness, last_pull_clock = self._commit_one(
                            delta, last_pull_clock, ctx_attrs.get("worker"),
                            sparse=False, telemetry=telemetry)
                        net.send_raw_frame(conn, ack)
                        if getattr(sp, "attrs", None) is not None:
                            # the span's attribution payload: the staleness
                            # this exact commit applied with (fleet_report
                            # joins it to the announcing worker)
                            sp.attrs["staleness"] = staleness
                    # live health plane: this commit's staleness joins the
                    # announcing worker's sliding-window series (no-op —
                    # one attribute check — until a worker reports health)
                    self._observe_health(ctx_attrs.get("worker"),
                                         "staleness", staleness)
                    if telemetry:
                        obs.counter("ps_commits_total", **self._mlabels).inc()
                        obs.counter("ps_commit_bytes_total",
                                    **self._mlabels).inc(
                            sum(b.nbytes for b in blobs))
                        obs.histogram("ps_rpc_seconds", rpc="commit",
                                      **self._mlabels).observe(
                            time.perf_counter() - t0)
                        # per-connection staleness: commits the hub applied
                        # between this worker's last pull and its commit —
                        # the quantity DynSGD scales by, now visible for
                        # EVERY hub flavor.  Created lazily so a hub with
                        # telemetry off never registers per-connection state
                        obs.gauge("ps_staleness", conn=str(conn_idx),
                                  **self._mlabels).set(staleness)
                        obs.histogram("ps_commit_staleness",
                                      **self._mlabels).observe(staleness)
                elif action == net.ACTION_SPARSE_PULL:
                    if job_state is not None or job_rejected:
                        raise net.ProtocolError(
                            "sparse actions are default-namespace only "
                            "(job-scoped sessions exchange dense P/C/Q)")
                    if sp_enc is None:
                        raise net.ProtocolError(
                            "sparse pull against a hub with no sparse "
                            "tables (pass sparse_leaves to the hub)")
                    if self._standby and not self._synced.is_set():
                        raise net.ProtocolError(
                            "pull from a never-synced standby refused "
                            "(it holds no job state yet)")
                    ids_list = self._decode_sparse_ids(blobs)
                    rows_pulled = int(sum(ids.size for ids in ids_list))
                    with obs.span("ps.handle_pull", conn=conn_idx,
                                  sparse_rows=rows_pulled,
                                  **self._shard_attrs, **ctx_attrs):
                        with self._lock:
                            # fancy-indexed row gathers copy; dense leaves
                            # are memcpy'd straight into the frame by
                            # pack() — all under the lock, send after
                            it = iter(ids_list)
                            arrays = [self.center[i][next(it)]
                                      if i in self._sparse_set
                                      else self.center[i]
                                      for i in range(len(self.center))]
                            frame = sp_enc.pack(net.ACTION_SPARSE_WEIGHTS,
                                                arrays)
                            last_pull_clock = self._clock
                            if telemetry:
                                self._touch_rows_locked(
                                    zip(self.sparse_leaves, ids_list))
                        net.send_raw_frame(conn, frame)
                    if telemetry:
                        obs.counter("ps_pulls_total", **self._mlabels).inc()
                        # raw tensor bytes, the same basis the dense pull
                        # (_center_bytes) and both commit paths use — the
                        # bench's sparse-vs-dense ratio must not compare
                        # framed bytes against raw bytes
                        obs.counter("ps_pull_bytes_total",
                                    **self._mlabels).inc(
                            sum(a.nbytes for a in arrays))
                        obs.counter("ps.sparse_rows_pulled",
                                    **self._mlabels).inc(rows_pulled)
                        obs.counter("ps.sparse_wire_bytes_saved",
                                    **self._mlabels).inc(
                            max(0, self._frame_bytes - sp_enc.frame_len))
                        obs.histogram("ps_rpc_seconds", rpc="pull",
                                      **self._mlabels).observe(
                            time.perf_counter() - t0)
                elif action in (net.ACTION_SPARSE_COMMIT,
                                net.ACTION_SPARSE_QCOMMIT):
                    if job_state is not None or job_rejected:
                        raise net.ProtocolError(
                            "sparse actions are default-namespace only "
                            "(job-scoped sessions exchange dense P/C/Q)")
                    if not self.sparse_leaves:
                        raise net.ProtocolError(
                            "sparse commit against a hub with no sparse "
                            "tables (pass sparse_leaves to the hub)")
                    parts = self._decode_sparse_commit(
                        blobs,
                        quantized=(action == net.ACTION_SPARSE_QCOMMIT))
                    if self._standby:
                        if not self._synced.is_set():
                            raise net.ProtocolError(
                                "commit into a never-synced standby "
                                "refused (it has no state to take over)")
                        self._standby_commit_gate()
                        self.promote(reason="commit received while standby "
                                            "(worker failed over)")
                    if not joined:
                        joined = True
                        self._member_join(member_token)
                    rows_committed = int(sum(
                        p[0].size for p in parts if isinstance(p, tuple)))
                    with obs.span("ps.handle_commit", conn=conn_idx,
                                  sparse_rows=rows_committed,
                                  **self._shard_attrs, **ctx_attrs) as sp:
                        staleness, last_pull_clock = self._commit_one(
                            parts, last_pull_clock, ctx_attrs.get("worker"),
                            sparse=True, telemetry=telemetry)
                        net.send_raw_frame(conn, ack)
                        if getattr(sp, "attrs", None) is not None:
                            sp.attrs["staleness"] = staleness
                    self._observe_health(ctx_attrs.get("worker"),
                                         "staleness", staleness)
                    if telemetry:
                        wire = sum(b.nbytes for b in blobs)
                        dense_equiv = (
                            self._frame_bytes - 8
                            if action == net.ACTION_SPARSE_COMMIT
                            else self._q_payload_bytes())
                        obs.counter("ps_commits_total", **self._mlabels).inc()
                        obs.counter("ps_commit_bytes_total",
                                    **self._mlabels).inc(wire)
                        obs.counter("ps.sparse_rows_committed",
                                    **self._mlabels).inc(rows_committed)
                        obs.counter("ps.sparse_wire_bytes_saved",
                                    **self._mlabels).inc(
                            max(0, dense_equiv - wire))
                        obs.histogram("ps_rpc_seconds", rpc="commit",
                                      **self._mlabels).observe(
                            time.perf_counter() - t0)
                        obs.gauge("ps_staleness", conn=str(conn_idx),
                                  **self._mlabels).set(staleness)
                        obs.histogram("ps_commit_staleness",
                                      **self._mlabels).observe(staleness)
                elif action == net.ACTION_TRACE:
                    # trace-context announce: tag this connection's spans
                    # with the worker's identity and reply with this hub's
                    # monotonic clock (the NTP-style sample the client's
                    # offset estimate is built from).  Malformed context is
                    # ignored, not fatal — tracing must never take down a
                    # training connection
                    raw = bytes(blobs[0]) if blobs else b""
                    try:
                        ctx = dtrace.TraceContext.from_json(raw)
                        ctx_attrs = ctx.span_attrs()
                    except Exception:
                        # any malformed blob shape (missing blob, non-object
                        # JSON, null fields -> TypeError/AttributeError):
                        # an unattributed connection, never a dropped one
                        ctx_attrs = {}
                    # multi-job announce (ISSUE 19): a job_ns key turns
                    # this T into a job-scoped announce whose reply is the
                    # admission verdict.  Absent (every pre-multi-job
                    # client), the reply below is the exact HEAD timestamp
                    # frame — byte-identical wire
                    job_ns = None
                    try:
                        doc = json.loads(raw.decode("utf-8"))
                        if isinstance(doc, dict):
                            job_ns = doc.get("job_ns")
                    except Exception:
                        job_ns = None
                    if job_ns is None:
                        if job_state is not None:
                            # a later plain trace announce on an admitted
                            # session must not drop the job attribution
                            ctx_attrs["job"] = job_state.job
                        net.send_frame(conn, net.encode_time_payload(
                            time.perf_counter_ns()))
                    else:
                        admitted, reason, job_state = self._admit_job(
                            str(job_ns))
                        job_rejected = not admitted
                        if admitted:
                            # the namespace IS the job for every span and
                            # health series this connection produces —
                            # fairness reporting groups by it
                            ctx_attrs["job"] = job_state.job
                        net.send_frame(conn, net.encode_admission_payload(
                            time.perf_counter_ns(), admitted, reason))
                elif action == net.ACTION_REPL:
                    # replica handshake: this peer is a hot standby, not a
                    # worker.  Attach it to the replication feed (full
                    # sync + delta stream) and hand the socket over — the
                    # feed owns it from here, this handler thread exits
                    clock_hdr, kind = net.decode_repl_header(blobs[0])
                    if kind != net.REPL_HELLO:
                        raise net.ProtocolError(
                            f"unexpected replication kind {kind} from a peer "
                            f"(only hello initiates a feed)")
                    if receiver is not None and receiver.pending():
                        # bytes batched past the hello belong to the feed's
                        # stream, which reads the raw socket — handing the
                        # socket over would silently drop them
                        raise net.ProtocolError(
                            "frames batched past a replication hello")
                    with self._feed_lock:
                        if self._feed is None:
                            self._feed = ReplicationFeed(self)
                        feed = self._feed
                    with obs.span("ps.replica_attach", conn=conn_idx,
                                  replica_clock=clock_hdr,
                                  **self._shard_attrs):
                        feed.attach(conn, conn_idx,
                                    capabilities=net.decode_repl_caps(
                                        blobs[0]))
                    handoff = True
                    return
                elif action == net.ACTION_HEALTH:
                    # worker health report (ISSUE 8): fold into the live
                    # collector and ack — the ack coalesces into the
                    # client's later receives exactly like a commit ack,
                    # so reports ride the pipelined FIFO.  The guard is
                    # BROAD on purpose: malformed JSON, a broken detector,
                    # a full-disk JSONL sink — health must never take down
                    # a training connection (the malformed-T rule)
                    try:
                        self._ingest_health(json.loads(bytes(blobs[0])))
                    except Exception:
                        pass
                    net.send_raw_frame(conn, ack)
                elif action == net.ACTION_RECONNECT:
                    # adaptive reconnect announce (ISSUE 10): answer with
                    # a retry-after hint (0 = proceed; announcers that
                    # already waited their slot are admitted).  Every hub
                    # of this generation answers G — the frame only ever
                    # moves when the CLIENT opted in with adaptive=True,
                    # so pre-existing byte streams are untouched
                    net.send_frame(conn, net.encode_retry_payload(
                        self._retry_after_ms(
                            net.decode_reconnect_payload(blobs))))
                elif action == net.ACTION_SHM:
                    # zero-copy attach handshake (ISSUE 18), entirely
                    # inside this dispatch arm so the switch point is
                    # exact: reply with an offer (two freshly created ring
                    # files) or a decline, then — on an offer — read the
                    # client's confirm off the SAME TCP stream.  Only an
                    # attached confirm swaps this connection onto the
                    # rings; a decline, an abort, or a mapping failure
                    # leaves it pure TCP, byte-identical to a pre-Z hub
                    # (analysis/protocol_model.py walks all of this)
                    version, cap_hint = net.decode_shm_request(blobs)
                    rings = None
                    if (self.shm_dir is not None
                            and version == net.SHM_VERSION
                            and not isinstance(conn, net.ShmEndpoint)):
                        with self._conn_lock:
                            self._shm_seq += 1
                            tag = self._shm_seq
                        base = os.path.join(
                            self.shm_dir, f"ring-{self.port}-{tag}")
                        # each ring must hold at least a couple of this
                        # connection's largest frames or the transport
                        # would deadlock pipelined exchanges on capacity
                        cap = max(int(cap_hint), 2 * self._frame_bytes,
                                  net.SHM_RING_DEFAULT_CAPACITY)
                        try:
                            rings = (net.ShmFrameRing.create(
                                         base + ".c2h", "consumer", cap),
                                     net.ShmFrameRing.create(
                                         base + ".h2c", "producer", cap))
                        except OSError:
                            rings = None  # can't create -> decline
                    if rings is None:
                        net.send_frame(conn, net.encode_shm_decline())
                    else:
                        rx_ring, tx_ring = rings
                        try:
                            net.send_frame(conn, net.encode_shm_offer(
                                rx_ring.path, tx_ring.path))
                            # the confirm is the very next frame on the
                            # TCP FIFO — read it where the batched
                            # receiver (if any) already is
                            if receiver is not None:
                                c_payload = receiver.recv_frame_into(
                                    limit=self._max_payload)
                            else:
                                c_payload = net.recv_frame_into(
                                    conn, rx, limit=self._max_payload)
                            c_action, c_blobs = net.decode_tensor_views(
                                c_payload)
                            if c_action != net.ACTION_SHM:
                                raise net.ProtocolError(
                                    f"expected Z confirm after shm offer, "
                                    f"got {c_action!r}")
                            attached = net.decode_shm_confirm(c_blobs)
                        except BaseException:
                            rx_ring.close()
                            tx_ring.close()
                            rx_ring.unlink()
                            tx_ring.unlink()
                            raise
                        # the client has mapped (or abandoned) the files;
                        # either way the names can leave the filesystem —
                        # the mappings keep the memory alive
                        rx_ring.unlink()
                        tx_ring.unlink()
                        if attached:
                            if receiver is not None and receiver.pending():
                                raise net.ProtocolError(
                                    "frames batched past an shm attach")
                            receiver = None  # rings need no syscall batching
                            endpoint = net.ShmEndpoint(conn, tx_ring,
                                                       rx_ring)
                            # stop()'s sever loop must wake the ring, not
                            # just the now-idle anchor socket
                            with self._conn_lock:
                                if conn in self._conns:
                                    self._conns[self._conns.index(conn)] = \
                                        endpoint
                            conn = endpoint
                            if self.idle_timeout is not None:
                                conn.settimeout(self.idle_timeout)
                        else:
                            rx_ring.close()
                            tx_ring.close()
                elif action == net.ACTION_PING:
                    # heartbeat-on-idle: proves liveness (resetting the
                    # idle clock above) and keeps a slow-but-alive worker's
                    # membership from lapsing; acked so the client can
                    # bound its own round trips
                    net.send_raw_frame(conn, ack)
                elif action == net.ACTION_BYE:
                    break
                else:
                    raise net.ProtocolError(f"unknown action {action!r}")
        except (ConnectionError, ValueError, OSError):
            pass  # worker vanished mid-exchange; reference behavior: drop it
        finally:
            self._member_leave(member_token)
            if not handoff:
                try:
                    conn.close()
                except OSError:
                    pass
                # forget the socket so stop() never shuts down an unrelated
                # descriptor that reuses this slot
                with self._conn_lock:
                    if conn in self._conns:
                        self._conns.remove(conn)

    # -- in-process transport (transport="inproc") -----------------------------
    # Co-located workers skip sockets and framing entirely and call the
    # SAME center logic the handlers run, under the same lock.  The pair
    # below is the whole inproc wire protocol: pull_direct is the 'P'
    # branch minus the frame, commit_direct is the 'C' branch minus the
    # decode.  The C++ hub exposes the same pair (runtime/native.py), so
    # InprocPSClient works against either hub.

    def pull_direct(self) -> Tuple[List[np.ndarray], int]:
        """Snapshot (center copy, clock at snapshot) — the caller passes the
        clock back with its commit, exactly like a socket worker's
        connection state does."""
        if self._standby and not self._synced.is_set():
            # same rule as the socket pull path: seed weights must never
            # be served as if they were the job's state
            raise RuntimeError(
                "pull_direct from a never-synced standby refused "
                "(it holds no job state yet); wait_synced() first")
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        # the inproc call runs IN the worker's thread, so the committing
        # worker's thread-local trace context IS the right attribution
        with obs.span("ps.handle_pull", transport="inproc",
                      **self._shard_attrs, **dtrace.current_span_attrs()):
            with self._lock:
                snapshot = [w.copy() for w in self.center]
                clock = self._clock
        if telemetry:
            obs.counter("ps_pulls_total", **self._mlabels).inc()
            obs.histogram("ps_rpc_seconds", rpc="pull.inproc",
                          **self._mlabels).observe(
                time.perf_counter() - t0)
        return snapshot, clock

    def commit_direct(self, delta: Sequence[np.ndarray], last_pull_clock: int) -> None:
        """Apply one commit with the staleness implied by ``last_pull_clock``
        (the value returned by the matching :meth:`pull_direct`)."""
        if len(delta) != len(self.center):
            raise ValueError(f"commit has {len(delta)} tensors, center has {len(self.center)}")
        for d, c in zip(delta, self.center):
            if np.asarray(d).size != c.size:
                raise ValueError(f"commit tensor size {np.asarray(d).size} != "
                                 f"center size {c.size}")
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        if self._standby:
            if not self._synced.is_set():
                # same rule as the socket path: a never-synced standby has
                # nothing to take over — refuse loudly rather than promote
                # fresh init weights into "the job's state"
                raise RuntimeError(
                    "commit_direct into a never-synced standby refused "
                    "(it has no state to take over); wait_synced() first")
            self._standby_commit_gate()
            # an inproc commit into a standby means its owner considers it
            # the live hub: promote (fence first, then apply)
            self.promote(reason="commit_direct while standby")
        # dtype/shape normalization outside the lock (no-op views for the
        # trainers' float32 payloads)
        arrays = [np.asarray(d, np.float32).reshape(c.shape)
                  for d, c in zip(delta, self.center)]
        with obs.span("ps.handle_commit", transport="inproc",
                      **self._shard_attrs, **dtrace.current_span_attrs()) as sp:
            # the inproc call runs IN the worker's thread, so its
            # thread-local trace context names the worker; the re-based
            # clock is discarded — inproc callers present theirs per call
            staleness, _ = self._commit_one(
                arrays, last_pull_clock,
                dtrace.current_span_attrs().get("worker"),
                sparse=False, telemetry=telemetry)
            if getattr(sp, "attrs", None) is not None:
                sp.attrs["staleness"] = staleness
        if self._health is not None:
            # guarded HERE so the disabled path never even builds the span
            # attrs dict (the zero-cost-when-off contract)
            self._observe_health(dtrace.current_span_attrs().get("worker"),
                                 "staleness", staleness)
        if telemetry:
            obs.counter("ps_commits_total", **self._mlabels).inc()
            obs.histogram("ps_rpc_seconds", rpc="commit.inproc",
                          **self._mlabels).observe(
                time.perf_counter() - t0)
            obs.histogram("ps_commit_staleness",
                          **self._mlabels).observe(staleness)

    def pull_sparse_direct(self, ids_list: Sequence[np.ndarray]
                           ) -> Tuple[List[Any], int]:
        """The S/V exchange minus the frame (InprocPSClient's sparse
        path): one validated sorted-unique id array per sparse table in,
        ``(per-leaf values, clock)`` out — full copies for dense leaves,
        the requested ``[k, dim]`` row blocks for sparse leaves."""
        if not self.sparse_leaves:
            raise RuntimeError("pull_sparse_direct on a hub with no sparse "
                               "tables (pass sparse_leaves to the hub)")
        if self._standby and not self._synced.is_set():
            raise RuntimeError(
                "pull_sparse_direct from a never-synced standby refused "
                "(it holds no job state yet); wait_synced() first")
        if len(ids_list) != len(self.sparse_leaves):
            # checked BEFORE the zip below, which would silently truncate
            raise ValueError(f"got {len(ids_list)} id arrays, hub has "
                             f"{len(self.sparse_leaves)} sparse tables")
        ids_list = [self._check_row_ids(
            np.asarray(ids, net.ROW_ID_DTYPE), i)
            for ids, i in zip(ids_list, self.sparse_leaves)]
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        rows_pulled = int(sum(ids.size for ids in ids_list))
        with obs.span("ps.handle_pull", transport="inproc",
                      sparse_rows=rows_pulled, **self._shard_attrs,
                      **dtrace.current_span_attrs()):
            with self._lock:
                it = iter(ids_list)
                values: List[Any] = [
                    self.center[i][next(it)] if i in self._sparse_set
                    else self.center[i].copy()
                    for i in range(len(self.center))]
                clock = self._clock
                if telemetry:
                    self._touch_rows_locked(
                        zip(self.sparse_leaves, ids_list))
        if telemetry:
            obs.counter("ps_pulls_total", **self._mlabels).inc()
            obs.counter("ps.sparse_rows_pulled",
                        **self._mlabels).inc(rows_pulled)
            obs.histogram("ps_rpc_seconds", rpc="pull.inproc",
                          **self._mlabels).observe(time.perf_counter() - t0)
        return values, clock

    def commit_sparse_direct(self, parts: Sequence[Any],
                             last_pull_clock: int) -> None:
        """Apply one row-sparse commit (the U exchange minus the frame):
        ``parts`` aligned with the center — full f32 delta for dense
        leaves, ``(ids, grads)`` for sparse leaves — with the staleness
        implied by ``last_pull_clock``."""
        if not self.sparse_leaves:
            raise RuntimeError("commit_sparse_direct on a hub with no "
                               "sparse tables (pass sparse_leaves)")
        if len(parts) != len(self.center):
            raise ValueError(f"commit has {len(parts)} parts, center has "
                             f"{len(self.center)}")
        norm: List[Any] = []
        for i, (p, c) in enumerate(zip(parts, self.center)):
            if i in self._sparse_set:
                ids, grads = p
                ids = self._check_row_ids(np.asarray(ids, net.ROW_ID_DTYPE), i)
                grads = np.asarray(grads, np.float32).reshape(
                    ids.size, c.shape[1])
                norm.append((ids, grads))
            else:
                norm.append(np.asarray(p, np.float32).reshape(c.shape))
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        if self._standby:
            if not self._synced.is_set():
                raise RuntimeError(
                    "commit_sparse_direct into a never-synced standby "
                    "refused (it has no state to take over); "
                    "wait_synced() first")
            self._standby_commit_gate()
            self.promote(reason="commit_sparse_direct while standby")
        rows_committed = int(sum(
            p[0].size for p in norm if isinstance(p, tuple)))
        with obs.span("ps.handle_commit", transport="inproc",
                      sparse_rows=rows_committed, **self._shard_attrs,
                      **dtrace.current_span_attrs()) as sp:
            staleness, _ = self._commit_one(
                norm, last_pull_clock,
                dtrace.current_span_attrs().get("worker"),
                sparse=True, telemetry=telemetry)
            if getattr(sp, "attrs", None) is not None:
                sp.attrs["staleness"] = staleness
        if self._health is not None:
            self._observe_health(dtrace.current_span_attrs().get("worker"),
                                 "staleness", staleness)
        if telemetry:
            obs.counter("ps_commits_total", **self._mlabels).inc()
            obs.counter("ps.sparse_rows_committed",
                        **self._mlabels).inc(rows_committed)
            obs.histogram("ps_rpc_seconds", rpc="commit.inproc",
                          **self._mlabels).observe(time.perf_counter() - t0)
            obs.histogram("ps_commit_staleness",
                          **self._mlabels).observe(staleness)

    # -- commit rules ----------------------------------------------------------
    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:  # pragma: no cover
        raise NotImplementedError

    def commit_scale(self, staleness: int) -> float:  # pragma: no cover
        """The scalar this hub multiplies a commit by before adding it to
        the center.  The replication path (``replica_of`` standbys)
        materializes ``delta * commit_scale`` so the replica applies the
        exact post-aggregation bytes the primary did; ``apply_commit``
        stays the non-replicated in-place fast path, and the two must
        agree.  Subclasses with a scaling rule override both."""
        raise NotImplementedError

    def _apply_commit_locked(self, delta: Sequence[np.ndarray],
                             staleness: int) -> Optional[List[np.ndarray]]:
        """Apply one commit (caller holds the center lock) and return the
        scaled applied arrays for the replication feed, or ``None`` when no
        replica is attached — the pre-HA in-place path, bit-identical
        (``x * float32(1.0)`` is exact, so a replicated primary's center
        trajectory matches an unreplicated one bit for bit)."""
        feed = self._feed
        if feed is None or not feed.active():
            self.apply_commit(list(delta), staleness)
            return None
        scale = np.float32(self.commit_scale(staleness))
        # materialize OWNED copies: socket deltas are views into the
        # connection's receive buffer, which the next frame overwrites —
        # the feed must outlive that
        scaled = [np.asarray(d, np.float32) * scale for d in delta]
        for c, s in zip(self.center, scaled):
            c += s
        return scaled


class DeltaParameterServer(SocketParameterServer):
    """Unscaled delta adds: ``center += delta``.  Reference
    ``DeltaParameterServer`` — serves DOWNPOUR (accumulated gradients) and
    the elastic family (workers pre-scale by alpha)."""

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        for c, d in zip(self.center, delta):
            c += d

    def commit_scale(self, staleness: int) -> float:
        return 1.0


class ADAGParameterServer(SocketParameterServer):
    """ADAG normalization: ``center += delta / num_workers`` (reference
    ``ADAGParameterServer.handle_commit``, SURVEY §2.6).

    ``elastic=True`` replaces the static configured denominator with the
    LIVE worker count from hub membership (join on first commit, leave on
    disconnect/idle-lapse, capped at num_workers): when a worker dies
    permanently mid-run, the survivors' deltas stop being diluted by a
    ghost — degraded-but-correct averaging under churn, the elastic
    coordination the EASGD lineage (arXiv:1412.6651) is built on.  The
    cap keeps transient over-registration (a worker reconnecting before
    its old handler noticed the death) from scaling commits UP past the
    configured cohort; zero membership (commits arriving via
    ``commit_direct`` — the inproc transport, which has no connections to
    track) falls back to the static ``num_workers`` denominator."""

    def __init__(self, weights: Sequence[np.ndarray], num_workers: int,
                 elastic: bool = False, **kwargs):
        super().__init__(weights, **kwargs)
        self.num_workers = int(num_workers)
        self.elastic = bool(elastic)

    def _algo_state(self) -> Dict[str, Any]:
        return {"num_workers": self.num_workers, "elastic": self.elastic}

    def commit_scale(self, staleness: int) -> float:
        n = self.num_workers
        if self.elastic:
            live = self.live_workers()
            # membership is a SOCKET-connection concept (join on first
            # commit, leave on disconnect): a socket committer is always
            # its own live member, so live >= 1 here for wire commits.
            # live == 0 means this commit arrived via commit_direct
            # (inproc workers bypass connections) — fall back to the
            # static denominator rather than scaling by 1/1, which would
            # over-apply every inproc delta num_workers-fold
            n = min(live, self.num_workers) if live >= 1 else self.num_workers
        return 1.0 / n

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        inv = self.commit_scale(staleness)
        for c, d in zip(self.center, delta):
            c += d * inv


class DynSGDParameterServer(SocketParameterServer):
    """Staleness-aware scaling: ``center += delta / (staleness + 1)`` where
    staleness = commits applied since this worker's last pull (reference
    ``DynSGDParameterServer.handle_commit``, SURVEY §2.7)."""

    def commit_scale(self, staleness: int) -> float:
        return 1.0 / (staleness + 1.0)

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        inv = self.commit_scale(staleness)
        for c, d in zip(self.center, delta):
            c += d * inv


def _normalize_failover(entry) -> List[Tuple[str, int]]:
    """One shard's failover spec -> list of (host, port): accepts ``None``
    (no standby), one ``(host, port)`` pair, or a sequence of pairs.  A
    bare string (a pair's stray host, or a sliced-up pair) is a caller
    bug — iterating its characters would fabricate garbage addresses."""
    if entry is None:
        return []
    if isinstance(entry, (str, bytes)):
        raise ValueError(f"failover entry {entry!r} is a bare string; "
                         f"pass a (host, port) pair or a list of them")
    entry = list(entry)
    if entry and isinstance(entry[0], (str, bytes)):
        return [(str(entry[0]), int(entry[1]))]
    return [(str(h), int(p)) for h, p in entry]


class StripeLostError(ConnectionError):
    """One stripe of a sharded PS deployment is gone: the per-shard
    connection named here exhausted its reconnect/failover budget (or was
    configured fail-fast) mid fan-out.  Subclasses ``ConnectionError`` so
    every pre-existing handler still catches it; the shard identity
    (index + address) rides the exception so an operator knows WHICH hub
    to look at instead of a generic connection error."""

    def __init__(self, shard_index: int, host: str, port: int,
                 cause: BaseException):
        self.shard_index = int(shard_index)
        self.host = str(host)
        self.port = int(port)
        super().__init__(
            f"PS stripe lost: shard {self.shard_index} at "
            f"{self.host}:{self.port} ({type(cause).__name__}: {cause})")


def _quantize_commit(delta: Sequence[np.ndarray],
                     residual: List[np.ndarray]) -> List[np.ndarray]:
    """Advance the int8 error-feedback chain one commit: quantize each
    delta WITH its carried residual, store the new residual in place, and
    return the wire blobs (uint8 arrays: be-f32 scale + int8 values).

    The one implementation both transports call — the socket client frames
    the blobs as an action-``Q`` message, the inproc client dequantizes
    them right back — so the quantize/residual math can never fork between
    transports (the bit-parity property ``tests/test_transport.py`` pins)."""
    blobs = []
    for i, d in enumerate(delta):
        carried = np.asarray(d, np.float32) + residual[i]
        blob, residual[i] = net.quantize_q_blob(carried)
        blobs.append(np.frombuffer(blob, dtype=np.uint8))
    return blobs


def _sparse_commit_arrays(delta: Sequence[np.ndarray],
                          templates: Sequence[np.ndarray],
                          sparse_set, ids_list: Sequence[np.ndarray],
                          residual: Optional[List[np.ndarray]],
                          compress: Optional[str]) -> List[np.ndarray]:
    """Full-order delta + per-table touched-row ids -> the U/X wire blob
    arrays (advancing the int8 residuals in place) — the one
    implementation both transports share, so the row-gather and
    quantize/residual math can never fork between sockets and inproc.

    int8 residuals use the documented DENSE-residual fallback: one
    full-table float32 residual per sparse leaf (the same array the dense
    path would keep), indexed by the touched rows — per-row error
    feedback without a second bookkeeping structure.  Each table's row
    block is quantized as ONE unit (one scale for the [k, dim] block)."""
    arrays: List[np.ndarray] = []
    it = iter(ids_list)
    for i, d in enumerate(delta):
        if i in sparse_set:
            ids = next(it)
            rows = np.ascontiguousarray(np.asarray(d, np.float32)[ids])
            if compress == "int8":
                carried = rows + residual[i][ids]
                blob, r = net.quantize_q_blob(carried)
                residual[i][ids] = r
                arrays.append(ids)
                arrays.append(np.frombuffer(blob, np.uint8))
            else:
                arrays.append(ids)
                arrays.append(rows)
        else:
            if compress == "int8":
                carried = np.asarray(d, np.float32) + residual[i]
                blob, residual[i] = net.quantize_q_blob(carried)
                arrays.append(np.frombuffer(blob, np.uint8))
            else:
                arrays.append(np.asarray(d, np.float32))
    return arrays


def _sparse_parts_from_arrays(arrays: Sequence[np.ndarray],
                              templates: Sequence[np.ndarray],
                              sparse_set,
                              compress: Optional[str]) -> List[Any]:
    """Inverse of :func:`_sparse_commit_arrays` at the VALUE level: what
    the hub would reconstruct from those wire blobs — the inproc client
    round-trips every sparse commit through this so compressed inproc
    runs stay trajectory-identical to the wire (the
    ``tests/test_transport.py`` contract, extended to sparse)."""
    parts: List[Any] = []
    it = iter(arrays)
    for i, t in enumerate(templates):
        if i in sparse_set:
            ids = next(it)
            val = next(it)
            dim = t.shape[1]
            if compress == "int8":
                grads = net.dequantize_q_blob(
                    memoryview(val), ids.size * dim).reshape(ids.size, dim)
            else:
                grads = val
            parts.append((ids, grads))
        else:
            val = next(it)
            if compress == "int8":
                parts.append(net.dequantize_q_blob(
                    memoryview(val), t.size).reshape(t.shape))
            else:
                parts.append(val)
    return parts


def _init_hot_tier(client: Any, sparse_cache_rows: Optional[int],
                   compress: Optional[str]) -> None:
    """Shared hot-tier state constructor (PSClient + InprocPSClient):
    validates ``sparse_cache_rows``, builds either the PR-9 full-size
    per-table caches (``None``) or one bounded :class:`_RowLRU` per
    table, and the evict-forces-flush overflow.  Requires
    ``client.templates`` / ``client._sparse`` to be set."""
    client._cache_rows = (None if sparse_cache_rows is None
                          else int(sparse_cache_rows))
    if client._cache_rows is not None:
        if not client._sparse:
            raise ValueError("sparse_cache_rows needs sparse_leaves")
        if client._cache_rows < 1:
            raise ValueError(f"sparse_cache_rows must be >= 1, got "
                             f"{client._cache_rows}")
    if client._cache_rows is None:
        client._cache = {i: np.array(client.templates[i], np.float32)
                         for i in client._sparse}
        client._lru = {}
    else:
        client._cache = {}
        client._lru = {
            i: _RowLRU(min(client._cache_rows,
                           client.templates[i].shape[0]),
                       client.templates[i].shape[1],
                       residual=(compress == "int8"))
            for i in client._sparse}
    # evict-forces-flush overflow (int8 cache mode): leaf -> {row id ->
    # pending residual row} accumulated at eviction, flushed as extra
    # (ids, residual) rows on the next sparse commit of that leaf —
    # eviction never LOSES a pending residual
    client._flush_pending = {i: {} for i in client._sparse}


def _hot_tier_gather(client: Any, ids_list: Sequence[np.ndarray]
                     ) -> Tuple[List[np.ndarray], List[np.ndarray],
                                List[np.ndarray]]:
    """Resolve one pull's ids against the LRUs NOW (hit values copied
    into fresh result blocks at this instant); returns
    ``(blocks, miss_positions, miss_ids)`` per table.  Counts the hits
    into the registry."""
    hits0 = client.sparse_cache_hits
    blocks: List[np.ndarray] = []
    miss_pos: List[np.ndarray] = []
    miss: List[np.ndarray] = []
    for ids, i in zip(ids_list, client._sparse):
        block = np.empty((ids.size, client.templates[i].shape[1]),
                         np.float32)
        mp, miss_ids = client._lru[i].gather(ids, block)
        blocks.append(block)
        miss_pos.append(mp)
        miss.append(miss_ids)
    if obs.enabled() and client.sparse_cache_hits > hits0:
        obs.counter("ps_sparse_cache_hits_total",
                    **getattr(client, "_mlabels", {})).inc(
            client.sparse_cache_hits - hits0)
    return blocks, miss_pos, miss


def _hot_tier_file_misses(client: Any, leaf: int, miss_ids: np.ndarray,
                          rows: np.ndarray) -> None:
    """File one table's freshly-pulled miss rows into its LRU,
    accumulating evicted rows' pending int8 residuals into the flush
    overflow (the evict-forces-flush rule)."""
    for rid, res_row in client._lru[leaf].insert(miss_ids, rows):
        pend = client._flush_pending[leaf]
        if rid in pend:
            pend[rid] += res_row
        else:
            pend[rid] = res_row


def _count_cache_misses(client: Any, misses0: int) -> None:
    if obs.enabled() and client.sparse_cache_misses > misses0:
        obs.counter("ps_sparse_cache_misses_total",
                    **getattr(client, "_mlabels", {})).inc(
            client.sparse_cache_misses - misses0)


def _hot_tier_seed(client: Any, leaf: int, full: np.ndarray) -> None:
    """A full pull's table values refresh every RESIDENT row and, on
    first contact, seed the LRU with the table's lowest ids (CTR
    vocabularies conventionally place frequent ids low; a wrong guess
    only costs misses)."""
    lru = client._lru[leaf]
    full = np.asarray(full, np.float32)
    if not lru.slots:
        seed = np.arange(lru.cap, dtype=net.ROW_ID_DTYPE)
        lru.insert(seed, full[:lru.cap])
        lru.misses -= lru.cap  # seeding is not demand misses
    else:
        for rid, slot in lru.slots.items():
            lru.vals[slot] = full[rid]


def _hot_tier_commit_arrays(client: Any, delta: Sequence[np.ndarray],
                            ids_list: Sequence[np.ndarray]
                            ) -> List[np.ndarray]:
    """The ONE hot-tier commit implementation both transports share (the
    ``_sparse_commit_arrays`` convention extended to the bounded LRU):
    ``client`` is a PSClient/InprocPSClient in cache mode — its per-leaf
    LRUs supply residual state, evicted-residual flushes join the id set,
    and the post-wire rows merge into resident entries in place."""
    arrays: List[np.ndarray] = []
    it = iter(ids_list)
    for i, d in enumerate(delta):
        if i not in client._sparse_set:
            if client.compress == "int8":
                carried = np.asarray(d, np.float32) + client._residual[i]
                blob, client._residual[i] = net.quantize_q_blob(carried)
                arrays.append(np.frombuffer(blob, np.uint8))
            else:
                arrays.append(np.asarray(d, np.float32))
            continue
        ids = next(it)
        lru = client._lru[i]
        dim = client.templates[i].shape[1]
        pend = client._flush_pending[i]
        if pend:
            ids_all = np.union1d(
                ids, np.fromiter(pend.keys(), np.int64, len(pend)))
        else:
            ids_all = ids
        rows = np.ascontiguousarray(np.asarray(d, np.float32)[ids_all])
        if client.compress == "int8":
            carried = rows + lru.residual_rows(ids_all)
            if pend:
                for pos, rid in enumerate(ids_all):
                    r = pend.pop(int(rid), None)
                    if r is not None:
                        carried[pos] += r
            blob, res = net.quantize_q_blob(carried)
            lru.store_residuals(ids_all, res)
            wire_rows = net.dequantize_q_blob(
                blob, ids_all.size * dim).reshape(ids_all.size, dim)
            arrays.append(ids_all)
            arrays.append(np.frombuffer(blob, np.uint8))
        else:
            wire_rows = rows
            arrays.append(ids_all)
            arrays.append(rows)
        lru.merge(ids_all, wire_rows)
    return arrays


class _RowLRU:
    """Bounded host store for ONE sparse table's hot rows (the hyperscale
    client tier, ISSUE 15): ``cap`` value rows (+ int8 residual rows when
    error feedback is on) keyed by row id, least-recently-used eviction.

    This replaces the full-size per-table host cache AND residual slab of
    the PR-9 client — host memory per table drops from ``rows x dim x 4``
    (x2 under int8) to ``cap x dim x 4`` (x2), so a client serving a
    hundred-GB vocabulary holds only its hot tier.  Semantics:

    - ``gather`` resolves a pull's ids against the store: hit rows are
      copied out IMMEDIATELY (so later merges/evictions can never tear a
      pull that was already resolved) and only the misses go to the wire;
    - ``insert`` files a miss reply's fresh rows, evicting LRU victims;
      an evicted row's pending int8 residual is RETURNED to the caller
      (the evict-forces-flush rule — it piggybacks on the next commit,
      never silently dropped);
    - ``merge`` folds the client's OWN committed rows into resident
      entries in place (hits merge in place), keeping a hit's value
      exact under scale-1 hubs and within the async staleness tolerance
      otherwise (other workers' updates arrive when the row next
      misses).

    Not thread-safe: owned by the client's caller thread like every other
    pipeline structure."""

    def __init__(self, cap: int, dim: int, residual: bool):
        self.cap = max(1, int(cap))
        self.dim = int(dim)
        self.vals = np.zeros((self.cap, self.dim), np.float32)
        self.res = (np.zeros((self.cap, self.dim), np.float32)
                    if residual else None)
        # id -> slot; Python dicts preserve insertion order, so re-inserting
        # on touch makes the FIRST key the LRU victim (an OrderedDict
        # without the import)
        self.slots: Dict[int, int] = {}
        self._free = list(range(self.cap - 1, -1, -1))
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def nbytes(self) -> int:
        return self.vals.nbytes + (self.res.nbytes if self.res is not None
                                   else 0)

    def _touch(self, rid: int, slot: int) -> None:
        del self.slots[rid]
        self.slots[rid] = slot

    def gather(self, ids: np.ndarray, out: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Resolve ``ids`` (sorted unique) against the store: hit rows are
        copied into their positions of ``out`` ([k, dim], the pull's
        result block) now; returns ``(miss_positions, miss_ids)`` — the
        rows the wire must fetch."""
        miss_pos: List[int] = []
        for pos, rid in enumerate(ids):
            slot = self.slots.get(int(rid))
            if slot is None:
                miss_pos.append(pos)
            else:
                out[pos] = self.vals[slot]
                self._touch(int(rid), slot)
                self.hits += 1
        mp = np.asarray(miss_pos, np.int64)
        return mp, ids[mp]

    def insert(self, ids: np.ndarray, rows: np.ndarray
               ) -> List[Tuple[int, np.ndarray]]:
        """File freshly-pulled rows (misses, or a seeding pass); returns
        ``[(evicted id, pending residual row)]`` for victims whose int8
        residual was nonzero (the evict-forces-flush payload)."""
        flushed: List[Tuple[int, np.ndarray]] = []
        for pos, rid in enumerate(ids):
            rid = int(rid)
            slot = self.slots.get(rid)
            if slot is not None:
                self.vals[slot] = rows[pos]
                self._touch(rid, slot)
                continue
            self.misses += 1
            if self._free:
                slot = self._free.pop()
            else:
                victim, slot = next(iter(self.slots.items()))
                del self.slots[victim]
                self.evictions += 1
                if self.res is not None and self.res[slot].any():
                    flushed.append((victim, self.res[slot].copy()))
            self.vals[slot] = rows[pos]
            if self.res is not None:
                self.res[slot] = 0.0
            self.slots[rid] = slot
        return flushed

    def merge(self, ids: np.ndarray, rows: np.ndarray) -> None:
        """Fold the client's own committed (post-wire) rows into resident
        entries in place; absent rows are skipped (they re-pull fresh on
        their next miss)."""
        for pos, rid in enumerate(ids):
            slot = self.slots.get(int(rid))
            if slot is not None:
                self.vals[slot] += rows[pos]

    def residual_rows(self, ids: np.ndarray) -> np.ndarray:
        """[k, dim] residual block for ``ids``: resident rows read their
        slot, absent rows read zero (their pending residual, if any, was
        already flushed at eviction)."""
        out = np.zeros((len(ids), self.dim), np.float32)
        if self.res is not None:
            for pos, rid in enumerate(ids):
                slot = self.slots.get(int(rid))
                if slot is not None:
                    out[pos] = self.res[slot]
        return out

    def store_residuals(self, ids: np.ndarray, rows: np.ndarray) -> None:
        """Write back post-quantization residual rows for resident ids;
        a non-resident id's fresh rounding error (at most one quantization
        step of this block) is dropped — the documented flush tail."""
        if self.res is None:
            return
        for pos, rid in enumerate(ids):
            slot = self.slots.get(int(rid))
            if slot is not None:
                self.res[slot] = rows[pos]


class _HotTierCacheSurface:
    """The hot-tier cache accessors both transports share (ISSUE 15):
    hit/miss totals for health reports + registry deltas, and the host
    bytes the sparse caches hold — bounded LRU stores in cache mode, the
    full-size per-table caches (+ int8 residual slabs) otherwise."""

    @property
    def sparse_cache_hits(self) -> int:
        """Pulled rows served from the hot-tier LRU (zero wire cost);
        0 for full-cache clients."""
        return sum(lru.hits for lru in self._lru.values())

    @property
    def sparse_cache_misses(self) -> int:
        """Pulled rows that took a wire fetch + LRU slot; 0 for
        full-cache clients."""
        return sum(lru.misses for lru in self._lru.values())

    def sparse_cache_bytes(self) -> int:
        """Host bytes the sparse-table caches hold — the number the
        hyperscale bench tripwire compares against the full-vocabulary
        footprint."""
        if self._cache_rows is not None:
            return sum(lru.nbytes() for lru in self._lru.values())
        total = sum(c.nbytes for c in self._cache.values())
        if self._residual is not None:
            total += sum(self._residual[i].nbytes for i in self._sparse)
        return total


_CLIENT_ORDINALS = itertools.count()


class PSClient(_HotTierCacheSurface):
    """Worker-side connection: ``pull()`` / ``commit(delta)`` (reference:
    ``NetworkWorker.pull/commit``, SURVEY §2.10) — plus the pipelined
    fire-and-forget API the async hot path runs on
    (``pull_nowait`` / ``wait_weights`` / ``commit_nowait`` / ``drain``).

    Framing is the zero-copy flat path (:class:`~.networking.FlatFrameCodec`):
    commits leave through one preallocated frame buffer (one memcpy per
    tensor, single ``sendall``), pulls scatter-receive with ``recv_into``
    into one of two reusable landing buffers — double-buffered because the
    caller may still be consuming pull *k* while the prefetched pull *k+1*
    streams in.  Arrays returned by ``pull``/``wait_weights`` therefore
    alias client-owned storage that is REUSED two pulls later; copy
    anything that must outlive that.

    Pipelining: the nowait methods send a request and record the expected
    reply in a FIFO; replies are consumed lazily, in wire order, by
    ``wait_weights``/``drain`` — commit acks coalesce into the next
    weights receive instead of costing their own blocking round trip.  At
    most ``max_inflight`` commits ride unacknowledged (enforced by
    consuming replies before sending more: wire back-pressure, not an
    unbounded queue).  After any mid-frame error the stream is
    desynchronized — the connection is single-use, callers drop it.

    ``compress="int8"`` sends commits as action-``Q`` frames — symmetric
    per-tensor int8 with a float32 scale (4x fewer wire bytes) — keeping
    the quantization residual client-side and folding it into the next
    commit (error feedback: the sum of dequantized commits tracks the sum
    of true deltas, so compression does not bias the center).  The
    residual chain advances at QUANTIZATION time: pipelined commits have
    no per-commit ack to gate on, and a dead connection is fatal to the
    worker anyway (nothing reconnects and retries a half-sent commit).
    Pulls always stay full precision: weight error hits the model
    directly, while delta rounding error is recycled.

    Resilience (``timeout`` is the per-recv/send socket timeout — a hub
    that stops responding surfaces as ``socket.timeout`` instead of a
    hang): with ``max_reconnects > 0``, any connection fault (reset, EOF,
    recv timeout, desynchronized stream) triggers reconnection with
    exponential backoff + jitter — in-flight pipelined state is DISCARDED
    (unacked commits are lost; async SGD tolerates dropped updates),
    in-flight pulls are re-issued against the new connection so the next
    ``wait_weights`` observes the (possibly restarted) hub's fresh center,
    and the interrupted operation is retried.  ``max_reconnects`` is a
    lifetime budget (a flapping hub cannot storm forever);
    ``reconnect_backoff`` seeds the exponential delay, capped at
    ``reconnect_backoff_max``, each attempt jittered into
    ``[0.5, 1.0] x`` the nominal delay so a fleet of workers does not
    thundering-herd a restarted hub.  With the default
    ``max_reconnects=0`` faults raise exactly as before.

    ``heartbeat_interval`` (seconds, default off) starts a daemon thread
    that sends a 13-byte ping whenever the connection has been idle that
    long with nothing in flight — keeping a slow-but-alive worker (long
    compile, big window) from tripping the hub's ``idle_timeout``
    eviction.  Socket sends and reply bookkeeping share one lock so the
    ping and its ack slot into the reply FIFO without racing the hot path.

    Telemetry (client side): ``ps.commit_bytes`` wire bytes,
    ``ps.pull_latency_ms`` / ``ps.commit_latency_ms`` send-to-reply-
    consumed latencies, ``ps.pull_stall_ms`` time actually BLOCKED waiting
    for weights (the post-overlap stall the trainer pays),
    ``ps.serialize_ms`` frame-pack time, ``ps.inflight_depth`` unacked
    commits, ``ps.reconnects`` successful reconnections and
    ``ps.reconnect_ms`` fault-to-reconnected recovery time."""

    def __init__(self, host: str, port: int, templates: Sequence[np.ndarray],
                 timeout: Optional[float] = 60.0,
                 compress: Optional[str] = None,
                 max_inflight: int = 2,
                 max_reconnects: int = 0,
                 reconnect_backoff: float = 0.1,
                 reconnect_backoff_max: float = 5.0,
                 heartbeat_interval: Optional[float] = None,
                 trace_context: Optional["dtrace.TraceContext"] = None,
                 shard_id: Optional[int] = None,
                 failover: Sequence[Tuple[str, int]] = (),
                 sparse_leaves: Sequence[int] = (),
                 adaptive: bool = False,
                 sparse_cache_rows: Optional[int] = None,
                 shm: bool = False,
                 job: Optional[str] = None):
        if compress not in (None, "int8"):
            raise ValueError(f"unknown compress {compress!r}; use None or 'int8'")
        self.templates = [np.asarray(t, dtype=np.float32) for t in templates]
        self.compress = compress
        # row-sparse embedding tables (ISSUE 9): leaf indices exchanged by
        # row set.  The client keeps one full-size host CACHE per table: a
        # full pull (sparse_rows=None) seeds it, each sparse pull merges
        # just the touched rows into it, and wait_weights hands the cache
        # out in place of a landing buffer — callers see full-order weight
        # lists either way while only touched rows cross the wire.  Rows
        # the hub updated that this worker never re-pulls stay stale in
        # the cache, which is exactly the per-row staleness the async
        # algorithms already tolerate (untouched rows also receive no
        # gradient, so their committed delta is zero)
        self._sparse = tuple(sorted({int(i) for i in sparse_leaves}))
        for i in self._sparse:
            if not 0 <= i < len(self.templates):
                raise ValueError(f"sparse leaf index {i} out of range for "
                                 f"{len(self.templates)} templates")
            if self.templates[i].ndim != 2:
                raise ValueError(f"sparse leaf {i} must be a [rows, dim] "
                                 f"table, got {self.templates[i].shape}")
        self._sparse_set = frozenset(self._sparse)
        # hot-tier client caching (ISSUE 15): ``sparse_cache_rows=N``
        # replaces the full-size per-table host cache (and, under int8,
        # the full-size residual slab) with one bounded :class:`_RowLRU`
        # per table — host memory scales with the configured hot tier,
        # not the vocabulary.  A sparse pull then fetches only the rows
        # NOT resident (hits are gathered locally at issue time, so a
        # hot row costs zero wire), ``wait_weights`` hands back a
        # ``[k, dim]`` row block aligned with the request ids instead of
        # a full-shape table, and the client's own commits merge into
        # resident rows in place.  ``None`` (default) keeps the PR-9
        # full-cache path byte-identical.
        _init_hot_tier(self, sparse_cache_rows, compress)
        self._sp_enc = net.VarFrameEncoder() if self._sparse else None
        # ids of in-flight sparse pulls, FIFO-aligned with the
        # ACTION_SPARSE_WEIGHTS entries in _pending (a reconnect re-issues
        # from here, so it never clears with _pending).  Full-cache mode
        # entries are the per-table id lists; cache mode entries are
        # richer records (request ids + the partially-gathered result
        # blocks + the miss subsets the wire was asked for)
        self._sparse_pull_ids: Deque[Any] = deque()
        # per-shard connection of a striped client (ShardedPSClient): every
        # client-side metric/span carries the shard label so the per-shard
        # wall/wire decomposition is readable straight off the registry.
        # None (all unsharded callers) emits the exact pre-sharding series
        self.shard_id = None if shard_id is None else int(shard_id)
        self._mlabels = ({} if shard_id is None
                         else {"shard": str(int(shard_id))})
        # failover-event dedup key: a process-monotonic ordinal, NOT
        # id(self) — CPython reuses addresses after GC, and a recycled id
        # would let a replacement client's failover land inside the dead
        # client's cooldown and vanish
        self._client_ordinal = next(_CLIENT_ORDINALS)
        # int8 error-feedback residuals: full-shape per leaf — except the
        # sparse leaves of a hot-tier client, whose residuals live in the
        # bounded LRU slots (None placeholders keep leaf alignment)
        self._residual = ([None if (self._cache_rows is not None
                                    and i in self._sparse_set)
                           else np.zeros(t.shape, np.float32)
                           for i, t in enumerate(self.templates)]
                          if compress else None)
        self._codec = net.FlatFrameCodec(self.templates)
        # int8 commits have their own fixed layout (4-byte scale + one int8
        # per element), so they get their own preallocated frame
        self._q_codec = (net.FlatFrameCodec(
            [np.zeros(4 + t.size, np.uint8) for t in self.templates])
            if compress == "int8" else None)
        self.max_inflight = max(1, int(max_inflight))
        self._pending: Deque[Tuple[bytes, float]] = deque()  # expected replies, wire order
        self._pull_frame = net.empty_tensor_frame(net.ACTION_PULL)
        # hot-tier mode keeps NO preallocated full-shape landing storage
        # for sparse leaves (that storage is the memory the LRU bounds);
        # the rare full pull (initial seed, explicit re-sync) lands those
        # slots in transient arrays allocated per call
        if self._cache_rows is None:
            self._pull_bufs = ([np.empty_like(t) for t in self.templates],
                               [np.empty_like(t) for t in self.templates])
        else:
            self._pull_bufs = tuple(
                [None if i in self._sparse_set else np.empty_like(t)
                 for i, t in enumerate(self.templates)]
                for _ in range(2))
        self._flip = 0
        # weights replies consumed off the wire but not yet claimed by
        # wait_weights (commit_nowait pre-drains them — see below); two
        # landing buffers bound this queue at two entries
        self._ready: Deque[List[np.ndarray]] = deque()
        self.host, self.port, self.timeout = host, int(port), timeout
        # failover address list (ISSUE 7): the primary's address first,
        # then each hot standby.  Reconnect attempts rotate through the
        # list (retry the current address once, then walk the standbys),
        # all under the ONE lifetime budget — failing over is just a
        # reconnect that lands elsewhere, so the backoff/jitter/budget
        # semantics PR 4 established apply unchanged
        self._addresses: List[Tuple[str, int]] = (
            [(str(host), int(port))]
            + [(str(h), int(p)) for h, p in (failover or ())])
        self._addr_idx = 0
        self.max_reconnects = int(max_reconnects)
        self.reconnect_backoff = float(reconnect_backoff)
        self.reconnect_backoff_max = float(reconnect_backoff_max)
        self.reconnects_used = 0
        # reconnects that LANDED on a different (standby) address — the
        # cumulative count the worker's health reports carry (ISSUE 8), so
        # the hub-side failover-storm detector sees it as a moving series
        self.failovers_used = 0
        # reconnect-storm backpressure (ISSUE 10): adaptive clients
        # announce every reconnect with an action-G frame and honor the
        # hub's retry-after hint — a shed herd spreads over time instead
        # of hammering the hub in lockstep.  Default off: no G frame ever
        # moves, the byte stream is exactly the pre-adaptive one
        self.adaptive = bool(adaptive)
        self.backpressure_waits = 0
        # multi-job namespace (ISSUE 19): job="name" announces a job_ns
        # key on a T frame at every (re)connect and trains against the
        # hub's admission-controlled private center for that job.  None
        # (default): no announce — the default namespace, byte-identical
        # to the pre-multi-job client
        self.job = None if job is None else str(job)
        # zero-copy shm transport (ISSUE 18): shm=True asks every fresh
        # connection for an shm attach (action Z).  The hub offers a ring
        # pair (same host, shm armed) or declines; a LEGACY hub closing
        # on the unknown action reads as a decline too — the client
        # redials plain TCP once, so the stream is never torn.  transport
        # reports what this connection actually rides ("tcp"/"shm") —
        # health reports carry it, distkeras-top displays it
        self.shm = bool(shm)
        self.transport = "tcp"
        # entropy-seeded ON PURPOSE: the jitter exists so a fleet of
        # workers severed by one hub restart does NOT retry in lockstep —
        # a shared deterministic seed would reproduce exactly that herd
        self._jitter = random.Random()
        self._closed = False
        self._consuming = False  # caller blocked in a reply recv
        # serializes socket SENDS and their _pending bookkeeping between
        # the caller thread and the heartbeat thread, so the reply FIFO
        # always matches wire order (receives stay single-threaded: only
        # the caller consumes).  Without a heartbeat thread the caller is
        # the ONLY thread touching the socket, so the hot path takes a
        # no-op guard instead of a real lock — the pipelined exchange pays
        # nothing for resilience it hasn't enabled
        self._io_lock = (threading.Lock() if heartbeat_interval is not None
                         else contextlib.nullcontext())
        self._last_io = time.monotonic()
        self.sock = self._connect_any()
        self._maybe_attach_shm()
        # distributed tracing (ISSUE #5): this worker's trace context,
        # announced over the wire (action T) so the hub's spans are
        # attributable, with the local->hub clock offset estimated from
        # the announce round trips (NTP-style midpoint).  Off (None) by
        # default: an un-announced client sends exactly the pre-T byte
        # stream, so it interoperates with pre-T hubs
        self.trace_context = trace_context
        self.clock_offset_ns = 0
        self.clock_error_ns: Optional[int] = None
        self.heartbeat_interval = (None if heartbeat_interval is None
                                   else float(heartbeat_interval))
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._ping_frame = net.empty_tensor_frame(net.ACTION_PING)
        # job announce first (ISSUE 19): the admission verdict must
        # settle before ANY other traffic — a rejected job fails loudly
        # at construction instead of training on the default center.
        # Same failure contract as the trace announce below: close the
        # socket, leave a closeable object, re-raise
        if self.job is not None:
            try:
                self._announce_job()
            except BaseException:
                try:
                    self.sock.close()
                except OSError:
                    pass
                raise
        # announce AFTER every attribute exists (a failed announce —
        # e.g. tracing enabled against a pre-T hub — must leave an object
        # whose close() works) and BEFORE the heartbeat thread starts
        # (the announce round trips own the socket exclusively)
        if trace_context is not None:
            try:
                self._announce_and_sync()
            except BaseException:
                try:
                    self.sock.close()
                except OSError:
                    pass
                raise
        if self.heartbeat_interval is not None:
            self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                               daemon=True)
            self._hb_thread.start()

    # -- multi-job namespace (ISSUE 19) ----------------------------------------
    def _announce_job(self) -> None:
        """Send the job-scoped T announce (a ``job_ns`` JSON key) and
        settle the admission verdict.  Runs on a freshly-connected
        socket before any pipelined traffic — the strict reply FIFO is
        never disturbed — and raises :class:`JobAdmissionError` on a
        reject, so a rejected job can never be silently served the
        default center."""
        doc = json.dumps({"job_ns": self.job}).encode("utf-8")
        net.send_frame(self.sock, net.encode_context_payload(doc))
        action, blobs = net.recv_tensors(self.sock)
        if action != net.ACTION_TRACE:
            raise net.ProtocolError(
                f"expected T reply to job announce, got {action!r}")
        _t_ns, admitted, reason = net.decode_admission_payload(blobs)
        if not admitted:
            raise JobAdmissionError(self.job, reason)

    # -- distributed tracing ---------------------------------------------------
    def _announce_and_sync(self, rounds: int = 3) -> None:
        """Send the action-T context announce and estimate the local->hub
        clock offset from its round trips: the hub stamps its monotonic
        clock into each reply, ``offset = hub_ts - (t0 + t1) / 2``, and
        the minimum-RTT sample wins (its error bound, rtt/2, is the
        alignment-error contract ``merge_traces`` documents).  Runs on the
        freshly-connected socket BEFORE any pipelined traffic, so the
        strict reply FIFO is never disturbed."""
        announce = net.encode_context_payload(
            self.trace_context.to_json().encode("utf-8"))
        best_rtt = best_offset = None
        for _ in range(max(1, rounds)):
            t0 = time.perf_counter_ns()
            net.send_frame(self.sock, announce)
            action, blobs = net.recv_tensors(self.sock)
            t1 = time.perf_counter_ns()
            if action != net.ACTION_TRACE:
                raise net.ProtocolError(
                    f"expected T reply to context announce, got {action!r}")
            hub_ns = net.decode_time_payload(blobs)
            rtt = t1 - t0
            if best_rtt is None or rtt < best_rtt:
                best_rtt = rtt
                best_offset = hub_ns - (t0 + t1) // 2
        self.clock_offset_ns = int(best_offset)
        self.clock_error_ns = int(best_rtt) // 2
        dtrace.record_clock_sync(self.clock_offset_ns, self.clock_error_ns)

    # -- resilience ------------------------------------------------------------
    _RETRYABLE = (ConnectionError, OSError, net.ProtocolError)
    # hub-paced retry-after waits are refunded from the reconnect budget
    # up to this many times; past it they start consuming budget again,
    # so a hub that never stops hinting cannot livelock a worker forever
    _MAX_BP_WAITS = 32
    # ceiling on any single honored hint: the hub caps its own at 2 s,
    # so a larger value is a version-skewed/buggy hub or a corrupted
    # blob — a worker must never be parked on a garbage uint64 of ms
    _MAX_RETRY_AFTER_MS = 10_000

    def _reconnect_hello(self, waits_taken: int) -> int:
        """The G/Y round trip on a freshly dialed connection (adaptive
        clients only): announce the reconnect — carrying how many
        hub-paced waits this episode already took, so a client that
        waited its slot is admitted — and return the hub's retry-after
        hint in milliseconds.  Connection faults raise the usual
        retryable types — the attempt's handler rotates and backs off
        exactly as for a failed dial."""
        net.send_frame(self.sock,
                       net.encode_reconnect_payload(waits_taken))
        action, blobs = net.recv_tensors(self.sock)
        if action != net.ACTION_RETRY:
            raise net.ProtocolError(
                f"expected Y reply to reconnect announce, got {action!r}")
        return min(net.decode_retry_payload(blobs), self._MAX_RETRY_AFTER_MS)

    def _connect_any(self) -> socket.socket:
        """Initial connect: the primary first, then each failover address
        in order — a worker (re)started AFTER a failover must be able to
        join the promoted standby without an operator rewriting its
        config.  Raises the primary's error when every address refuses."""
        first_err: Optional[BaseException] = None
        for i, (host, port) in enumerate(self._addresses):
            try:
                sock = net.connect(host, port, timeout=self.timeout,
                                   payload_hint=self._codec.frame_len)
            except OSError as e:
                if first_err is None:
                    first_err = e
                continue
            self._addr_idx = i
            self.host, self.port = host, port
            return sock
        raise first_err  # at least one address exists, so this is set

    def _maybe_attach_shm(self) -> None:
        """The action-Z attach on a freshly dialed connection (shm clients
        only): request, map the offered ring pair, confirm over TCP, then
        swap :attr:`sock` for a :class:`~.networking.ShmEndpoint` — every
        subsequent frame rides shared memory, byte-identical to what the
        socket would have carried.  A decline (or a mapping failure,
        aborted over TCP) leaves the connection pure TCP; a legacy hub
        CLOSING on the unknown action is treated as a decline and the
        client redials plain TCP once — the connection fault never
        escapes, so the protocol model's never-torn walk holds here."""
        self.transport = "tcp"
        if not self.shm:
            return
        try:
            net.send_frame(self.sock, net.encode_shm_request(
                max(net.SHM_RING_DEFAULT_CAPACITY,
                    2 * self._codec.frame_len)))
            action, blobs = net.recv_tensors(self.sock)
            if action != net.ACTION_SHM:
                raise net.ProtocolError(
                    f"expected Z reply to shm request, got {action!r}")
            offer = net.decode_shm_offer(blobs)
        except (ConnectionError, OSError, net.ProtocolError):
            # legacy hub: it dropped the connection on the unknown
            # action.  No frame beyond the Z request ever moved, so a
            # single plain-TCP redial resumes cleanly
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = self._connect_any()
            return
        if offer is None:
            return  # hub declined; stay on TCP
        c2h_path, h2c_path = offer
        try:
            tx_ring = net.ShmFrameRing.open(c2h_path, "producer")
        except (OSError, net.ProtocolError):
            net.send_frame(self.sock, net.encode_shm_confirm(False))
            return
        try:
            rx_ring = net.ShmFrameRing.open(h2c_path, "consumer")
        except (OSError, net.ProtocolError):
            tx_ring.close()
            net.send_frame(self.sock, net.encode_shm_confirm(False))
            return
        # confirm rides TCP: the hub reads it off the FIFO, so both ends
        # agree the very NEXT frame is on the rings — never a torn stream
        net.send_frame(self.sock, net.encode_shm_confirm(True))
        self.sock = net.ShmEndpoint(self.sock, tx_ring, rx_ring)
        self.sock.settimeout(self.timeout)
        self.transport = "shm"

    def _heartbeat_loop(self) -> None:
        interval = self.heartbeat_interval
        while not self._hb_stop.wait(interval / 4.0):
            with self._io_lock:
                if self._closed:
                    return
                # only ping a genuinely idle connection: traffic in flight
                # already proves liveness, and interleaving a ping between
                # a request and its reply is exactly what the FIFO forbids.
                # _consuming covers the caller mid-receive (it pops the
                # pending entry BEFORE its blocking recv, so _pending alone
                # can look empty while the socket is busy) — its rising
                # edge is serialized with this critical section, so a ping
                # round trip and a caller recv can never interleave
                if (self._pending or self._consuming
                        or time.monotonic() - self._last_io < interval):
                    continue
                try:
                    # the ping's ack is consumed HERE, under the io lock
                    # (the caller is idle by construction — nothing
                    # pending — so this thread owns the whole round trip;
                    # leaving the ack for the caller would stall the next
                    # ping behind a reply nobody is consuming).  The round
                    # trip runs under its OWN short timeout: a ping must
                    # never hold the io lock for the full data-plane
                    # timeout, or close()/reconnect would block behind an
                    # idle-liveness probe for up to a minute
                    ping_timeout = max(1.0, interval)
                    if self.timeout is not None:
                        ping_timeout = min(ping_timeout, self.timeout)
                    self.sock.settimeout(ping_timeout)
                    try:
                        self.sock.sendall(self._ping_frame)
                        net.recv_action(self.sock)
                    finally:
                        self.sock.settimeout(self.timeout)
                    self._last_io = time.monotonic()
                except (OSError, ValueError):
                    # poison the connection: a ping whose ack timed out may
                    # deliver that ack LATE, and a caller then parsing it
                    # as its own reply would desync the stream.  Closing
                    # here turns the caller's next op into a clean
                    # ConnectionError/EBADF — which reconnects when a
                    # budget is configured.  NOTE the whole ping (and this
                    # close) runs under the io lock, and _reconnect swaps
                    # the socket under the SAME lock with _last_io reset:
                    # a ping can never fire into a half-swapped socket,
                    # and a swap can never be poisoned by a stale ping —
                    # so a heartbeat racing a reconnect costs the caller
                    # ZERO budget beyond the real fault
                    # (tests/test_ha.py pins this)
                    try:
                        self.sock.close()
                    except OSError:
                        pass

    def _resilient(self, op):
        """Run ``op`` to completion, reconnecting (bounded) across any
        connection fault.  With ``max_reconnects=0`` the original
        exception propagates untouched — the pre-resilience contract."""
        while True:
            try:
                return op()
            except self._RETRYABLE as e:
                if self._closed or self.max_reconnects <= 0:
                    raise
                self._reconnect(e)

    def _reconnect(self, cause: BaseException) -> None:
        """Tear down the desynchronized connection, back off (exponential +
        jitter), reconnect, and re-issue any pulls that were in flight —
        the re-pull observes the (possibly restarted) hub's CURRENT
        center.  Unacked commits are dropped, not replayed: a commit whose
        send or ack failed may or may not have been applied, and async SGD
        tolerates a lost update far better than a doubled one.  Raises
        ``ConnectionError`` from ``cause`` once the lifetime budget is
        exhausted."""
        t_fault = time.perf_counter()
        t_fault_ns = time.perf_counter_ns()
        addr_at_fault = (self.host, self.port)
        # the ENTIRE teardown/backoff/redial runs under the io lock: the
        # heartbeat thread must neither ping a socket mid-replacement nor
        # close (its failure path) the freshly reconnected one — and with
        # no heartbeat the lock is a no-op context, so the common path
        # pays nothing.  Entered lock-free: every op releases the lock
        # before its exception reaches _resilient
        with self._io_lock:
            # in-flight pulls to re-issue, in wire order; sparse pulls
            # keep their ids in _sparse_pull_ids (which deliberately does
            # NOT clear with _pending — it is the re-issue source)
            lost_kinds = [kind for kind, _ in self._pending
                          if kind in (net.ACTION_WEIGHTS,
                                      net.ACTION_SPARSE_WEIGHTS)]
            self._pending.clear()
            try:
                self.sock.close()
            except OSError:
                pass
            # hub-paced waits taken in THIS reconnect episode: the G
            # announce carries it, so the hub admits us once we have
            # waited our slot (one wait per client per storm).  A redial
            # right after a slot wait skips the exponential backoff —
            # the hub just SCHEDULED our arrival; re-randomizing on top
            # would scramble the paced order the slots exist to create
            bp_episode = 0
            skip_backoff = False
            while True:
                if self.reconnects_used >= self.max_reconnects:
                    raise ConnectionError(
                        f"PS connection to {self.host}:{self.port} lost and the "
                        f"reconnect budget ({self.max_reconnects}) is exhausted"
                        + (f" across {len(self._addresses)} failover addresses"
                           if len(self._addresses) > 1 else "")
                    ) from cause
                self.reconnects_used += 1
                if skip_backoff:
                    skip_backoff = False
                else:
                    nominal = min(self.reconnect_backoff
                                  * (2.0 ** (self.reconnects_used - 1)),
                                  self.reconnect_backoff_max)
                    time.sleep(nominal * (0.5 + 0.5 * self._jitter.random()))
                # address rotation: the current address gets one retry,
                # then attempts walk the failover list — a dead primary's
                # refused connect fails fast, so the standby is reached
                # on the very next budgeted attempt
                host, port = self._addresses[self._addr_idx]
                try:
                    self.sock = net.connect(host, port,
                                            timeout=self.timeout,
                                            payload_hint=self._codec.frame_len)
                    self.host, self.port = host, port
                    # reconnect-storm backpressure (ISSUE 10): announce
                    # the reconnect (action G) and honor the hub's
                    # retry-after hint.  Hub-paced waits are
                    # budget-NEUTRAL (refunded, bounded by _MAX_BP_WAITS
                    # against a hub that never stops hinting): being told
                    # to wait by a healthy hub is not a fault, and a shed
                    # herd must not exhaust its reconnect budgets
                    if self.adaptive:
                        hint_ms = self._reconnect_hello(bp_episode)
                        if hint_ms > 0:
                            try:
                                self.sock.close()
                            except OSError:
                                pass
                            bp_episode += 1
                            self.backpressure_waits += 1
                            if bp_episode <= self._MAX_BP_WAITS:
                                self.reconnects_used -= 1
                            if obs.enabled():
                                obs.counter("ps.backpressure_waits",
                                            **self._mlabels).inc()
                                obs.histogram("ps.retry_after_wait_ms",
                                              **self._mlabels).observe(
                                    hint_ms)
                            time.sleep(hint_ms / 1000.0)
                            skip_backoff = True
                            continue
                    # re-negotiate the shm attach on the fresh connection
                    # (ring files are per-connection; the old pair died
                    # with the old socket).  Landing on TCP — a standby
                    # with shm off, a remote failover target — is a
                    # degrade, not a fault
                    self._maybe_attach_shm()
                    # re-announce the job namespace (admission is
                    # per-connection; a restarted hub re-admits, a full
                    # or standby hub rejects — a ProtocolError here
                    # rotates to the next address under the same budget)
                    if self.job is not None:
                        self._announce_job()
                    # re-announce the trace context on the fresh
                    # connection (a restarted hub has no memory of the
                    # old one) and refresh the clock-offset estimate
                    if self.trace_context is not None:
                        self._announce_and_sync()
                    # re-pull cleanly INSIDE the attempt: the discarded
                    # in-flight pulls are re-issued so wait_weights finds
                    # its reply.  A hub dying again right here must consume
                    # another budgeted attempt, not escape to the caller —
                    # this runs inside _resilient's except handler, where a
                    # raised exception would NOT be re-caught by its loop
                    si = 0
                    for kind in lost_kinds:
                        if kind == net.ACTION_WEIGHTS:
                            self.sock.sendall(self._pull_frame)
                        else:
                            # re-ask for the SAME rows; the reply observes
                            # the restarted hub's current center like any
                            # re-issued pull (hot-tier records re-send
                            # their recorded MISS subset — the hit rows
                            # were resolved locally at issue time)
                            sp = self._sparse_pull_ids[si]
                            self._sp_enc.send(self.sock,
                                              net.ACTION_SPARSE_PULL,
                                              sp["miss"]
                                              if isinstance(sp, dict)
                                              else sp)
                            si += 1
                        self._pending.append((kind, time.perf_counter()))
                    self._last_io = time.monotonic()
                    break
                except (OSError, net.ProtocolError):
                    # hub still down (or died again mid-re-pull/announce):
                    # drop any entries from the half-reconnected socket,
                    # rotate to the next address and back off further
                    self._pending.clear()
                    self._addr_idx = ((self._addr_idx + 1)
                                      % len(self._addresses))
                    continue
        failed_over = (self.host, self.port) != addr_at_fault
        if obs.enabled():
            # labelled by announced worker identity when tracing is on, so
            # fleet_report can attribute reconnect storms to a worker
            wattrs = (self.trace_context.span_attrs()
                      if self.trace_context is not None else {})
            obs.counter("ps.reconnects", **self._mlabels).inc()
            obs.histogram("ps.reconnect_ms", **self._mlabels).observe(
                (time.perf_counter() - t_fault) * 1e3)
            obs.TRACER.record_span("ps.reconnect", t_fault_ns,
                                   time.perf_counter_ns(), **self._mlabels,
                                   **wattrs)
            if failed_over:
                # the reconnect landed on a different (standby) address:
                # record the fault-to-recovered failover time — the
                # availability number the kill-primary drills pin
                obs.counter("ps.failovers", **self._mlabels).inc()
                obs.histogram("ps.failover_ms", **self._mlabels).observe(
                    (time.perf_counter() - t_fault) * 1e3)
                obs.TRACER.record_span(
                    "ps.failover", t_fault_ns, time.perf_counter_ns(),
                    from_addr=f"{addr_at_fault[0]}:{addr_at_fault[1]}",
                    to_addr=f"{self.host}:{self.port}",
                    **self._mlabels, **wattrs)
        if failed_over:
            self.failovers_used += 1
            warnings.warn(f"PS client failed over from "
                          f"{addr_at_fault[0]}:{addr_at_fault[1]} to "
                          f"{self.host}:{self.port}")
            # live health plane (ISSUE 8): surface the failover as a
            # HealthEvent in THIS process's monitor immediately — naming
            # the standby the client landed on — so a co-located
            # distkeras-top / punchcard health pull sees it during the
            # run (remote hubs additionally learn of it through the
            # failovers_total series in the next health report)
            try:
                from distkeras_tpu.observability import health as _health

                _health.monitor().emit(
                    "failover", "critical",
                    worker=(self.trace_context.worker_id
                            if self.trace_context is not None else None),
                    shard=self.shard_id,
                    # untraced clients carry no worker id: without a
                    # per-client dedup, every failover of a multi-worker
                    # fleet in one process would collapse to the first
                    dedup=f"client:{self._client_ordinal}",
                    from_addr=f"{addr_at_fault[0]}:{addr_at_fault[1]}",
                    to_addr=f"{self.host}:{self.port}",
                    failover_ms=round((time.perf_counter() - t_fault) * 1e3,
                                      1))
            except Exception:
                pass

    # -- pipelined API ---------------------------------------------------------
    def pull_nowait(self, sparse_rows: Optional[Sequence] = None) -> None:
        """Fire a pull request; the reply is consumed later by
        :meth:`wait_weights`.  Issue it while the device computes and the
        weights' wire time hides under the window.

        ``sparse_rows`` (sparse-configured clients only): one row-id array
        per sparse table — the pull moves only those rows (action ``S``),
        merging them into the client cache on receive.  ``None`` pulls the
        full center (action ``P``, the pre-sparse byte stream; also
        re-seeds the caches)."""
        with self._io_lock:
            outstanding = (sum(1 for kind, _ in self._pending
                               if kind in (net.ACTION_WEIGHTS,
                                           net.ACTION_SPARSE_WEIGHTS))
                           + len(self._ready))
        if outstanding >= 2:
            raise RuntimeError("at most 2 pulls may be outstanding (two "
                               "landing buffers); claim one with "
                               "wait_weights() first")
        if sparse_rows is None:
            self._resilient(self._pull_nowait_once)
            return
        if not self._sparse:
            raise ValueError("sparse_rows passed to a client with no "
                             "sparse_leaves configured")
        if len(sparse_rows) != len(self._sparse):
            raise ValueError(f"got {len(sparse_rows)} id arrays, client has "
                             f"{len(self._sparse)} sparse tables")
        ids_list = [net.normalize_row_ids(ids, self.templates[i].shape[0])
                    for ids, i in zip(sparse_rows, self._sparse)]
        if self._cache_rows is None:
            self._resilient(lambda: self._sparse_pull_once(ids_list))
            return
        # hot-tier path: resolve hits against the LRU NOW (their values
        # are copied into the result blocks at this instant — the center
        # state a full-cache client's pull would also have observed at
        # issue time) and ask the wire for only the misses.  The gather
        # runs once, outside the retry loop: a reconnect re-sends the
        # SAME miss subset
        blocks, miss_pos, miss = _hot_tier_gather(self, ids_list)
        record = {"ids": ids_list, "out": blocks, "miss_pos": miss_pos,
                  "miss": miss}
        self._resilient(lambda: self._sparse_pull_once(record["miss"],
                                                       record=record))

    def _pull_nowait_once(self) -> None:
        with self._io_lock:
            net.send_raw_frame(self.sock, self._pull_frame)
            self._pending.append((net.ACTION_WEIGHTS, time.perf_counter()))
            self._last_io = time.monotonic()

    def _sparse_pull_once(self, ids_list: List[np.ndarray],
                          record: Optional[Dict[str, Any]] = None) -> None:
        with self._io_lock:
            self._sp_enc.send(self.sock, net.ACTION_SPARSE_PULL, ids_list)
            self._pending.append((net.ACTION_SPARSE_WEIGHTS,
                                  time.perf_counter()))
            self._sparse_pull_ids.append(
                ids_list if record is None else record)
            self._last_io = time.monotonic()

    def commit_nowait(self, delta: Sequence[np.ndarray],
                      sparse_rows: Optional[Sequence] = None) -> None:
        """Send a commit without waiting for its ack (coalesced into a later
        receive).  Blocks only when ``max_inflight`` commits are already
        unacknowledged.

        ``sparse_rows`` (sparse-configured clients only): one row-id array
        per sparse table — the commit carries only those rows' gradients
        as ``(ids, grads)`` pairs (action ``U``, or ``X`` under int8)."""
        # the span covers the work the client actually does per commit
        # (back-pressure + quantize/pack + send); the ack wait is measured
        # separately by ps.commit_latency_ms when the reply is consumed
        with obs.span("ps.commit", compress=self.compress or "none",
                      **self._mlabels):
            self._resilient(
                lambda: self._commit_nowait_once(delta, sparse_rows))

    def _commit_nowait_once(self, delta: Sequence[np.ndarray],
                            sparse_rows: Optional[Sequence] = None) -> None:
        # deadlock avoidance: never start a potentially-blocking large
        # send while a weights reply may still be in flight — the hub
        # does not read while it writes, so two big sendalls in
        # opposite directions can fill both kernel buffers and stall
        # forever once frames outgrow the socket buffers.  Claim any
        # pending pull into its landing buffer first (wait_weights
        # hands it out later); the hub is then parked in recv when the
        # commit bytes arrive.  This receive time is pull wire-wait,
        # so it lands in ps.pull_stall_ms like any other pull block.
        if self._has_pending(net.ACTION_WEIGHTS) \
                or self._has_pending(net.ACTION_SPARSE_WEIGHTS):
            t_drain = time.perf_counter() if obs.enabled() else 0.0
            while (self._has_pending(net.ACTION_WEIGHTS)
                   or self._has_pending(net.ACTION_SPARSE_WEIGHTS)):
                self._consume_one()
            if t_drain:
                obs.histogram("ps.pull_stall_ms", **self._mlabels).observe(
                    (time.perf_counter() - t_drain) * 1e3)
        while self._unacked() >= self.max_inflight:
            self._consume_one()
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        if sparse_rows is not None:
            if not self._sparse:
                raise ValueError("sparse_rows passed to a client with no "
                                 "sparse_leaves configured")
            if len(sparse_rows) != len(self._sparse):
                # checked BEFORE the zip below, which would truncate
                raise ValueError(f"got {len(sparse_rows)} id arrays, client "
                                 f"has {len(self._sparse)} sparse tables")
            ids_list = [net.normalize_row_ids(ids, self.templates[i].shape[0])
                        for ids, i in zip(sparse_rows, self._sparse)]
            if self._cache_rows is None:
                arrays = _sparse_commit_arrays(
                    delta, self.templates, self._sparse_set, ids_list,
                    self._residual, self.compress)
            else:
                arrays = self._cached_commit_arrays(delta, ids_list)
            action = (net.ACTION_SPARSE_QCOMMIT if self.compress == "int8"
                      else net.ACTION_SPARSE_COMMIT)
            frame = self._sp_enc.pack(action, arrays)
            if telemetry:
                obs.histogram("ps.serialize_ms", **self._mlabels).observe(
                    (time.perf_counter() - t0) * 1e3)
                obs.counter("ps.commit_bytes",
                            **self._mlabels).inc(self._sp_enc.frame_len)
            with self._io_lock:
                net.send_raw_frame(self.sock, frame)
                self._pending.append((net.ACTION_ACK, time.perf_counter()))
                self._last_io = time.monotonic()
            if telemetry:
                obs.gauge("ps.inflight_depth",
                          **self._mlabels).set(self._unacked())
            return
        if self.compress == "int8":
            codec, action = self._q_codec, net.ACTION_QCOMMIT
            # safe across a reconnect retry: the residual chain carries
            # only ROUNDING error, so re-quantizing the same delta after
            # a failed (never-applied) send still lands the delta once
            arrays = _quantize_commit(delta, self._residual)
        else:
            codec, action = self._codec, net.ACTION_COMMIT
            arrays = [np.asarray(d, np.float32) for d in delta]
        codec.pack(action, arrays)
        if telemetry:
            obs.histogram("ps.serialize_ms", **self._mlabels).observe(
                (time.perf_counter() - t0) * 1e3)
            obs.counter("ps.commit_bytes", **self._mlabels).inc(codec.frame_len)
        with self._io_lock:
            codec.send_packed(self.sock)
            self._pending.append((net.ACTION_ACK, time.perf_counter()))
            self._last_io = time.monotonic()
        if telemetry:
            obs.gauge("ps.inflight_depth", **self._mlabels).set(self._unacked())

    def _cached_commit_arrays(self, delta: Sequence[np.ndarray],
                              ids_list: List[np.ndarray]) -> List[np.ndarray]:
        """Hot-tier twin of :func:`_sparse_commit_arrays`: U/X wire blobs
        for one commit with the per-row state read from the bounded LRU
        instead of full-shape slabs.  Three extra duties:

        - **flush union**: row ids whose int8 residuals were evicted
          since the last commit join this commit's id set (their delta
          rows are the model's true gradient for those rows — zero when
          untouched — plus the flushed residual), so eviction never
          loses error-feedback state;
        - **slot residuals**: carried/stored per resident row; a row
          evicted AND flushed in the same interval contributes both its
          pending and (zeroed-at-reinsert) slot residual exactly once;
        - **hits merge in place**: the post-wire committed rows (the
          exact values the hub will apply at scale 1) fold into resident
          LRU entries, so a hot row's cached value tracks this client's
          own progress between misses.

        With ``cache_rows >= vocabulary`` (no evictions) the produced
        wire bytes are identical to the full-slab path's — the
        trajectory-parity property ``tests/test_hyperscale.py`` pins."""
        return _hot_tier_commit_arrays(self, delta, ids_list)

    def wait_weights(self) -> List[np.ndarray]:
        """Hand out the oldest in-flight pull, consuming replies (and any
        commit acks queued ahead of it) as needed."""
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        self._resilient(self._fill_ready_once)
        if telemetry:
            obs.histogram("ps.pull_stall_ms", **self._mlabels).observe(
                (time.perf_counter() - t0) * 1e3)
        return self._ready.popleft()

    def _fill_ready_once(self) -> None:
        while not self._ready:
            if not self._pending:
                # caller bug, not a connection fault (RuntimeError keeps it
                # out of _RETRYABLE — it must not burn the reconnect
                # budget; matches InprocPSClient's contract)
                raise RuntimeError("wait_weights() with no pull in flight")
            self._consume_one()

    def drain(self) -> None:
        """Consume every outstanding reply — trailing commit acks at the end
        of a run, plus any prefetched pull that will go unused."""
        self._resilient(self._drain_once)
        self._ready.clear()
        if obs.enabled():
            obs.gauge("ps.inflight_depth", **self._mlabels).set(0)

    def _drain_once(self) -> None:
        while self._pending:
            self._consume_one()

    # -- live health plane (ISSUE 8) -------------------------------------------
    def report_health(self, report: Dict[str, Any]) -> None:
        """Push one compact health report to the hub (wire action ``M``) —
        the worker half of the streaming collector.  Fire-and-forget on
        the pipelined FIFO: the hub's ack coalesces into later receives
        exactly like a commit ack, so a report costs one small send, not a
        round trip.  Opt-in like the ``T`` announce: a client that never
        reports sends exactly the pre-``M`` byte stream (and a report sent
        to a hub that predates action ``M`` surfaces as a connection
        fault, the documented upgrade contract)."""
        payload = net.encode_health_payload(
            json.dumps(report).encode("utf-8"))
        self._resilient(lambda: self._report_health_once(payload))

    def _report_health_once(self, payload: bytes) -> None:
        with self._io_lock:
            # send_frame (not send_raw_frame): encode_health_payload
            # returns the prefix-less payload, like the T announce.
            # Pending kind is ACTION_HEALTH, not ACTION_ACK: the hub's
            # reply frame is the same ack byte, but a health ack must not
            # land in ps.commit_latency_ms or hold a max_inflight commit
            # slot (_unacked counts ACTION_ACK entries only)
            net.send_frame(self.sock, payload)
            self._pending.append((net.ACTION_HEALTH, time.perf_counter()))
            self._last_io = time.monotonic()

    def _has_pending(self, kind: bytes) -> bool:
        # snapshot under the io lock: the heartbeat thread appends to
        # _pending, and a deque must not be iterated during a mutation
        with self._io_lock:
            return any(k == kind for k, _ in self._pending)

    def _unacked(self) -> int:
        with self._io_lock:
            return sum(1 for kind, _ in self._pending if kind == net.ACTION_ACK)

    def _consume_one(self) -> None:
        # mark the receive busy UNDER the io lock: if a heartbeat round
        # trip is in flight we wait for it to finish; once set, the
        # heartbeat thread will not start another until we clear it
        with self._io_lock:
            self._consuming = True
        try:
            self._consume_one_inner()
        finally:
            self._consuming = False

    def _consume_one_inner(self) -> None:
        kind, t_sent = self._pending.popleft()
        if kind == net.ACTION_SPARSE_WEIGHTS:
            # sparse pull reply: dense leaves scatter into the flip
            # landing buffers exactly like a full pull, row blocks land in
            # per-pull scratch.  Full-cache mode merges them into the
            # per-table caches and hands the caches out; hot-tier mode
            # files the MISS rows into their result-block positions and
            # the LRU (hit rows were gathered at issue time), handing the
            # [k, dim] blocks out instead of full-shape tables
            entry = self._sparse_pull_ids[0]
            cached = isinstance(entry, dict)
            ids_list = entry["miss"] if cached else entry
            bufs = self._pull_bufs[self._flip]
            self._flip ^= 1
            out: List[np.ndarray] = []
            si = 0
            for i, t in enumerate(self.templates):
                if i in self._sparse_set:
                    out.append(np.empty((ids_list[si].size, t.shape[1]),
                                        np.float32))
                    si += 1
                else:
                    out.append(bufs[i])
            try:
                reply, _ = net.recv_tensors(self.sock, out=out)
                if reply != net.ACTION_SPARSE_WEIGHTS:
                    raise ConnectionError(
                        f"expected sparse weights reply, got {reply!r}")
            except Exception:
                self._flip ^= 1
                self._pending.appendleft((kind, t_sent))
                raise
            self._last_io = time.monotonic()  # lint: unguarded-ok receive leg runs outside the io lock by design; the _consuming flag excludes the heartbeat's round trips, and a racing timestamp store only under-reports idleness
            self._sparse_pull_ids.popleft()
            result: List[np.ndarray] = []
            si = 0
            misses0 = self.sparse_cache_misses
            for i in range(len(self.templates)):
                if i in self._sparse_set:
                    if cached:
                        block = entry["out"][si]
                        mp = entry["miss_pos"][si]
                        if mp.size:
                            block[mp] = out[i]
                        _hot_tier_file_misses(self, i, entry["miss"][si],
                                              out[i])
                        result.append(block)
                    else:
                        ids = ids_list[si]
                        if ids.size:
                            self._cache[i][ids] = out[i]
                        result.append(self._cache[i])
                    si += 1
                else:
                    result.append(out[i])
            self._ready.append(result)
            if cached:
                _count_cache_misses(self, misses0)
            if obs.enabled():
                obs.histogram("ps.pull_latency_ms", **self._mlabels).observe(
                    (time.perf_counter() - t_sent) * 1e3)
        elif kind != net.ACTION_WEIGHTS:
            # ACTION_ACK (commit) and ACTION_HEALTH (report) both await
            # the same ack byte; only the commit's round trip is a commit
            # latency sample
            reply = net.recv_action(self.sock)
            self._last_io = time.monotonic()  # lint: unguarded-ok receive leg runs outside the io lock by design; the _consuming flag excludes the heartbeat's round trips, and a racing timestamp store only under-reports idleness
            if reply != net.ACTION_ACK:
                raise ConnectionError(f"expected ack, got {reply!r}")
            if kind == net.ACTION_ACK and obs.enabled():
                obs.histogram("ps.commit_latency_ms", **self._mlabels).observe(
                    (time.perf_counter() - t_sent) * 1e3)
                obs.gauge("ps.inflight_depth", **self._mlabels).set(
                    self._unacked())
        else:
            bufs = self._pull_bufs[self._flip]
            self._flip ^= 1
            if self._cache_rows is None:
                out = bufs
            else:
                # hot-tier mode holds no full-shape landing storage for
                # sparse leaves — the rare full pull (initial seed,
                # explicit re-sync) lands them in transient arrays that
                # die with the caller's reference
                out = [np.empty_like(t) if b is None else b
                       for b, t in zip(bufs, self.templates)]
            try:
                reply = self._codec.recv_into(self.sock, out)
                if reply != net.ACTION_WEIGHTS:
                    raise ConnectionError(f"expected weights reply, got {reply!r}")
            except Exception:
                # the receive died mid-weights: restore the entry (and the
                # landing buffer) so a reconnect counts this pull as lost
                # and re-issues it — without this, wait_weights retried
                # after a mid-frame fault would find "no pull in flight"
                self._flip ^= 1
                self._pending.appendleft((kind, t_sent))
                raise
            self._last_io = time.monotonic()  # lint: unguarded-ok receive leg runs outside the io lock by design; the _consuming flag excludes the heartbeat's round trips, and a racing timestamp store only under-reports idleness
            # a full pull re-seeds the sparse caches: the landing buffer
            # is reused two pulls later, the cache is the stable copy the
            # sparse exchange merges into.  Hot-tier mode seeds/refreshes
            # its bounded LRU instead (_hot_tier_seed)
            for i in self._sparse:
                if self._cache_rows is None:
                    self._cache[i][...] = out[i]
                else:
                    _hot_tier_seed(self, i, out[i])
            self._ready.append(out)
            if obs.enabled():
                obs.histogram("ps.pull_latency_ms", **self._mlabels).observe(
                    (time.perf_counter() - t_sent) * 1e3)

    # -- blocking API (control plane + non-pipelined callers) ------------------
    def pull(self) -> List[np.ndarray]:
        with obs.span("ps.pull", **self._mlabels):
            self.pull_nowait()
            return self.wait_weights()

    def commit(self, delta: Sequence[np.ndarray],
               sparse_rows: Optional[Sequence] = None) -> None:
        self.commit_nowait(delta, sparse_rows=sparse_rows)
        self.drain()

    def close(self) -> None:
        self._hb_stop.set()
        # the BYE + close runs under the io lock: without it, a heartbeat
        # mid-ping (which owns the socket for its bounded round trip)
        # could interleave with the farewell frame, or poison-close a
        # socket close() is still writing to.  The bounded ping timeout
        # above caps how long this can wait
        with self._io_lock:
            self._closed = True
            try:
                net.send_raw_frame(self.sock,
                                   net.empty_tensor_frame(net.ACTION_BYE))
            except OSError:
                pass
            finally:
                try:
                    self.sock.close()
                except OSError:
                    pass
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None

    def __enter__(self) -> "PSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class InprocPSClient(_HotTierCacheSurface):
    """:class:`PSClient` surface over a co-located hub (``transport="inproc"``).

    Pull/commit call the SAME center logic the socket handlers run —
    ``pull_direct`` / ``commit_direct``, under the hub's lock — with no
    sockets, no framing, and no wire copies; the staleness clock rides the
    client object instead of a connection.  Works against the Python hubs
    and the C++ hub (both expose the direct pair).

    The nowait/wait methods execute EAGERLY at the exact program points
    the socket client would *send* at, so a deterministic (single-worker)
    schedule observes identical center states on both transports — the
    trajectory-parity property ``tests/test_transport.py`` pins.

    ``compress="int8"`` round-trips every commit through the same
    quantize/dequantize + error-feedback math the wire path uses, so
    compressed runs also stay trajectory-identical across transports."""

    def __init__(self, ps: Any, templates: Sequence[np.ndarray],
                 compress: Optional[str] = None,
                 trace_context: Optional["dtrace.TraceContext"] = None,
                 sparse_leaves: Sequence[int] = (),
                 sparse_cache_rows: Optional[int] = None):
        if compress not in (None, "int8"):
            raise ValueError(f"unknown compress {compress!r}; use None or 'int8'")
        self.ps = ps
        self.templates = [np.asarray(t, dtype=np.float32) for t in templates]
        self.compress = compress
        # row-sparse tables (ISSUE 9): the inproc client mirrors the
        # socket client's cache-and-merge behavior over the hub's direct
        # sparse pair, so sparse runs stay trajectory-identical across
        # transports (no wire to save here — parity is the point).
        # Requires a co-located hub exposing pull_sparse_direct (both
        # unsharded hub implementations); the sharded facade has no
        # sparse direct pair — the trainer raises there
        self._sparse = tuple(sorted({int(i) for i in sparse_leaves}))
        self._sparse_set = frozenset(self._sparse)
        # hot-tier mode (ISSUE 15): the exact PSClient semantics minus
        # the wire — hits gather from the bounded LRU at pull time,
        # misses go through the direct pair, own commits merge in place
        # (one shared constructor with the socket client, so the two
        # transports' cache state can never drift)
        _init_hot_tier(self, sparse_cache_rows, compress)
        if self._sparse and not hasattr(ps, "pull_sparse_direct"):
            raise ValueError(
                f"sparse_leaves need a hub with a sparse direct pair "
                f"(pull_sparse_direct/commit_sparse_direct); "
                f"{type(ps).__name__} has none — use the socket transport "
                f"or an unsharded hub")
        self._residual = ([None if (self._cache_rows is not None
                                    and i in self._sparse_set)
                           else np.zeros(t.shape, np.float32)
                           for i, t in enumerate(self.templates)]
                          if compress else None)
        self._last_pull_clock = 0
        self._pulled: Optional[List[np.ndarray]] = None
        # what the health plane's TRANS column reports for this worker
        # (PSClient: "tcp"/"shm" depending on the attach negotiation)
        self.transport = "inproc"
        # inproc shares the hub's process AND clock: the context needs no
        # wire announce (the hub reads the worker thread's context via
        # dtrace.current()), and the clock offset is exactly zero — which
        # is ALSO the process default when nothing ever syncs, so nothing
        # is recorded globally (an unbeatable error=0 record would pin a
        # later socket job in this process to a stale zero offset)
        self.trace_context = trace_context
        self.clock_offset_ns = 0
        self.clock_error_ns: Optional[int] = 0 if trace_context is not None else None
        # no connection, so nothing to reconnect or fail over — kept so
        # the worker loop's health reports read one uniform client surface
        self.reconnects_used = 0
        self.failovers_used = 0

    # -- live health plane (ISSUE 8) -------------------------------------------
    def report_health(self, report: Dict[str, Any]) -> None:
        """Same contract as :meth:`PSClient.report_health`, minus the wire:
        the report folds straight into the co-located hub's collector
        (Python hubs and the sharded facade ingest with their shard
        labels; a native hub's reports land in the process-default
        collector directly)."""
        ingest = getattr(self.ps, "_ingest_health", None)
        if ingest is not None:
            ingest(report)
            return
        from distkeras_tpu.observability import health as _health

        _health.collector().ingest(report)
        _health.monitor().maybe_check()

    # -- pipelined API (eager) -------------------------------------------------
    def pull_nowait(self, sparse_rows: Optional[Sequence] = None) -> None:
        telemetry = obs.enabled()
        t0 = time.perf_counter() if telemetry else 0.0
        if sparse_rows is not None:
            if not self._sparse:
                raise ValueError("sparse_rows passed to a client with no "
                                 "sparse_leaves configured")
            if len(sparse_rows) != len(self._sparse):
                raise ValueError(f"got {len(sparse_rows)} id arrays, "
                                 f"client has {len(self._sparse)} sparse "
                                 f"tables")
            ids_list = [net.normalize_row_ids(ids,
                                              self.templates[i].shape[0])
                        for ids, i in zip(sparse_rows, self._sparse)]
            if self._cache_rows is not None:
                # hot-tier: gather hits now, direct-pull only the misses,
                # file them, hand back [k, dim] blocks (PSClient parity —
                # the same shared helpers, so the transports can't drift)
                blocks, miss_pos, miss = _hot_tier_gather(self, ids_list)
                misses0 = self.sparse_cache_misses
                values, clock = self.ps.pull_sparse_direct(miss)
                result = []
                si = 0
                for i, v in enumerate(values):
                    if i in self._sparse_set:
                        if miss_pos[si].size:
                            blocks[si][miss_pos[si]] = v
                        _hot_tier_file_misses(self, i, miss[si],
                                              np.asarray(v, np.float32))
                        result.append(blocks[si])
                        si += 1
                    else:
                        result.append(v)
                _count_cache_misses(self, misses0)
                self._last_pull_clock = clock
                self._pulled = result
                if telemetry:
                    obs.histogram("ps.pull_latency_ms").observe(
                        (time.perf_counter() - t0) * 1e3)
                return
            values, clock = self.ps.pull_sparse_direct(ids_list)
            result: List[np.ndarray] = []
            si = 0
            for i, v in enumerate(values):
                if i in self._sparse_set:
                    ids = ids_list[si]
                    if ids.size:
                        self._cache[i][ids] = v
                    result.append(self._cache[i])
                    si += 1
                else:
                    result.append(v)
            self._last_pull_clock = clock
            self._pulled = result
        else:
            weights, clock = self.ps.pull_direct()
            for i in self._sparse:
                if self._cache_rows is None:
                    self._cache[i][...] = weights[i]
                else:
                    _hot_tier_seed(self, i, weights[i])
            self._last_pull_clock = clock
            self._pulled = weights
        if telemetry:
            obs.histogram("ps.pull_latency_ms").observe(
                (time.perf_counter() - t0) * 1e3)

    def wait_weights(self) -> List[np.ndarray]:
        if self._pulled is None:
            raise RuntimeError("wait_weights() with no pull in flight")
        pulled, self._pulled = self._pulled, None
        return pulled

    def commit_nowait(self, delta: Sequence[np.ndarray],
                      sparse_rows: Optional[Sequence] = None) -> None:
        with obs.span("ps.commit", transport="inproc",
                      compress=self.compress or "none"):
            telemetry = obs.enabled()
            t0 = time.perf_counter() if telemetry else 0.0
            if sparse_rows is not None:
                if not self._sparse:
                    raise ValueError("sparse_rows passed to a client with "
                                     "no sparse_leaves configured")
                if len(sparse_rows) != len(self._sparse):
                    raise ValueError(f"got {len(sparse_rows)} id arrays, "
                                     f"client has {len(self._sparse)} "
                                     f"sparse tables")
                ids_list = [net.normalize_row_ids(
                    ids, self.templates[i].shape[0])
                    for ids, i in zip(sparse_rows, self._sparse)]
                # same row gather + quantize/residual math as the wire
                # path, then straight back through the dequantizer — what
                # the hub would have reconstructed from the U/X frame
                if self._cache_rows is None:
                    arrays = _sparse_commit_arrays(
                        delta, self.templates, self._sparse_set, ids_list,
                        self._residual, self.compress)
                else:
                    arrays = _hot_tier_commit_arrays(self, delta, ids_list)
                parts = _sparse_parts_from_arrays(
                    arrays, self.templates, self._sparse_set, self.compress)
                self.ps.commit_sparse_direct(parts, self._last_pull_clock)
            elif self.compress == "int8":
                # same quantize + residual advance as the wire path, then
                # straight back through the dequantizer — what the hub
                # would have reconstructed from the Q frame
                blobs = _quantize_commit(delta, self._residual)
                arrays = [net.dequantize_q_blob(memoryview(b), t.size)
                          .reshape(t.shape)
                          for b, t in zip(blobs, self.templates)]
                self.ps.commit_direct(arrays, self._last_pull_clock)
            else:
                arrays = [np.asarray(d, np.float32) for d in delta]
                self.ps.commit_direct(arrays, self._last_pull_clock)
            if telemetry:
                obs.histogram("ps.commit_latency_ms").observe(
                    (time.perf_counter() - t0) * 1e3)

    def drain(self) -> None:
        pass  # nothing rides in flight: commits apply synchronously

    # -- blocking API ----------------------------------------------------------
    def pull(self) -> List[np.ndarray]:
        with obs.span("ps.pull", transport="inproc"):
            self.pull_nowait()
            return self.wait_weights()

    def commit(self, delta: Sequence[np.ndarray],
               sparse_rows: Optional[Sequence] = None) -> None:
        self.commit_nowait(delta, sparse_rows=sparse_rows)

    def close(self) -> None:
        pass  # no connection; the hub's lifecycle belongs to the trainer

    def __enter__(self) -> "InprocPSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- sharded hub (ISSUE 6): stripe the center across N hub shards --------------
# One hub holding the whole center is a single-socket bandwidth and
# single-lock ceiling (the "weight-update state" that arXiv:2004.13336
# partitions across replicas).  The pieces below partition it across N
# independent hubs — each shard owns a subset of the center's leaves, runs
# its own lock, listener and commit clock — while the worker side stripes
# every pull/commit across all shards over per-shard connections reusing
# the existing pipelined/zero-copy machinery per connection.


class ShardPlan:
    """A deterministic leaf->shard assignment over a fixed template list.

    ``assignments[s]`` is the ASCENDING list of leaf indices shard ``s``
    owns — ascending so each shard's frame layout preserves template
    order (the 1-shard plan is exactly ``[[0..n-1]]``, whose frames are
    byte-identical to the unsharded codec's).  Built by
    :func:`shard_plan`; both ends of a sharded deployment (trainer
    workers, standalone ``distkeras-ps --shard-index`` hubs) derive the
    SAME plan from the same model, so no plan ever travels on the wire."""

    def __init__(self, num_shards: int, assignments: Sequence[Sequence[int]],
                 shard_bytes: Sequence[int],
                 sparse_ranges: Optional[Dict[int, Sequence[Tuple[int, int]]]]
                 = None):
        self.num_shards = int(num_shards)
        self.assignments: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(int(i) for i in idxs) for idxs in assignments)
        self.shard_bytes: Tuple[int, ...] = tuple(int(b) for b in shard_bytes)
        # row-sparse tables (ISSUE 9): leaf index -> one contiguous
        # (row_lo, row_hi) range per shard.  A sparse leaf appears in
        # EVERY shard's assignment list (each shard owns its row range of
        # it), so ``num_leaves`` counts DISTINCT leaves
        self.sparse_ranges: Dict[int, Tuple[Tuple[int, int], ...]] = {
            int(k): tuple((int(a), int(b)) for a, b in v)
            for k, v in (sparse_ranges or {}).items()}
        if self.sparse_ranges:
            self.num_leaves = len({i for idxs in self.assignments
                                   for i in idxs})
        else:
            self.num_leaves = sum(len(idxs) for idxs in self.assignments)

    def local_sparse(self, shard: int) -> Tuple[int, ...]:
        """Positions of the sparse leaves WITHIN shard ``shard``'s leaf
        list — the per-shard hub/client ``sparse_leaves`` argument."""
        return tuple(pos for pos, i in enumerate(self.assignments[shard])
                     if i in self.sparse_ranges)

    def split(self, arrays: Sequence[Any]) -> List[List[Any]]:
        """Stripe a full-order leaf list into per-shard sublists (reference
        slicing, no copies: sparse leaves contribute their shard's
        contiguous row-range VIEW)."""
        if len(arrays) != self.num_leaves:
            raise ValueError(f"got {len(arrays)} leaves, plan covers "
                             f"{self.num_leaves}")
        out: List[List[Any]] = []
        for s, idxs in enumerate(self.assignments):
            part: List[Any] = []
            for i in idxs:
                rng = self.sparse_ranges.get(i)
                if rng is None:
                    part.append(arrays[i])
                else:
                    lo, hi = rng[s]
                    part.append(arrays[i][lo:hi])
            out.append(part)
        return out

    def assemble(self, shard_lists: Sequence[Sequence[Any]],
                 sparse_fill: Optional[Dict[int, Any]] = None) -> List[Any]:
        """Inverse of :meth:`split`: reassemble per-shard sublists into the
        full-order leaf list — by reference for whole leaves, so the
        per-shard landing buffers ARE the result's storage.  A row-range-
        split sparse leaf is rebuilt by concatenating its per-shard
        slices (one copy) — unless ``sparse_fill`` supplies the full
        array for it (the striped client's full cache, whose row-range
        views the per-shard slices already wrote into)."""
        out: List[Any] = [None] * self.num_leaves
        slices: Dict[int, List[Any]] = {i: [] for i in self.sparse_ranges}
        for idxs, vals in zip(self.assignments, shard_lists):
            if len(idxs) != len(vals):
                raise ValueError(f"shard holds {len(idxs)} leaves, got "
                                 f"{len(vals)} values")
            for i, v in zip(idxs, vals):
                if i in slices:
                    slices[i].append(v)
                else:
                    out[i] = v
        for i, parts in slices.items():
            if sparse_fill is not None and i in sparse_fill:
                out[i] = sparse_fill[i]
            else:
                out[i] = np.concatenate([np.asarray(p) for p in parts],
                                        axis=0)
        return out

    def __repr__(self) -> str:
        return (f"ShardPlan(num_shards={self.num_shards}, "
                f"leaves={self.num_leaves}, "
                f"shard_bytes={list(self.shard_bytes)}"
                + (f", sparse={sorted(self.sparse_ranges)}"
                   if self.sparse_ranges else "") + ")")


def shard_plan(templates: Sequence[np.ndarray], num_shards: int,
               sparse_leaves: Sequence[int] = ()) -> ShardPlan:
    """Deterministic, size-balanced leaf->shard assignment.

    Leaves are taken in a CANONICAL order — bytes descending, then dtype,
    then shape — and greedily assigned to the currently-smallest shard
    (lowest shard id on ties): classic LPT scheduling, so the heaviest
    shard exceeds the lightest by at most one leaf's bytes.  Because the
    canonical order depends only on each leaf's (nbytes, dtype, shape)
    identity, the assignment is STABLE under leaf reordering: permuting
    the template list maps each leaf to the same shard (leaves with fully
    identical layout are interchangeable — their mutual order falls back
    to input position, which only ever swaps byte-identical slots).

    ``sparse_leaves`` (ISSUE 9) names row-sparse ``[rows, dim]`` embedding
    tables: each is split across ALL shards by contiguous row range
    (near-equal row counts, earlier shards take the remainder), so a
    table that dwarfs the dense model never lands whole on one shard and
    sparse row traffic stripes naturally.  Dense leaves are then
    LPT-balanced over shards pre-loaded with their sparse-range bytes.

    ``num_shards=1`` returns the identity plan (all leaves, template
    order); more shards than leaves (when nothing is sparse) is an error
    — an empty shard would serve zero-tensor frames to no purpose."""
    n = len(templates)
    num_shards = int(num_shards)
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    arrs = [np.asarray(t) for t in templates]
    sparse = tuple(sorted({int(i) for i in sparse_leaves}))
    for i in sparse:
        if not 0 <= i < n:
            raise ValueError(f"sparse leaf index {i} out of range for "
                             f"{n} templates")
        if arrs[i].ndim != 2:
            raise ValueError(f"sparse leaf {i} must be a [rows, dim] table, "
                             f"got shape {arrs[i].shape}")
    if num_shards == 1:
        return ShardPlan(1, [list(range(n))], [sum(a.nbytes for a in arrs)],
                         sparse_ranges={i: [(0, arrs[i].shape[0])]
                                        for i in sparse})
    if not sparse and num_shards > n:
        raise ValueError(f"num_shards={num_shards} exceeds the model's "
                         f"{n} leaves; every shard must own at least one")
    loads = [0] * num_shards
    sparse_ranges: Dict[int, List[Tuple[int, int]]] = {}
    for i in sparse:
        rows = arrs[i].shape[0]
        if rows < num_shards:
            raise ValueError(f"sparse leaf {i} has {rows} rows < "
                             f"num_shards={num_shards}; every shard must "
                             f"own at least one row")
        row_bytes = arrs[i].nbytes // rows
        base, rem = divmod(rows, num_shards)
        bounds: List[Tuple[int, int]] = []
        lo = 0
        for s in range(num_shards):
            hi = lo + base + (1 if s < rem else 0)
            bounds.append((lo, hi))
            loads[s] += (hi - lo) * row_bytes
            lo = hi
        sparse_ranges[i] = bounds
    dense = [i for i in range(n) if i not in set(sparse)]
    order = sorted(dense,
                   key=lambda i: (-arrs[i].nbytes, str(arrs[i].dtype),
                                  arrs[i].shape, i))
    heap = [(loads[s], s) for s in range(num_shards)]  # (bytes, shard id)
    heapq.heapify(heap)
    assignments: List[List[int]] = [list(sparse) for _ in range(num_shards)]
    for i in order:
        filled, s = heapq.heappop(heap)
        assignments[s].append(i)
        heapq.heappush(heap, (filled + arrs[i].nbytes, s))
    for idxs in assignments:
        idxs.sort()
    shard_bytes = [
        sum((sparse_ranges[i][s][1] - sparse_ranges[i][s][0])
            * (arrs[i].nbytes // arrs[i].shape[0]) if i in sparse_ranges
            else arrs[i].nbytes
            for i in idxs)
        for s, idxs in enumerate(assignments)]
    return ShardPlan(num_shards, assignments, shard_bytes,
                     sparse_ranges=sparse_ranges)


class SnapshotSetCoordinator:
    """Fleet-consistent snapshot sets for a sharded hub (ISSUE 7).

    PR 6 left each shard hub snapshotting independently — a multi-shard
    restore could therefore resurrect a TORN center (shard 0 at clock
    1000, shard 1 at clock 400: a parameter vector no training state ever
    was).  This coordinator replaces the per-shard snapshotters when all
    shards live in one process: each tick briefly FENCES commits across
    every shard (all shard center locks held at once — safe because no
    commit path ever holds two shard locks) and reads all N shard states
    inside that barrier, so the N per-shard snapshots share one causal
    cut.  Native shard hubs keep their own internal atomicity per shard;
    the cross-shard cut is then only as tight as the read loop, but the
    recorded clock vector still makes a torn restore detectable.

    Every shard's snapshot is stamped with the SAME step number, a shared
    ``snapshot_set`` id and the full per-shard ``set_clocks`` vector;
    :meth:`restore_latest_set` restores only a step that is present,
    readable, same-set and clock-consistent on EVERY shard — falling back
    to the newest COMPLETE set when the newest is torn, and raising when
    sets exist but none survives the checks.

    Retention is set-level: saves skip the per-directory keep-N prune and
    the coordinator deletes each doomed step from EVERY ``shard-NN/``
    directory before advancing to the next, oldest first — a crash
    between prunes can strand at most the oldest step half-deleted, never
    leave step K readable on shard 0 but pruned on shard 1.

    Telemetry: ``ps.snapshot_set_ms`` (whole save), ``ps.snapshot_fence_ms``
    (how long commits were fenced — the barrier's cost),
    ``ps_snapshot_sets_total``."""

    def __init__(self, hubs: Sequence[Any], directory: str,
                 interval: float = 30.0, keep: int = 3):
        from distkeras_tpu.checkpoint import Checkpointer

        self.hubs = list(hubs)
        self.directory = directory
        self.interval = float(interval)
        self.keep = int(keep)
        self.checkpointers = [
            Checkpointer(os.path.join(directory, f"shard-{sid:02d}"),
                         keep=keep)
            for sid in range(len(self.hubs))]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._save_lock = threading.Lock()
        self._next_step = 1 + max(
            (cp.latest_step() or 0) for cp in self.checkpointers)

    # -- the causal cut --------------------------------------------------------
    def _cut(self) -> List[Tuple[List[np.ndarray], Dict[str, Any]]]:
        locks = [getattr(hub, "_lock", None) for hub in self.hubs]
        if all(lk is not None for lk in locks):
            # Python hubs: a true barrier — every shard's center lock held
            # at once (commit handlers take exactly one shard lock, so no
            # ordering cycle exists), states read inside
            t0 = time.perf_counter()
            with contextlib.ExitStack() as stack:
                for lk in locks:
                    stack.enter_context(lk)
                states = [hub._snapshot_state_locked() for hub in self.hubs]
            if obs.enabled():
                obs.histogram("ps.snapshot_fence_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
            return states
        # native hubs lock in C++: per-shard snapshots are atomic, the
        # cross-shard cut is best-effort (documented); torn restores are
        # still detected via the recorded clock vector
        return [hub.snapshot_state() for hub in self.hubs]

    def save_set(self) -> None:
        """Write one coordinated snapshot set (all shards, one step, one
        causal cut), then advance set-level retention."""
        with self._save_lock, obs.span("ps.snapshot_set"):
            t0 = time.perf_counter()
            step = self._next_step
            set_id = f"set-{step:010d}-{random.getrandbits(32):08x}"
            states = self._cut()
            clocks = [int(state["clock"]) for _, state in states]
            for sid, (cp, (center, state)) in enumerate(
                    zip(self.checkpointers, states)):
                cp.save(step, {"center": center},
                        metadata={"kind": "ps-hub-snapshot", **state,
                                  "snapshot_set": set_id,
                                  "set_clocks": clocks,
                                  "shard_id": sid,
                                  "num_shards": len(self.hubs)},
                        apply_retention=False)
            self._next_step = step + 1
            self._prune(step)
            if obs.enabled():
                obs.histogram("ps.snapshot_set_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
                obs.counter("ps_snapshot_sets_total").inc()

    def _prune(self, latest_step: int) -> None:
        doomed = sorted({s for cp in self.checkpointers
                         for s in cp.all_steps()
                         if s <= latest_step - self.keep})
        for step in doomed:  # oldest first, each step from EVERY shard
            for cp in self.checkpointers:
                cp.delete_step(step)

    def restore_latest_set(self) -> bool:
        """Restore the newest COMPLETE, same-set, clock-consistent snapshot
        set into the hubs (each shard re-arms its clock fence via
        ``restore_state``).  Returns False on a genuinely empty directory
        (first boot); raises when sets exist but every candidate is torn
        or unreadable — silently serving fresh weights would discard the
        job."""
        per_shard = [set(cp.all_steps()) for cp in self.checkpointers]
        if not any(per_shard):
            return False
        candidates = sorted(set().union(*per_shard), reverse=True)
        for step in candidates:
            if not all(step in steps for steps in per_shard):
                missing = [sid for sid, steps in enumerate(per_shard)
                           if step not in steps]
                warnings.warn(f"snapshot step {step} missing on shard(s) "
                              f"{missing}: torn set, falling back older")
                continue
            try:
                metas = [cp.metadata(step=step)["metadata"]
                         for cp in self.checkpointers]
                set_ids = {m.get("snapshot_set") for m in metas}
                if set_ids == {None}:
                    # pre-coordination (PR 6) per-shard snapshots: every
                    # shard wrote independently, so there is no set id or
                    # clock vector to check.  Still restorable — each
                    # shard's fence keeps clocks safe — but the cut is
                    # uncoordinated: say so instead of stranding the job
                    warnings.warn(
                        f"snapshot step {step} predates coordinated sets "
                        f"(no snapshot_set id): restoring per-shard "
                        f"snapshots whose center may be torn by up to one "
                        f"snapshot interval across shards (the pre-HA "
                        f"contract)")
                elif len(set_ids) != 1 or None in set_ids:
                    raise ValueError(f"mismatched snapshot_set ids "
                                     f"{sorted(map(str, set_ids))}")
                else:
                    for sid, m in enumerate(metas):
                        vec = m.get("set_clocks")
                        if vec is None or \
                                int(m.get("clock", -1)) != int(vec[sid]):
                            raise ValueError(
                                f"shard {sid} clock {m.get('clock')} does "
                                f"not match the set's recorded vector {vec}")
                trees = [cp.restore({"center": hub.get_weights()}, step=step)
                         for cp, hub in zip(self.checkpointers, self.hubs)]
            except Exception as e:
                warnings.warn(f"skipping torn/unreadable snapshot set at "
                              f"step {step}: {type(e).__name__}: {e}")
                continue
            for hub, tree, m in zip(self.hubs, trees, metas):
                hub.restore_state(tree["center"], m)
            # under the save lock: same contract as HubSnapshotter —
            # a restore racing the periodic save loop must not lose a
            # step advance (guarded-by contract, ISSUE 14)
            with self._save_lock:
                self._next_step = max(self._next_step, step + 1)
            return True
        raise RuntimeError(
            f"restore requested: snapshot sets exist under {self.directory} "
            f"but none is complete and clock-consistent across all "
            f"{len(self.hubs)} shards (see warnings)")

    # -- lifecycle -------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.save_set()
            except Exception as e:  # a full disk must not kill the hubs
                warnings.warn(f"coordinated PS snapshot failed: "
                              f"{type(e).__name__}: {e}")

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self, final_snapshot: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if final_snapshot:
            try:
                self.save_set()
            except Exception as e:
                warnings.warn(f"final coordinated PS snapshot failed: "
                              f"{type(e).__name__}: {e}")


class _ShardWorkerPool:
    """One long-lived handler thread per shard hub (ISSUE 18): a striped
    direct-transport request dispatches one closure per shard and joins —
    so a 4-shard in-process hub applies the 4 stripes on 4 cores instead
    of walking them sequentially on the caller's thread.  Safe because
    the shards are DISJOINT state (each hub has its own center, lock and
    clock — the same isolation the per-connection socket handlers rely
    on), and numpy's apply kernels release the GIL.  Results are
    bit-identical to the sequential walk: each stripe runs the exact same
    per-hub call, just concurrently with its siblings.

    Each shard's queue is strictly FIFO and single-consumer, so two
    overlapped striped commits keep their per-shard apply order.  No new
    lock is introduced (the queues synchronize internally); the pool
    holds none while running a closure, so it cannot participate in any
    lock-order cycle."""

    def __init__(self, num_shards: int):
        self._queues = [queue.SimpleQueue() for _ in range(num_shards)]
        self._threads: List[threading.Thread] = []
        self.running = False

    def start(self) -> None:
        if self.running:
            return
        self.running = True
        for i, q in enumerate(self._queues):
            t = threading.Thread(target=self._loop, args=(q,),
                                 name=f"dk-shard-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    @staticmethod
    def _loop(q: "queue.SimpleQueue") -> None:
        while True:
            item = q.get()
            if item is None:
                return
            fn, box, done = item
            try:
                fn()
            except BaseException as e:
                box[0] = e
            done.set()

    def run(self, thunks: Sequence[Any]) -> None:
        """Run one thunk per shard, in parallel, and join.  The FIRST
        shard's error (in shard order) is re-raised after every shard
        finished — a failed stripe must not leave siblings mid-apply.
        Before start()/after stop() the thunks run sequentially inline,
        so lifecycle edges never drop work."""
        if not self.running:
            for fn in thunks:
                fn()
            return
        boxes = []
        events = []
        for q, fn in zip(self._queues, thunks):
            box: List[Optional[BaseException]] = [None]
            done = threading.Event()
            q.put((fn, box, done))
            boxes.append(box)
            events.append(done)
        for done in events:
            done.wait()
        for box in boxes:
            if box[0] is not None:
                raise box[0]

    def stop(self) -> None:
        if not self.running:
            return
        self.running = False
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []


class ShardedParameterServer:
    """Facade over N per-shard hubs: one :class:`SocketParameterServer`
    subclass (or :class:`~distkeras_tpu.runtime.native.
    NativeParameterServer`) per shard, each serving its slice of the
    center on its own port, lock and commit clock.

    ``hub_factory(shard_weights, shard_id)`` builds one UNSTARTED hub per
    shard — the trainer's algorithm-specific allocator with the shard's
    weight subset and identity (so per-shard spans/metrics carry the
    shard label).  The facade owns lifecycle (``start`` is all-or-nothing:
    a shard that fails to bind tears the others down), reassembles
    ``get_weights()`` into full template order, and exposes the direct
    (inproc) transport pair — ``pull_direct`` returns the full center plus
    a per-shard clock TUPLE, and ``commit_direct`` accepts that tuple (or
    a plain int, broadcast — the unsharded client's initial 0), so
    :class:`InprocPSClient` works against the facade unchanged.

    Snapshot/fence semantics: each shard hub snapshots and restores its
    OWN slice (give each a per-shard ``snapshot_dir`` subdirectory via the
    factory); on restore every shard arms its own clock fence, so a
    snapshot set whose shards are one interval apart is still safe —
    commits against any shard's dead-incarnation clock are clamped at
    that shard's restore point.  Elastic membership is per shard
    (connection-scoped); :meth:`live_workers` reports the MIN across
    shards — a worker counts as fleet-live only while all its shard
    connections do."""

    def __init__(self, weights: Sequence[np.ndarray], plan: ShardPlan,
                 hub_factory,
                 snapshot_dir: Optional[str] = None,
                 snapshot_interval: float = 30.0,
                 snapshot_keep: int = 3,
                 restore: bool = False,
                 parallel_direct: bool = True):
        if plan.num_leaves != len(weights):
            raise ValueError(f"plan covers {plan.num_leaves} leaves, model "
                             f"has {len(weights)}")
        self.plan = plan
        self.shards: List[Any] = []
        for sid, shard_weights in enumerate(plan.split(list(weights))):
            self.shards.append(hub_factory(shard_weights, sid))
        # per-shard handler pool (ISSUE 18): striped direct pulls/commits
        # fan out to one long-lived thread per shard, so an in-process
        # multi-shard hub uses one core PER SHARD instead of serializing
        # the stripes on the caller.  parallel_direct=False keeps the
        # sequential walk (bit-identical results either way — the shards
        # are disjoint)
        self._pool = (_ShardWorkerPool(plan.num_shards)
                      if parallel_direct and plan.num_shards > 1 else None)
        # coordinated snapshot sets (ISSUE 7): when the facade owns the
        # durability story, the N per-shard snapshots are taken inside one
        # commit barrier and restored only as a complete, clock-consistent
        # set.  (Per-shard snapshotters built by hub_factory remain the
        # multi-process --shard-index topology's independent fallback —
        # don't configure both.)
        self.coordinator: Optional[SnapshotSetCoordinator] = None
        self._restore = bool(restore)
        if restore and snapshot_dir is None:
            raise ValueError("restore=True requires snapshot_dir")
        if snapshot_dir is not None:
            self.coordinator = SnapshotSetCoordinator(
                self.shards, snapshot_dir, interval=snapshot_interval,
                keep=snapshot_keep)

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        if self.coordinator is not None and self._restore:
            # load BEFORE any shard binds: the first striped pull must
            # observe the restored (fenced) set everywhere
            if not self.coordinator.restore_latest_set():
                warnings.warn("restore requested but no snapshot set "
                              "exists yet; serving initial weights")
        started = []
        try:
            for hub in self.shards:
                hub.start()
                started.append(hub)
        except BaseException:
            for hub in started:
                try:
                    hub.stop()
                except Exception:
                    pass
            raise
        if self.coordinator is not None:
            self.coordinator.start()
        if self._pool is not None:
            self._pool.start()

    def stop(self) -> None:
        if self._pool is not None:
            self._pool.stop()
        if self.coordinator is not None:
            self.coordinator.stop(final_snapshot=True)
        for hub in self.shards:
            hub.stop()

    def kill(self) -> None:
        """Crash-like teardown of every shard (see
        ``SocketParameterServer.kill``): no final snapshot set — recovery
        must come from the last periodic one."""
        if self._pool is not None:
            self._pool.stop()
        if self.coordinator is not None:
            self.coordinator.stop(final_snapshot=False)
        for hub in self.shards:
            hub.kill()

    @property
    def ports(self) -> List[int]:
        return [hub.port for hub in self.shards]

    @property
    def port(self) -> int:
        """Shard 0's port — for code paths that log or display 'the' hub
        address; striped clients must use :attr:`ports`."""
        return self.shards[0].port

    @property
    def num_updates(self) -> int:
        """Logical commits applied: every striped commit increments every
        shard once, so the max across shards is the logical count (shards
        may momentarily differ while a stripe is in flight)."""
        return max(hub.num_updates for hub in self.shards)

    def live_workers(self) -> int:
        """Fleet-live workers: the MIN across shards — a worker whose
        connection to ANY shard has lapsed no longer counts (its commits
        are only partially landing)."""
        return min(hub.live_workers() for hub in self.shards)

    def get_weights(self) -> List[np.ndarray]:
        return self.plan.assemble([hub.get_weights() for hub in self.shards])

    # -- in-process transport (transport="inproc") -----------------------------
    def pull_direct(self) -> Tuple[List[np.ndarray], Tuple[int, ...]]:
        """(full center in template order, per-shard clock tuple).  The
        tuple rides back through the matching :meth:`commit_direct` —
        opaque to :class:`InprocPSClient`, exactly like the int clock of
        an unsharded hub."""
        n = self.plan.num_shards
        shard_weights: List[Any] = [None] * n
        clocks: List[Any] = [None] * n

        def make(i: int, hub: Any):
            def fn() -> None:
                shard_weights[i], clocks[i] = hub.pull_direct()
            return fn

        thunks = [make(i, hub) for i, hub in enumerate(self.shards)]
        if self._pool is not None:
            self._pool.run(thunks)
        else:
            for fn in thunks:
                fn()
        return self.plan.assemble(shard_weights), tuple(clocks)

    def commit_direct(self, delta: Sequence[np.ndarray],
                      last_pull_clock) -> None:
        parts = self.plan.split(list(delta))
        if isinstance(last_pull_clock, (tuple, list)):
            clocks = list(last_pull_clock)
            if len(clocks) != self.plan.num_shards:
                raise ValueError(f"clock tuple has {len(clocks)} entries, "
                                 f"plan has {self.plan.num_shards} shards")
        else:
            # a plain int (the inproc client's commit-before-first-pull
            # default of 0): broadcast to every shard's clock domain
            clocks = [int(last_pull_clock)] * self.plan.num_shards

        def make(hub: Any, part: Any, clock: Any):
            def fn() -> None:
                hub.commit_direct(part, clock)
            return fn

        thunks = [make(hub, part, clock)
                  for hub, part, clock in zip(self.shards, parts, clocks)]
        if self._pool is not None:
            self._pool.run(thunks)
        else:
            for fn in thunks:
                fn()

    # -- live health plane (ISSUE 8) -------------------------------------------
    def _ingest_health(self, report: Dict[str, Any]) -> None:
        """Fold one worker report through shard 0 — mirroring the striped
        wire path, where reports travel on the shard-0 connection only (one
        LOGICAL report per worker, the ``fleet_report`` counting rule)."""
        ingest = getattr(self.shards[0], "_ingest_health", None)
        if ingest is not None:
            ingest(report)
            return
        # native shard hubs have no Python-side ingest: fold straight into
        # the process-default collector (same process by construction)
        from distkeras_tpu.observability import health as _health

        _health.collector().ingest(report, shard=0)
        _health.monitor().maybe_check()


class ShardedPSClient:
    """Striped worker-side client: the :class:`PSClient` surface over N
    per-shard connections.

    A pull fans ``pull_nowait`` out to every shard; each shard's reply
    streams — via the per-connection zero-copy ``FlatFrameCodec`` path —
    directly into that shard's slice of the double-buffered landing zone
    (each per-shard client's landing buffers ARE the slice), and
    :meth:`wait_weights` reassembles the full-order list by reference.
    Commits stripe the delta the same way, with acks coalesced per shard
    connection by the underlying pipelined clients.  ``compress="int8"``
    quantizes per shard with per-leaf residuals — the same per-leaf
    error-feedback chain as unsharded, so trajectories match.

    Reconnect/heartbeat semantics apply PER SHARD CONNECTION (each shard
    client carries its own budget and backoff state); a stripe whose
    budget runs out mid fan-out surfaces as :class:`StripeLostError`
    naming the shard (index + host:port) and emits a ``ps.stripe_lost``
    span so ``fleet_report`` can attribute the loss.  After any
    unrecovered fault the striped client as a whole is desynchronized —
    single-use, like :class:`PSClient`.  ``addresses`` is one
    ``(host, port)`` per shard, aligned with ``plan.assignments``;
    ``failover`` (optional) is one standby ``(host, port)`` — or a
    sequence of them — per shard, same alignment."""

    def __init__(self, addresses: Sequence[Tuple[str, int]],
                 templates: Sequence[np.ndarray], plan: ShardPlan,
                 timeout: Optional[float] = 60.0,
                 compress: Optional[str] = None,
                 max_inflight: int = 2,
                 max_reconnects: int = 0,
                 reconnect_backoff: float = 0.1,
                 reconnect_backoff_max: float = 5.0,
                 heartbeat_interval: Optional[float] = None,
                 trace_context: Optional["dtrace.TraceContext"] = None,
                 failover: Optional[Sequence[Any]] = None,
                 sparse_leaves: Sequence[int] = (),
                 adaptive: bool = False,
                 sparse_cache_rows: Optional[int] = None,
                 shm: bool = False):
        if sparse_cache_rows is not None:
            # the striped client's whole sparse design is row-range VIEWS
            # of one full-size cache; a bounded hot tier would need
            # per-shard LRU partitioning of the row ranges — documented
            # unsupported combination (MIGRATION.md), loud at construction
            raise ValueError(
                "sparse_cache_rows is not supported on the sharded client: "
                "hot-tier caching needs num_shards=1 (PSClient/"
                "InprocPSClient) — drop sparse_cache_rows or the sharding")
        if len(addresses) != plan.num_shards:
            raise ValueError(f"got {len(addresses)} shard addresses, plan "
                             f"has {plan.num_shards} shards")
        if failover is not None and len(failover) != plan.num_shards:
            raise ValueError(f"got {len(failover)} failover entries, plan "
                             f"has {plan.num_shards} shards (pass None for "
                             f"shards without a standby)")
        self.templates = [np.asarray(t, dtype=np.float32) for t in templates]
        if plan.num_leaves != len(self.templates):
            raise ValueError(f"plan covers {plan.num_leaves} leaves, model "
                             f"has {len(self.templates)}")
        self.plan = plan
        self.compress = compress
        # row-sparse tables (ISSUE 9): the plan splits each table across
        # ALL shards by contiguous row range; this client keeps ONE
        # full-size cache per table and hands each per-shard client its
        # row-range VIEW of it as that shard's local cache — so per-shard
        # sparse merges write straight into the full table, and
        # wait_weights reassembles with zero row copies
        self._sparse = tuple(sorted({int(i) for i in sparse_leaves}))
        if self._sparse and set(self._sparse) != set(plan.sparse_ranges):
            raise ValueError(
                f"sparse_leaves {list(self._sparse)} do not match the "
                f"plan's sparse tables {sorted(plan.sparse_ranges)}; build "
                f"the plan with shard_plan(..., sparse_leaves=...)")
        self._cache: Dict[int, np.ndarray] = {
            i: np.array(self.templates[i], np.float32) for i in self._sparse}
        self.shards: List[PSClient] = []
        try:
            local_templates = plan.split(self.templates)
            for sid, ((host, port), idxs) in enumerate(
                    zip(addresses, plan.assignments)):
                client = PSClient(
                    host, port, local_templates[sid],
                    timeout=timeout, compress=compress,
                    max_inflight=max_inflight,
                    max_reconnects=max_reconnects,
                    reconnect_backoff=reconnect_backoff,
                    reconnect_backoff_max=reconnect_backoff_max,
                    heartbeat_interval=heartbeat_interval,
                    trace_context=trace_context, shard_id=sid,
                    sparse_leaves=plan.local_sparse(sid)
                    if self._sparse else (),
                    failover=_normalize_failover(
                        failover[sid] if failover is not None else None),
                    adaptive=adaptive, shm=shm)
                # rebind the shard client's caches to row-range views of
                # the full tables (contiguous slices, so fancy-indexed
                # merges land in the full cache directly)
                if self._sparse:
                    for pos, i in zip(plan.local_sparse(sid),
                                      (j for j in idxs
                                       if j in plan.sparse_ranges)):
                        lo, hi = plan.sparse_ranges[i][sid]
                        client._cache[pos] = self._cache[i][lo:hi]
                self.shards.append(client)
        except BaseException:
            self.close()
            raise

    @property
    def transport(self) -> str:
        """Aggregate of the stripes' negotiated transports: ``"shm"``
        when every shard connection attached a ring pair, ``"tcp"`` when
        none did, ``"mixed"`` otherwise (e.g. one shard's hub declined —
        legal, each stripe negotiates independently)."""
        kinds = {getattr(c, "transport", "tcp") for c in self.shards}
        if kinds == {"shm"}:
            return "shm"
        if kinds <= {"tcp"}:
            return "tcp"
        return "mixed"

    def _stripe(self, sid: int, op):
        """Run one shard client's op, converting an unrecovered connection
        fault into the typed :class:`StripeLostError` naming the stripe
        (and recording the ``ps.stripe_lost`` span).  Catches the full
        retryable set (``PSClient._RETRYABLE``): with ``max_reconnects=0``
        the ORIGINAL fault propagates — a wedged hub surfaces as
        ``socket.timeout`` (an OSError that is not a ConnectionError) and
        a desynced stream as ``ProtocolError`` (a ValueError), and both
        are stripe deaths every bit as much as a reset is."""
        try:
            return op()
        except StripeLostError:
            raise  # already typed (nested striped clients don't exist, but)
        except PSClient._RETRYABLE as e:
            client = self.shards[sid]
            if obs.enabled():
                t_ns = time.perf_counter_ns()
                wattrs = (client.trace_context.span_attrs()
                          if client.trace_context is not None else {})
                obs.counter("ps_stripe_losses_total", shard=str(sid)).inc()
                obs.TRACER.record_span(
                    "ps.stripe_lost", t_ns, t_ns, shard=sid,
                    address=f"{client.host}:{client.port}", **wattrs)
            raise StripeLostError(sid, client.host, client.port, e) from e

    def _route_rows(self, sparse_rows: Sequence) -> List[List[np.ndarray]]:
        """Route each table's touched-row ids to the shard owning their
        row range (ids are sorted, so each shard's segment is one
        ``searchsorted`` slice), rebased to the shard's local row 0."""
        if len(sparse_rows) != len(self._sparse):
            # checked BEFORE the zip below, which would truncate
            raise ValueError(f"got {len(sparse_rows)} id arrays, client has "
                             f"{len(self._sparse)} sparse tables")
        ids_list = [net.normalize_row_ids(ids, self.templates[i].shape[0])
                    for ids, i in zip(sparse_rows, self._sparse)]
        per_shard: List[List[np.ndarray]] = []
        for sid in range(self.plan.num_shards):
            local: List[np.ndarray] = []
            for pos, i in enumerate(self._sparse):
                lo, hi = self.plan.sparse_ranges[i][sid]
                ids = ids_list[pos]
                a, b = np.searchsorted(ids, (lo, hi))
                local.append(ids[a:b] - lo)
            per_shard.append(local)
        return per_shard

    # -- pipelined API ---------------------------------------------------------
    def pull_nowait(self, sparse_rows: Optional[Sequence] = None) -> None:
        if sparse_rows is None:
            for sid, client in enumerate(self.shards):
                self._stripe(sid, client.pull_nowait)
            return
        if not self._sparse:
            raise ValueError("sparse_rows passed to a client with no "
                             "sparse_leaves configured")
        for sid, (client, local) in enumerate(
                zip(self.shards, self._route_rows(sparse_rows))):
            self._stripe(sid, lambda c=client, l=local:
                         c.pull_nowait(sparse_rows=l))

    def wait_weights(self) -> List[np.ndarray]:
        """Full-order weight list; each dense leaf aliases its shard
        client's landing buffer (reused two pulls later — same ownership
        contract as :meth:`PSClient.wait_weights`); each sparse table is
        the client's full cache (stable storage, merged in place)."""
        parts = [self._stripe(sid, c.wait_weights)
                 for sid, c in enumerate(self.shards)]
        return self.plan.assemble(
            parts, sparse_fill=self._cache if self._sparse else None)

    def commit_nowait(self, delta: Sequence[np.ndarray],
                      sparse_rows: Optional[Sequence] = None) -> None:
        if sparse_rows is not None and not self._sparse:
            raise ValueError("sparse_rows passed to a client with no "
                             "sparse_leaves configured")
        routed = (self._route_rows(sparse_rows)
                  if sparse_rows is not None else None)
        for sid, (client, part) in enumerate(
                zip(self.shards, self.plan.split(list(delta)))):
            local = routed[sid] if routed is not None else None
            self._stripe(sid, lambda c=client, p=part, l=local:
                         c.commit_nowait(p, sparse_rows=l))

    def drain(self) -> None:
        for sid, client in enumerate(self.shards):
            self._stripe(sid, client.drain)

    # -- live health plane (ISSUE 8) -------------------------------------------
    @property
    def reconnects_used(self) -> int:
        return sum(c.reconnects_used for c in self.shards)

    @property
    def failovers_used(self) -> int:
        return sum(c.failovers_used for c in self.shards)

    @property
    def sparse_cache_hits(self) -> int:
        return sum(c.sparse_cache_hits for c in self.shards)

    @property
    def sparse_cache_misses(self) -> int:
        return sum(c.sparse_cache_misses for c in self.shards)

    def report_health(self, report: Dict[str, Any]) -> None:
        """Push one report over the SHARD-0 connection only: a striped
        worker is one logical worker, and the fleet view must count it
        once (the ``fleet_report`` shard-0 convention; shard 0 exists in
        every plan)."""
        self._stripe(0, lambda: self.shards[0].report_health(report))

    # -- blocking API ----------------------------------------------------------
    def pull(self) -> List[np.ndarray]:
        with obs.span("ps.pull", sharded=self.plan.num_shards):
            self.pull_nowait()
            return self.wait_weights()

    def commit(self, delta: Sequence[np.ndarray],
               sparse_rows: Optional[Sequence] = None) -> None:
        self.commit_nowait(delta, sparse_rows=sparse_rows)
        self.drain()

    def close(self) -> None:
        for client in self.shards:
            try:
                client.close()
            except OSError:
                pass

    def __enter__(self) -> "ShardedPSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
