"""Parameter-server hub + worker client — reference parity for
``distkeras/parameter_servers.py`` (SURVEY.md §2.11, §3.4).

The reference ran a driver-side thread that bound a TCP socket, accepted
one connection per Spark worker, and dispatched pickled ``'pull'`` /
``'commit'`` messages under a single mutex.  This re-design keeps that
architecture — it is the *genuinely asynchronous* execution option for the
DOWNPOUR/EASGD family (SURVEY §7 "hard parts", option b), used when worker
processes drive their own chips over DCN — with three changes:

- the wire protocol is raw tensor frames, not pickle
  (:mod:`distkeras_tpu.runtime.networking`);
- the center is a flat ``float32`` weight list (the pytree structure stays
  with the trainer), so commits are pure vectorized numpy adds;
- the same protocol is implemented by a C++ hub
  (:mod:`distkeras_tpu.runtime.native`) that applies commits without the
  GIL; this Python hub is the portable fallback and the executable spec.

Server classes mirror the reference's:
``SocketParameterServer`` (base, pull/commit loop),
``DeltaParameterServer`` (unscaled adds — DOWNPOUR, elastic),
``ADAGParameterServer`` (delta / num_workers),
``DynSGDParameterServer`` (delta / (staleness + 1) with a global clock).
"""

from __future__ import annotations

import socket
import threading
from typing import List, Optional, Sequence

import time

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.runtime import networking as net


class SocketParameterServer:
    """Hub-and-spoke PS: one handler thread per worker connection, one lock
    around the center variable — the reference's concurrency model
    (SURVEY §3.4), minus pickle and minus the GIL-heavy payload decode.

    Telemetry (``distkeras_tpu.observability``, off by default): pull/
    commit counts and payload bytes (``ps_pulls_total``,
    ``ps_commits_total``, ``ps_pull_bytes_total``,
    ``ps_commit_bytes_total``), per-RPC handler latency
    (``ps_rpc_seconds{rpc=...}``) and the per-connection staleness gauge
    ``ps_staleness{conn=N}`` (N is the hub's accept ordinal modulo 256 —
    workers carry no identity on the wire, and the wrap bounds label
    cardinality under elastic connection churn) — the commit clock the paper lineage's
    staleness analysis (arXiv:1611.04581) is about, now a live signal
    instead of a number internal to DynSGD's scaling rule.  Instruments
    are looked up per RPC while telemetry is on (a dict get next to a
    socket exchange) so a mid-run ``obs.reset()`` cannot orphan them, and
    nothing is registered at all while telemetry is off."""

    def __init__(self, weights: Sequence[np.ndarray], host: str = "0.0.0.0", port: int = 0):
        self.center: List[np.ndarray] = [np.array(w, dtype=np.float32) for w in weights]
        self.host = host
        self.port = int(port)
        self.num_updates = 0
        self._clock = 0  # total commits applied (DynSGD's global clock)
        self._lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._handlers: List[threading.Thread] = []
        self._running = False
        self._center_bytes = sum(w.nbytes for w in self.center)
        self._conn_seq = 0  # connection ordinal -> staleness gauge label

    # -- lifecycle (reference: ParameterServer.start/stop) ---------------------
    def start(self) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(128)
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._running = False
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        for t in self._handlers:
            t.join(timeout=5)

    def get_weights(self) -> List[np.ndarray]:
        with self._lock:
            return [w.copy() for w in self.center]

    # -- serving loop (reference: SocketParameterServer.run) -------------------
    def _accept_loop(self) -> None:
        assert self._listener is not None
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                break  # listener closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # ordinal wraps at a fixed slot count so the staleness gauge's
            # label cardinality stays bounded even under elastic-run
            # connection churn (ordinals already restart at 0 per hub,
            # so slots only conflate workers past 256 live connections)
            conn_idx = self._conn_seq % 256
            self._conn_seq += 1
            t = threading.Thread(target=self._handle_connection,
                                 args=(conn, conn_idx), daemon=True)
            t.start()
            self._handlers.append(t)

    def _decode_delta(self, blobs) -> List[np.ndarray]:
        if len(blobs) != len(self.center):
            raise ValueError(f"commit has {len(blobs)} tensors, center has {len(self.center)}")
        out = []
        for blob, c in zip(blobs, self.center):
            arr = np.frombuffer(np.asarray(blob).tobytes(), dtype=c.dtype)
            if arr.size != c.size:
                raise ValueError(f"commit tensor size {arr.size} != center size {c.size}")
            out.append(arr.reshape(c.shape))
        return out

    def _decode_qdelta(self, blobs) -> List[np.ndarray]:
        """int8 commit (action Q): per-tensor f32 scale + int8 values."""
        if len(blobs) != len(self.center):
            raise ValueError(f"commit has {len(blobs)} tensors, center has {len(self.center)}")
        return [net.dequantize_q_blob(np.asarray(blob).tobytes(), c.size).reshape(c.shape)
                for blob, c in zip(blobs, self.center)]

    def _handle_connection(self, conn: socket.socket, conn_idx: int = 0) -> None:
        last_pull_clock = 0
        try:
            while True:
                # raw receive: pull/bye carry zero tensors, commit carries
                # len(center) — decode against the center only on commit
                action, blobs = net.recv_tensors(conn)
                telemetry = obs.enabled()
                t0 = time.perf_counter() if telemetry else 0.0
                if action == net.ACTION_PULL:
                    with self._lock:
                        snapshot = [w.copy() for w in self.center]
                        last_pull_clock = self._clock
                    net.send_tensors(conn, net.ACTION_WEIGHTS, snapshot)
                    if telemetry:
                        obs.counter("ps_pulls_total").inc()
                        obs.counter("ps_pull_bytes_total").inc(self._center_bytes)
                        obs.histogram("ps_rpc_seconds", rpc="pull").observe(
                            time.perf_counter() - t0)
                elif action in (net.ACTION_COMMIT, net.ACTION_QCOMMIT):
                    delta = (self._decode_delta(blobs)
                             if action == net.ACTION_COMMIT
                             else self._decode_qdelta(blobs))
                    with self._lock:
                        staleness = self._clock - last_pull_clock
                        self.apply_commit(delta, staleness)
                        self.num_updates += 1
                        self._clock += 1
                    net.send_tensors(conn, net.ACTION_ACK, [])
                    if telemetry:
                        obs.counter("ps_commits_total").inc()
                        obs.counter("ps_commit_bytes_total").inc(
                            sum(np.asarray(b).nbytes for b in blobs))
                        obs.histogram("ps_rpc_seconds", rpc="commit").observe(
                            time.perf_counter() - t0)
                        # per-connection staleness: commits the hub applied
                        # between this worker's last pull and its commit —
                        # the quantity DynSGD scales by, now visible for
                        # EVERY hub flavor.  Created lazily so a hub with
                        # telemetry off never registers per-connection state
                        obs.gauge("ps_staleness",
                                  conn=str(conn_idx)).set(staleness)
                elif action == net.ACTION_BYE:
                    break
                else:
                    raise ValueError(f"unknown action {action!r}")
        except (ConnectionError, ValueError, OSError):
            pass  # worker vanished mid-exchange; reference behavior: drop it
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- commit rules ----------------------------------------------------------
    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:  # pragma: no cover
        raise NotImplementedError


class DeltaParameterServer(SocketParameterServer):
    """Unscaled delta adds: ``center += delta``.  Reference
    ``DeltaParameterServer`` — serves DOWNPOUR (accumulated gradients) and
    the elastic family (workers pre-scale by alpha)."""

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        for c, d in zip(self.center, delta):
            c += d


class ADAGParameterServer(SocketParameterServer):
    """ADAG normalization: ``center += delta / num_workers`` (reference
    ``ADAGParameterServer.handle_commit``, SURVEY §2.6)."""

    def __init__(self, weights: Sequence[np.ndarray], num_workers: int, **kwargs):
        super().__init__(weights, **kwargs)
        self.num_workers = int(num_workers)

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        inv = 1.0 / self.num_workers
        for c, d in zip(self.center, delta):
            c += d * inv


class DynSGDParameterServer(SocketParameterServer):
    """Staleness-aware scaling: ``center += delta / (staleness + 1)`` where
    staleness = commits applied since this worker's last pull (reference
    ``DynSGDParameterServer.handle_commit``, SURVEY §2.7)."""

    def apply_commit(self, delta: List[np.ndarray], staleness: int) -> None:
        inv = 1.0 / (staleness + 1.0)
        for c, d in zip(self.center, delta):
            c += d * inv


class PSClient:
    """Worker-side connection: ``pull()`` / ``commit(delta)`` (reference:
    ``NetworkWorker.pull/commit``, SURVEY §2.10).

    ``compress="int8"`` sends commits as action-``Q`` frames — symmetric
    per-tensor int8 with a float32 scale (4x fewer wire bytes) — and
    keeps the quantization residual client-side, folding it into the
    next commit (error feedback: the sum of dequantized commits tracks
    the sum of true deltas, so compression does not bias the center).
    Pulls always stay full precision: weight error hits the model
    directly, while delta rounding error is recycled."""

    def __init__(self, host: str, port: int, templates: Sequence[np.ndarray],
                 timeout: Optional[float] = 60.0,
                 compress: Optional[str] = None):
        if compress not in (None, "int8"):
            raise ValueError(f"unknown compress {compress!r}; use None or 'int8'")
        self.templates = [np.asarray(t, dtype=np.float32) for t in templates]
        self.compress = compress
        self._residual = ([np.zeros(t.shape, np.float32) for t in self.templates]
                          if compress else None)
        self.sock = net.connect(host, port, timeout=timeout)

    def pull(self) -> List[np.ndarray]:
        with obs.span("ps.pull"):
            net.send_tensors(self.sock, net.ACTION_PULL, [])
            action, tensors = net.recv_tensors(self.sock, templates=self.templates)
        if action != net.ACTION_WEIGHTS:
            raise ConnectionError(f"expected weights reply, got {action!r}")
        return tensors

    def commit(self, delta: Sequence[np.ndarray]) -> None:
        with obs.span("ps.commit", compress=self.compress or "none"):
            self._commit(delta)

    def _commit(self, delta: Sequence[np.ndarray]) -> None:
        new_residuals = None
        if self.compress == "int8":
            action, arrays, new_residuals = net.ACTION_QCOMMIT, [], []
            for i, d in enumerate(delta):
                carried = np.asarray(d, np.float32) + self._residual[i]
                blob, res = net.quantize_q_blob(carried)
                arrays.append(np.frombuffer(blob, dtype=np.uint8))
                new_residuals.append(res)
        else:
            action = net.ACTION_COMMIT
            arrays = [np.asarray(d, np.float32) for d in delta]
        net.send_tensors(self.sock, action, arrays)
        reply, _ = net.recv_tensors(self.sock, templates=[])
        if reply != net.ACTION_ACK:
            raise ConnectionError(f"expected ack, got {reply!r}")
        if new_residuals is not None:
            # only a DELIVERED commit sheds its carried delta: updating the
            # residual before the ack would lose a whole window's worth of
            # update on a failed send, breaking the error-feedback
            # invariant for callers that reconnect and retry
            self._residual = new_residuals

    def close(self) -> None:
        try:
            net.send_tensors(self.sock, net.ACTION_BYE, [])
        except OSError:
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self) -> "PSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
