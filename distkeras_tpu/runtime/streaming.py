"""Streaming inference service — reference parity for the Kafka pipeline.

The reference's streaming story (SURVEY §2.21 [M]) was a notebook wiring
Kafka + Spark Streaming to a Keras model: events arrive continuously, get
micro-batched, scored, and emitted.  TPU-native redesign: a socket service
holding ONE jit-compiled apply function with a single static batch shape —
producers stream feature frames over the framed no-pickle transport and
receive prediction frames back.  Padding to the static shape means every
frame reuses the same XLA program: no recompiles, no Python per-row work,
and the TPU stays hot across clients (connections share the program; JAX
dispatch is thread-safe).

Wire protocol (after :mod:`distkeras_tpu.runtime.networking`):

    server hello: JSON {"streaming_predictor": 1, "row_shape": [...],
                        "dtype": "...", "max_batch": N, "output_shape": [...]}
    client frame: tensors(action 'C', [features [b, *row_shape]]), b <= N
    server frame: tensors(action 'W', [predictions [b, *output_shape]])
    action 'B' closes the connection.

Use :class:`StreamingClient` (or ``stream_predict`` for an iterator-in,
iterator-out pipeline — the shape of the reference's Kafka consumer loop).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from distkeras_tpu.runtime import networking as net


class StreamingInferenceServer:
    """Serve a model's predictions over TCP with one static-shape program.

    ``max_batch`` is the compiled batch size: larger client frames are
    rejected, smaller ones are padded (rows repeated) and truncated on
    reply.  ``port=0`` binds an ephemeral port (read ``.port``).
    """

    def __init__(self, model: Any, host: str = "127.0.0.1", port: int = 0,
                 max_batch: int = 256):
        import jax
        import jax.numpy as jnp

        self.model = model
        self._host, self._port = host, int(port)
        self.max_batch = int(max_batch)
        self.row_shape = tuple(model.spec.input_shape)
        self.row_dtype = np.dtype(model.spec.input_dtype)
        self._apply = jax.jit(model.spec.apply_fn())
        # compile once up front and learn the output shape from it
        dummy = jnp.zeros((self.max_batch,) + self.row_shape, self.row_dtype)
        out = np.asarray(self._apply(model.params, dummy))
        self.output_shape = tuple(out.shape[1:])
        self.output_dtype = np.dtype(out.dtype)
        self._jnp = jnp
        self._sock: Optional[socket.socket] = None
        self._running = False
        self._threads: List[threading.Thread] = []

    @property
    def port(self) -> int:
        if self._sock is None:
            raise RuntimeError("server not started")
        return self._sock.getsockname()[1]

    def start(self) -> "StreamingInferenceServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self._host, self._port))
        self._sock.listen(64)
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._handle, args=(conn,), daemon=True)
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        jnp = self._jnp
        row_elems = int(np.prod(self.row_shape)) if self.row_shape else 1
        try:
            net.send_json(conn, {
                "streaming_predictor": 1,
                "row_shape": list(self.row_shape),
                "dtype": self.row_dtype.str,
                "max_batch": self.max_batch,
                "output_shape": list(self.output_shape),
                "output_dtype": self.output_dtype.str,
            })
            while self._running:
                try:
                    action, blobs = net.recv_tensors(
                        conn, limit=16 + self.max_batch * row_elems * self.row_dtype.itemsize * 2)
                except (ConnectionError, OSError, ValueError):
                    return
                if action == net.ACTION_BYE:
                    return
                if action != net.ACTION_COMMIT or len(blobs) != 1:
                    net.send_json(conn, {"ok": False, "error": "expected one feature frame"})
                    return
                flat = np.frombuffer(blobs[0], dtype=self.row_dtype)
                if flat.size % row_elems:
                    net.send_json(conn, {"ok": False,
                                         "error": f"frame size {flat.size} not a multiple "
                                                  f"of row size {row_elems}"})
                    return
                batch = flat.reshape((-1,) + self.row_shape)
                b = len(batch)
                if b == 0 or b > self.max_batch:
                    net.send_json(conn, {"ok": False,
                                         "error": f"batch {b} outside 1..{self.max_batch}"})
                    return
                if b < self.max_batch:
                    batch = np.concatenate(
                        [batch, np.repeat(batch[-1:], self.max_batch - b, axis=0)])
                preds = np.asarray(self._apply(self.model.params, jnp.asarray(batch)))[:b]
                net.send_tensors(conn, net.ACTION_WEIGHTS, [np.ascontiguousarray(preds)])
        finally:
            try:
                conn.close()
            except OSError:
                pass


class StreamingClient:
    """Producer-side handle: ``predict(batch) -> predictions``, reusable
    across many micro-batches on one connection."""

    def __init__(self, host: str, port: int, timeout: Optional[float] = 60.0):
        self.sock = net.connect(host, port, timeout=timeout)
        hello = net.recv_json(self.sock)
        if hello.get("streaming_predictor") != 1:
            self.close()
            raise ConnectionError(f"not a streaming predictor endpoint: {hello}")
        self.row_shape = tuple(hello["row_shape"])
        self.dtype = np.dtype(hello["dtype"])
        self.max_batch = int(hello["max_batch"])
        self.output_shape = tuple(hello["output_shape"])
        self.output_dtype = np.dtype(hello.get("output_dtype", "<f4"))

    def predict(self, batch: np.ndarray) -> np.ndarray:
        batch = np.ascontiguousarray(np.asarray(batch, dtype=self.dtype))
        if batch.shape[1:] != self.row_shape:
            raise ValueError(f"rows of shape {batch.shape[1:]}, server expects {self.row_shape}")
        if not 1 <= len(batch) <= self.max_batch:
            raise ValueError(f"batch {len(batch)} outside 1..{self.max_batch}")
        net.send_tensors(self.sock, net.ACTION_COMMIT, [batch])
        payload = net.recv_frame(self.sock)
        if payload[:1] == net.ACTION_WEIGHTS:
            _, blobs = net.decode_tensors(payload)
            flat = np.frombuffer(blobs[0], dtype=self.output_dtype)
            return flat.reshape((len(batch),) + self.output_shape)
        import json

        err = json.loads(payload.decode("utf-8"))
        raise RuntimeError(err.get("error", "streaming predict failed"))

    def close(self) -> None:
        try:
            net.send_tensors(self.sock, net.ACTION_BYE, [])
        except OSError:
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self) -> "StreamingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def stream_predict(host: str, port: int, events: Iterable[np.ndarray],
                   micro_batch: int = 64) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Micro-batch an event stream through a predictor service.

    The reference's Kafka-consumer loop shape: ``events`` yields single
    feature rows; rows are grouped into ``micro_batch``-sized frames and
    ``(rows, predictions)`` pairs are yielded as they return.  The final
    partial batch is flushed at stream end.
    """
    with StreamingClient(host, port) as client:
        buf: List[np.ndarray] = []
        for row in events:
            buf.append(np.asarray(row))
            if len(buf) >= micro_batch:
                rows = np.stack(buf)
                yield rows, client.predict(rows)
                buf = []
        if buf:
            rows = np.stack(buf)
            yield rows, client.predict(rows)
