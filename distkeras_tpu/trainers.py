"""Trainer API (reference parity: ``distkeras/trainers.py``).

The reference exposed ``Trainer.train(dataframe) -> keras model`` with
concrete classes ``SingleTrainer``, ``ADAG``, ``DOWNPOUR``, ``AEASGD``,
``EAMSGD``, ``DynSGD``, ``AveragingTrainer``, ``EnsembleTrainer``
(SURVEY.md §2.1–2.9).  Constructor surfaces are kept kwargs-compatible
(``num_workers``, ``batch_size``, ``communication_window``, ``rho``,
``learning_rate``, ``momentum``, ``num_epoch``, ``features_col``,
``label_col``) so reference users can switch with minimal edits; Spark
DataFrames become :class:`distkeras_tpu.data.Dataset`, "workers" become
mesh replicas, and the parameter server becomes the window engine's
collectives (see ``parallel/engine.py``).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.checkpoint import Checkpointer
from distkeras_tpu.data.dataset import Dataset, prefetch_to_device
from distkeras_tpu.models.base import Model, ModelSpec
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.ops.optimizers import get_optimizer
from distkeras_tpu.parallel.algorithms import (
    AdagAlgorithm,
    Algorithm,
    DownpourAlgorithm,
    DynSGDAlgorithm,
    ElasticAlgorithm,
    NoCommitAlgorithm,
)
from distkeras_tpu.parallel.engine import WindowEngine, scan_epoch_fn
from distkeras_tpu.parallel.mesh import create_mesh


class Trainer:
    """Base trainer: holds the model, loss, worker optimizer, data columns,
    and wall-clock accounting (reference ``record_training_start/end``)."""

    def __init__(self, model: Union[Model, ModelSpec], loss: Union[str, Callable] = "categorical_crossentropy",
                 worker_optimizer: str = "sgd", learning_rate: float = 0.01,
                 momentum: Optional[float] = None,
                 features_col: str = "features", label_col: str = "label",
                 batch_size: int = 32, num_epoch: int = 1, seed: int = 0,
                 chunk_windows: Optional[Union[int, str]] = None,
                 profile_dir: Optional[str] = None):
        if isinstance(model, ModelSpec):
            model = Model.init(model, seed=seed)
        model.spec.reject_silent_aux(type(self).__name__)
        self.model = model
        self.loss = get_loss(loss)
        self.optimizer = get_optimizer(worker_optimizer, learning_rate=learning_rate, momentum=momentum)
        self.learning_rate = learning_rate
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        self.seed = seed
        # bound host->device feeding to this many windows per transfer
        # (None = whole epoch in one transfer, the small-data fast path;
        # "auto" = size chunks near DEFAULT_CHUNK_BUDGET_BYTES — the feed
        # bench's promoted chunk_mb — resolved per dataset at train time)
        if chunk_windows is None or chunk_windows == "auto":
            self.chunk_windows = chunk_windows
        else:
            self.chunk_windows = int(chunk_windows)
        # observability (SURVEY §5 rows 1/5): per-epoch throughput records
        # in self.metrics; profile_dir writes a jax.profiler trace of train()
        self.profile_dir = profile_dir
        self.metrics: List[dict] = []
        self.history: List[float] = []  # per-window (or per-batch) mean loss
        self._t_start: Optional[float] = None
        self._t_end: Optional[float] = None

    def _resolve_chunk_windows(self, dataset, batch_size: int, window: int):
        """``chunk_windows`` for this dataset: passthrough unless "auto",
        which sizes chunks near the feed budget (one row's feature bytes x
        batch x window per window — ``chunk_windows_for_budget``)."""
        if self.chunk_windows != "auto":
            return self.chunk_windows
        from distkeras_tpu.data.dataset import chunk_windows_for_budget

        row_bytes = int(np.asarray(dataset[self.features_col][0]).nbytes)
        return chunk_windows_for_budget(row_bytes, batch_size, window)

    # reference API: record_training_start/record_training_end/get_training_time
    def record_training_start(self) -> None:
        self._t_start = time.time()
        self._t_end = None

    def record_training_end(self) -> None:
        self._t_end = time.time()

    def get_training_time(self) -> float:
        if self._t_start is None:
            return 0.0
        end = self._t_end if self._t_end is not None else time.time()
        return end - self._t_start

    def train(self, dataset: Dataset, shuffle: bool = True,
              checkpointer: Optional[Checkpointer] = None,
              validation_data: Optional[Dataset] = None,
              early_stopping=None) -> Model:  # pragma: no cover - interface
        raise NotImplementedError

    def _profile_ctx(self):
        """``jax.profiler.trace`` over train() when ``profile_dir`` is set
        (view with TensorBoard / xprof); no-op otherwise."""
        if self.profile_dir is None:
            import contextlib

            return contextlib.nullcontext()
        return jax.profiler.trace(self.profile_dir)

    _VAL_BATCH = 1024  # validation chunk rows: bounds device residency for
                       # big validation sets (two static shapes per run: the
                       # full chunk and one remainder)

    def _validate(self, params, validation_data: Optional[Dataset]) -> Optional[dict]:
        """Per-epoch validation: loss (always) + accuracy (classification
        labels only).  Evaluated in bounded chunks; the jitted evaluator is
        cached per classification-mode, so reusing one trainer across
        classification and regression validation sets stays correct."""
        if validation_data is None:
            return None
        y_host = validation_data[self.label_col]
        # accuracy only for classification labels: integer class indices, or
        # float rows that are actually one-hot (a float vector target that
        # isn't one-hot is regression — argmax "accuracy" would be noise).
        # A trailing size-1 axis is an index column, not a one-class one-hot.
        y_probe = y_host[..., 0] if (y_host.ndim > 1 and y_host.shape[-1] == 1) else y_host
        if np.issubdtype(y_probe.dtype, np.integer):
            classify = True
        elif y_probe.ndim > 1:
            sample = np.asarray(y_probe[:256])
            classify = bool(np.all((sample == 0) | (sample == 1))
                            and np.allclose(sample.sum(axis=-1), 1))
        else:
            classify = False
        fns = getattr(self, "_val_fns", None)
        if fns is None:
            fns = {}
            self._val_fns = fns
        if classify not in fns:
            apply = self.model.spec.apply_fn()
            loss = self.loss
            want_acc = classify

            @jax.jit
            def val(params, x, y):
                from distkeras_tpu.evaluators import _to_index

                logits = apply(params, x)
                out = {"loss_sum": loss(logits, y) * x.shape[0]}
                if want_acc:
                    if logits.ndim > 1 and logits.shape[-1] == 1:
                        pred = (logits[..., 0] > 0).astype(jnp.int32)  # single-logit binary
                    elif logits.ndim == 1:
                        pred = (logits > 0).astype(jnp.int32)
                    else:
                        pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    idx = _to_index(y)
                    # shapes are static at trace time: token-level labels
                    # ((B, T) ints vs (B, T) preds) count every element;
                    # incompatible label/logit shapes drop accuracy rather
                    # than report a broadcasting accident
                    if pred.shape == idx.shape:
                        out["correct"] = jnp.sum((pred == idx).astype(jnp.float32))
                        out["acc_denom"] = jnp.asarray(float(pred.size), jnp.float32)
                return out

            fns[classify] = val
        fn = fns[classify]
        x_host = validation_data[self.features_col]
        n = len(x_host)
        if n == 0:
            raise ValueError("validation_data is empty — 0-row validation "
                             "would silently report val_loss 0.0")
        loss_sum = correct = denom = 0.0
        have_acc = classify
        for i in range(0, n, self._VAL_BATCH):
            out = fn(params, jnp.asarray(x_host[i:i + self._VAL_BATCH]),
                     jnp.asarray(y_host[i:i + self._VAL_BATCH]))
            loss_sum += float(out["loss_sum"])
            if "correct" in out:
                correct += float(out["correct"])
                denom += float(out["acc_denom"])
            else:
                have_acc = False
        result = {"val_loss": loss_sum / n}
        if have_acc and denom > 0:
            result["val_accuracy"] = correct / denom
        return result

    class _EarlyStopping:
        """Keras-``EarlyStopping`` semantics over the per-epoch validation
        metrics: stop once ``patience`` consecutive epochs pass without a
        ``min_delta`` improvement on ``monitor`` (val_loss: lower is
        better; val_accuracy: higher; ``patience=0`` behaves like 1, as in
        Keras).  ``restore_best=True`` (default) hands the best-epoch
        weights back instead of the last ones."""

        def __init__(self, patience: int = 3, min_delta: float = 0.0,
                     monitor: str = "val_loss", restore_best: bool = True):
            if monitor not in ("val_loss", "val_accuracy"):
                raise ValueError(f"monitor must be val_loss or val_accuracy, "
                                 f"got {monitor!r}")
            self.patience = int(patience)
            self.min_delta = float(min_delta)
            self.monitor = monitor
            self.restore_best = bool(restore_best)
            self.best: Optional[float] = None
            self.best_params = None
            self.stale = 0
            self.stopped_epoch: Optional[int] = None

        def update(self, epoch: int, metrics: dict, params) -> bool:
            """Record this epoch; True = stop now."""
            if self.monitor not in metrics:
                raise ValueError(
                    f"early stopping monitors {self.monitor!r} but the epoch "
                    f"metrics lack it (keys: {sorted(metrics)}); pass "
                    "validation_data=")
            value = metrics[self.monitor]
            better = (self.best is None
                      or (value < self.best - self.min_delta
                          if self.monitor == "val_loss"
                          else value > self.best + self.min_delta))
            if better:
                self.best = value
                self.stale = 0
                if self.restore_best:
                    self.best_params = jax.tree.map(np.asarray, params)
            else:
                self.stale += 1
                if self.stale >= max(self.patience, 1):
                    self.stopped_epoch = epoch
                    return True
            return False

    @staticmethod
    def _early_stopper(early_stopping) -> Optional["Trainer._EarlyStopping"]:
        if early_stopping is None:
            return None
        if isinstance(early_stopping, Trainer._EarlyStopping):
            return early_stopping
        return Trainer._EarlyStopping(**dict(early_stopping))

    def _batch_keys(self, epoch: int, chunk_idx: int, shape) -> np.ndarray:
        """Deterministic per-(seed, epoch, chunk, batch) dropout keys —
        raw uint32 threefry pairs, one per minibatch slot in ``shape``.
        One definition for single and distributed trainers so the
        determinism contract can't silently diverge between them."""
        krng = np.random.default_rng([self.seed, epoch, chunk_idx])
        return krng.integers(0, 2**32, size=tuple(shape) + (2,), dtype=np.uint32)

    def _record_epoch_metrics(self, epoch: int, samples: int, seconds: float,
                              chips: int = 1) -> None:
        """``chips`` = devices this trainer actually engaged — NOT
        ``jax.device_count()``, which would under-report per-chip rate when
        fewer replicas than visible devices are in use.

        Mirrored into the process telemetry registry (when enabled) under
        the ``trainer`` label, so a snapshot pulled off a running job sees
        the same per-epoch numbers this list accumulates."""
        rate = round(samples / max(seconds, 1e-9) / max(chips, 1), 1)
        self.metrics.append({
            "epoch": epoch,
            "samples": int(samples),
            "seconds": round(seconds, 4),
            "chips": int(chips),
            "samples_per_sec_per_chip": rate,
        })
        if obs.enabled():
            name = type(self).__name__
            obs.counter("trainer_epochs_total", trainer=name).inc()
            obs.counter("trainer_samples_total", trainer=name).inc(samples)
            obs.histogram("trainer_epoch_seconds", trainer=name).observe(seconds)
            obs.gauge("trainer_samples_per_sec_per_chip", trainer=name).set(rate)

    def _record_window_losses(self, losses) -> None:
        """Append per-window mean losses to ``history`` and (when telemetry
        is on) the ``trainer_window_loss`` histogram — the loss trace's
        queryable form."""
        values = [float(x) for x in np.asarray(losses).ravel()]
        self.history.extend(values)
        if obs.enabled() and values:
            hist = obs.histogram("trainer_window_loss",
                                 trainer=type(self).__name__)
            for v in values:
                hist.observe(v)


class SingleTrainer(Trainer):
    """Single-device training — the reference's minimal path (SURVEY §3.2):
    one coalesced partition, one worker, plain SGD.  Here: one chip, the
    epoch compiled to a single ``lax.scan`` program.

    ``checkpointer`` (no reference counterpart — SURVEY §5 "Checkpoint:
    none in-library") persists (params, opt_state) after every epoch and
    resumes from the latest checkpoint if one exists.
    """

    def train(self, dataset: Dataset, shuffle: bool = True,
              checkpointer: Optional[Checkpointer] = None,
              validation_data: Optional[Dataset] = None,
              early_stopping=None) -> Model:
        """``early_stopping``: None, a ``Trainer._EarlyStopping``, or a dict
        of its kwargs (``patience``/``min_delta``/``monitor``/
        ``restore_best``) — Keras-EarlyStopping semantics over the per-epoch
        validation metrics (requires ``validation_data=``)."""
        self.record_training_start()
        stopper = self._early_stopper(early_stopping)
        if stopper is not None and validation_data is None:
            raise ValueError(
                "early_stopping monitors validation metrics; pass "
                "validation_data= (failing now beats training a full epoch "
                "before the missing metric is noticed)")
        # cached across train() calls: scan_epoch_fn returns a fresh jit
        # closure each time, which would defeat the jit cache and recompile
        # on every call (callers like the baseline runner call train() once
        # per epoch to evaluate in between)
        epoch_fn = getattr(self, "_epoch_fn", None)
        needs_rng = self.model.spec.needs_rng
        if epoch_fn is None:
            apply = (self.model.spec.train_apply_fn() if needs_rng
                     else self.model.spec.apply_fn())
            epoch_fn = scan_epoch_fn(apply, self.loss, self.optimizer,
                                     with_rng=needs_rng)
            self._epoch_fn = epoch_fn
        # epoch_fn donates its (params, opt_state) buffers; work on a copy so
        # the caller's Model object stays valid
        params = jax.tree.map(jnp.array, self.model.params)
        opt_state = self.optimizer.init(params)
        start_epoch = 0
        if checkpointer is not None:
            # resolve the step once: restore() and metadata() must read the
            # SAME checkpoint even if a concurrent writer lands a new one
            ckpt_step = checkpointer.latest_step()
            if ckpt_step is not None:
                restored = checkpointer.restore({"params": params, "opt_state": opt_state},
                                                step=ckpt_step)
                params = jax.tree.map(jnp.asarray, restored["params"])
                opt_state = jax.tree.map(jnp.asarray, restored["opt_state"])
                start_epoch = int(checkpointer.metadata(step=ckpt_step)["metadata"]["epochs_done"])
        with self._profile_ctx():
            for epoch in range(start_epoch, self.num_epoch):
                t_epoch = time.time()
                samples = 0
                ds = dataset.shuffle(seed=self.seed + epoch) if shuffle else dataset

                def place(chunk):
                    # async H2D issue only — prefetch_to_device overlaps the
                    # next chunk's copy-in with this chunk's training
                    return (jnp.asarray(chunk[self.features_col].squeeze(1)),
                            jnp.asarray(chunk[self.label_col].squeeze(1)))

                placed = prefetch_to_device(
                    ds.chunked_epoch(self.batch_size,
                                     [self.features_col, self.label_col],
                                     window=1,
                                     chunk_windows=self._resolve_chunk_windows(
                                         ds, self.batch_size, 1)),
                    place)
                with obs.span("trainer.epoch", trainer=type(self).__name__,
                              epoch=epoch):
                    for chunk_idx, (xs, ys) in enumerate(placed):
                        if needs_rng:
                            keys = self._batch_keys(epoch, chunk_idx, (xs.shape[0],))
                            params, opt_state, losses = epoch_fn(
                                params, opt_state, xs, ys, jnp.asarray(keys))
                        else:
                            params, opt_state, losses = epoch_fn(params, opt_state,
                                                                 xs, ys)
                        self._record_window_losses(losses)
                        samples += xs.shape[0] * xs.shape[1]
                self._record_epoch_metrics(epoch, samples, time.time() - t_epoch, chips=1)
                val = self._validate(params, validation_data)
                if val:
                    self.metrics[-1].update(val)
                if checkpointer is not None:
                    checkpointer.save(epoch + 1, {"params": params, "opt_state": opt_state},
                                      metadata={"epochs_done": epoch + 1})
                if stopper is not None and stopper.update(epoch, self.metrics[-1], params):
                    if stopper.restore_best and stopper.best_params is not None:
                        params = jax.tree.map(jnp.asarray, stopper.best_params)
                    break
        self.model = Model(spec=self.model.spec, params=params)
        self.record_training_end()
        return self.model


class DistributedTrainer(Trainer):
    """Common scaffolding for mesh-replica training (reference §2.4).

    ``num_workers`` defaults to every visible device.  Subclasses provide
    ``allocate_algorithm()`` — the analogue of the reference's
    ``allocate_worker``/``allocate_parameter_server`` factory pair, now a
    single collective update rule.
    """

    def __init__(self, model, num_workers: Optional[int] = None, communication_window: int = 5,
                 mesh=None, **kwargs):
        super().__init__(model, **kwargs)
        self.communication_window = int(communication_window)
        self.mesh = mesh if mesh is not None else create_mesh(num_workers)
        self.num_workers = self.mesh.shape["replica"]
        self._engine: Optional[WindowEngine] = None

    def allocate_algorithm(self) -> Algorithm:  # pragma: no cover - interface
        raise NotImplementedError

    def _divergent_seeds(self) -> Optional[Sequence[int]]:
        return None

    @property
    def engine(self) -> WindowEngine:
        if self._engine is None:
            self._engine = WindowEngine(
                spec=self.model.spec,
                loss=self.loss,
                optimizer=self.optimizer,
                algorithm=self.allocate_algorithm(),
                mesh=self.mesh,
                window=self.communication_window,
            )
        return self._engine

    def _validation_params(self, state):
        """Params the per-epoch validation should score — the center for
        PS-style trainers; overridden where the center is not the artifact
        (AveragingTrainer scores the average of the replicas)."""
        return self.engine.center_model(state).params

    def _restore_best(self, model: Model) -> Model:
        """Swap in the early-stopping best-epoch weights when a stop
        recorded them; shared by every train() so subclasses overriding
        train() cannot silently drop restoration."""
        if getattr(self, "_es_best_params", None) is not None:
            return Model(spec=self.model.spec,
                         params=jax.tree.map(jnp.asarray, self._es_best_params))
        return model

    def _run_epochs(self, dataset: Dataset, shuffle: bool,
                    checkpointer: Optional[Checkpointer] = None,
                    validation_data: Optional[Dataset] = None,
                    early_stopping=None) -> Any:
        stopper = self._early_stopper(early_stopping)
        if stopper is not None and validation_data is None:
            raise ValueError(
                "early_stopping monitors validation metrics; pass "
                "validation_data= (failing now beats training a full epoch "
                "before the missing metric is noticed)")
        self._es_best_params = None  # set when early stopping restores best
        engine = self.engine
        state = engine.init_state(self.model, divergent_seeds=self._divergent_seeds())
        start_epoch = 0
        if checkpointer is not None:
            ckpt_step = checkpointer.latest_step()
            if jax.process_count() > 1:
                # every process MUST resume from the same step or they
                # issue different numbers of collectives and the job
                # hangs: process 0's view of the spool is authoritative
                # (it is the writer).  A process that then can't READ
                # that step fails loudly — the checkpoint dir must be a
                # shared filesystem.
                from jax.experimental import multihost_utils

                step = multihost_utils.broadcast_one_to_all(
                    np.int64(-1 if ckpt_step is None else ckpt_step))
                ckpt_step = None if int(step) < 0 else int(step)
            if ckpt_step is not None:
                restored = checkpointer.restore({"state": state}, step=ckpt_step)["state"]
                state = engine.shard_state(restored)
                start_epoch = int(checkpointer.metadata(step=ckpt_step)["metadata"]["epochs_done"])
        global_batch = self.batch_size * self.num_workers
        with self._profile_ctx():
            for epoch in range(start_epoch, self.num_epoch):
                t_epoch = time.time()
                samples = 0
                ds = dataset.shuffle(seed=self.seed + epoch) if shuffle else dataset
                placed = prefetch_to_device(
                    ds.chunked_epoch(global_batch,
                                     [self.features_col, self.label_col],
                                     window=self.communication_window,
                                     chunk_windows=self._resolve_chunk_windows(
                                         ds, global_batch,
                                         self.communication_window)),
                    lambda ch: engine.place_data(ch[self.features_col],
                                                 ch[self.label_col]))
                with obs.span("trainer.epoch", trainer=type(self).__name__,
                              epoch=epoch):
                    for chunk_idx, (xs_d, ys_d) in enumerate(placed):
                        keys = None
                        if engine.needs_rng:
                            keys = self._batch_keys(epoch, chunk_idx, xs_d.shape[:2])
                        state, losses = engine.run_epoch(state, xs_d, ys_d, keys=keys)
                        self._record_window_losses(losses)
                        samples += (xs_d.shape[0]
                                    * self.communication_window * global_batch)
                self._record_epoch_metrics(epoch, samples, time.time() - t_epoch,
                                           chips=self.num_workers)
                if validation_data is not None:
                    vparams = self._validation_params(state)
                    val = self._validate(vparams, validation_data)
                    self.metrics[-1].update(val)
                if checkpointer is not None:
                    if jax.process_count() > 1:
                        # replicas live on other hosts: ALL processes run
                        # the row-gather collectives, only process 0
                        # materializes the host copy and writes.  The
                        # barrier after the write is what makes the spool
                        # consistent: without it another process can
                        # finish train(), start a resumed run, and read
                        # latest_step() BEFORE process 0's atomic rename
                        # lands — divergent start_epochs then issue
                        # mismatched collectives and the job hangs.  (If
                        # process 0 dies mid-save the others block here
                        # until the distributed runtime declares it dead
                        # — a visible failure, not silent divergence.)
                        from jax.experimental import multihost_utils

                        writer = jax.process_index() == 0
                        host_state = engine.gather_state(state, to_host=writer)
                        if writer:
                            checkpointer.save(epoch + 1, {"state": host_state},
                                              metadata={"epochs_done": epoch + 1})
                        multihost_utils.sync_global_devices(
                            f"distkeras-ckpt-{epoch + 1}")
                    else:
                        checkpointer.save(epoch + 1, {"state": state},
                                          metadata={"epochs_done": epoch + 1})
                if stopper is not None and stopper.update(
                        epoch, self.metrics[-1], vparams):
                    if stopper.restore_best and stopper.best_params is not None:
                        self._es_best_params = stopper.best_params
                    break
        return state

    def train(self, dataset: Dataset, shuffle: bool = True,
              checkpointer: Optional[Checkpointer] = None,
              validation_data: Optional[Dataset] = None,
              early_stopping=None) -> Model:
        """``early_stopping``: see ``SingleTrainer.train`` — monitored on
        the center/average params the trainer would hand back."""
        self.record_training_start()
        state = self._run_epochs(dataset, shuffle, checkpointer, validation_data,
                                 early_stopping=early_stopping)
        self.model = self._restore_best(self.engine.center_model(state))
        self.record_training_end()
        return self.model


class ADAG(DistributedTrainer):
    """Asynchronous Distributed Adaptive Gradients (reference §2.6):
    windowed delta commits, normalized on the center."""

    def allocate_algorithm(self) -> Algorithm:
        return AdagAlgorithm()


class DOWNPOUR(DistributedTrainer):
    """Downpour SGD (reference §2.5): raw accumulated-delta commits."""

    def allocate_algorithm(self) -> Algorithm:
        return DownpourAlgorithm()


class AEASGD(DistributedTrainer):
    """Asynchronous elastic averaging SGD (reference §2.8)."""

    def __init__(self, model, rho: float = 5.0, communication_window: int = 32, **kwargs):
        super().__init__(model, communication_window=communication_window, **kwargs)
        if callable(self.learning_rate):
            raise ValueError(
                "elastic trainers need a scalar learning_rate (the elastic "
                "coupling alpha = rho * lr is a constant); to schedule the "
                "local steps, pass an optax optimizer built with the schedule "
                "as worker_optimizer and keep learning_rate scalar")
        self.rho = float(rho)

    def allocate_algorithm(self) -> Algorithm:
        return ElasticAlgorithm(rho=self.rho, learning_rate=self.learning_rate)


class EAMSGD(AEASGD):
    """Elastic averaging with momentum on the local step (reference §2.9).
    Same elastic commit as AEASGD; the momentum lives in the local optax
    optimizer (Nesterov by default, per the EAMSGD paper)."""

    def __init__(self, model, rho: float = 5.0, momentum: float = 0.9, **kwargs):
        kwargs.setdefault("worker_optimizer", "nesterov")
        super().__init__(model, rho=rho, momentum=momentum, **kwargs)


class DynSGD(DistributedTrainer):
    """Staleness-aware dynamic learning rate (reference §2.7):
    commit r scaled by 1/(staleness_r + 1)."""

    def allocate_algorithm(self) -> Algorithm:
        return DynSGDAlgorithm()


class AveragingTrainer(DistributedTrainer):
    """Train N independent replicas, then average weights (reference §2.2)."""

    def __init__(self, model, **kwargs):
        kwargs.setdefault("communication_window", 1)
        super().__init__(model, **kwargs)

    def allocate_algorithm(self) -> Algorithm:
        return NoCommitAlgorithm()

    def _validation_params(self, state):
        # NoCommit leaves the center at init; the meaningful per-epoch
        # artifact is the average of the replicas
        return self.engine.averaged_model(state).params

    def train(self, dataset: Dataset, shuffle: bool = True,
              checkpointer: Optional[Checkpointer] = None,
              validation_data: Optional[Dataset] = None,
              early_stopping=None) -> Model:
        self.record_training_start()
        state = self._run_epochs(dataset, shuffle, checkpointer, validation_data,
                                 early_stopping=early_stopping)
        self.model = self._restore_best(self.engine.averaged_model(state))
        self.record_training_end()
        return self.model


class EnsembleTrainer(DistributedTrainer):
    """Train N independent models and return all of them (reference §2.3).

    ``decorrelate=True`` re-initializes each member from its own seed
    (reference used ``utils.uniform_weights`` for this).
    """

    def __init__(self, model, decorrelate: bool = True, **kwargs):
        kwargs.setdefault("communication_window", 1)
        super().__init__(model, **kwargs)
        self.decorrelate = decorrelate

    def allocate_algorithm(self) -> Algorithm:
        return NoCommitAlgorithm()

    def _divergent_seeds(self) -> Optional[Sequence[int]]:
        if not self.decorrelate:
            return None
        return [self.seed + 1000 + i for i in range(self.num_workers)]

    def train(self, dataset: Dataset, shuffle: bool = True,
              checkpointer: Optional[Checkpointer] = None,
              validation_data: Optional[Dataset] = None,
              early_stopping=None) -> List[Model]:  # type: ignore[override]
        if validation_data is not None or early_stopping is not None:
            raise ValueError(
                "per-epoch validation (and early stopping on it) is "
                "ambiguous for an ensemble (N independent members, no "
                "single center); evaluate the returned models with "
                "ModelPredictor/AccuracyEvaluator")
        self.record_training_start()
        state = self._run_epochs(dataset, shuffle, checkpointer)
        models = self.engine.local_models(state)
        self.record_training_end()
        return models
