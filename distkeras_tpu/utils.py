"""Utility helpers (reference parity: ``distkeras/utils.py``).

The reference's utility layer provides Keras model serialization
(``serialize_keras_model`` / ``deserialize_keras_model`` — architecture JSON
plus a weight list), weight re-initialization (``uniform_weights``), dataset
shuffling, and small DataFrame helpers.  Here the same surface is provided
for Flax/JAX: a model is an architecture record (registry name + config)
plus a parameter pytree, and all helpers are pure functions over numpy/JAX
arrays.
"""

from __future__ import annotations

import io
import json
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def flatten_weights(params: Any) -> Tuple[List[np.ndarray], Any]:
    """Flatten a parameter pytree into an ordered weight list + treedef.

    Mirrors the reference's representation of a model's weights as the flat
    list returned by Keras ``model.get_weights()``.
    """
    leaves, treedef = jax.tree.flatten(params)
    return [np.asarray(leaf) for leaf in leaves], treedef


def unflatten_weights(treedef: Any, weights: List[np.ndarray]) -> Any:
    return jax.tree.unflatten(treedef, [jnp.asarray(w) for w in weights])


def _dtype_from_name(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registered custom dtypes (bfloat16, fp8, ...)

        return np.dtype(getattr(ml_dtypes, name))


def encode_array(arr: np.ndarray) -> np.ndarray:
    """Flat uint8 byte view of an array — the npz-safe leaf encoding shared
    by model serialization and checkpointing (handles bfloat16 etc., which
    npz cannot store natively)."""
    return np.ascontiguousarray(arr).view(np.uint8).reshape(-1)


def decode_array(raw: np.ndarray, dtype_name: str, shape) -> np.ndarray:
    """Inverse of :func:`encode_array` given the recorded dtype name/shape."""
    return np.frombuffer(raw.tobytes(), dtype=_dtype_from_name(dtype_name)).reshape(shape)


def serialize_model(architecture: Dict[str, Any], params: Any) -> bytes:
    """Serialize (architecture, weights) to bytes — npz + JSON, **no pickle**.

    Reference parity: ``utils.serialize_keras_model`` produced a dict of
    ``{'model': architecture_json, 'weights': weight_list}``; we keep the
    same two-part structure so a model can travel between processes (the
    reference shipped it inside Spark task closures; here it crosses host
    boundaries for multi-host launch or checkpointing).  The reference used
    pickle, which executes arbitrary code at load time; here weights are
    raw bytes with a JSON manifest of (dtype, shape), so loading untrusted
    checkpoints is safe.  Non-numpy dtypes (bfloat16 etc.) are stored as
    byte views and restored via their recorded dtype name.
    """
    weights, _ = flatten_weights(params)
    manifest = {
        "architecture": architecture,
        "weights": [{"dtype": w.dtype.name, "shape": list(w.shape)} for w in weights],
    }
    buf = io.BytesIO()
    arrays = {f"w{i}": encode_array(w) for i, w in enumerate(weights)}
    np.savez(buf, __manifest__=np.frombuffer(json.dumps(manifest).encode(), dtype=np.uint8), **arrays)
    return buf.getvalue()


def deserialize_model(blob: bytes) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Inverse of :func:`serialize_model` (``allow_pickle=False`` throughout).

    Returns the architecture dict and the flat weight list; use the model
    registry (``models.base.build_model``) to reconstruct the pytree
    structure and :func:`unflatten_weights` to restore parameters.
    """
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        manifest = json.loads(bytes(z["__manifest__"]).decode())
        weights = [decode_array(z[f"w{i}"], meta["dtype"], meta["shape"])
                   for i, meta in enumerate(manifest["weights"])]
    return manifest["architecture"], weights


def uniform_weights(params: Any, seed: int = 0, low: float = -0.05, high: float = 0.05) -> Any:
    """Re-initialize every weight tensor uniformly in ``[low, high]``.

    Reference parity: ``utils.uniform_weights(model)`` which re-drew each
    Keras weight array from a uniform distribution (used to decorrelate
    ensemble members).
    """
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(leaves))
    new_leaves = [
        jax.random.uniform(k, shape=jnp.shape(leaf), dtype=jnp.result_type(leaf), minval=low, maxval=high)
        for k, leaf in zip(keys, leaves)
    ]
    return jax.tree.unflatten(treedef, new_leaves)


def shuffle_arrays(arrays: Dict[str, np.ndarray], seed: int = 0) -> Dict[str, np.ndarray]:
    """Shuffle all columns with one shared permutation.

    Reference parity: ``utils.shuffle(dataset)`` (row shuffle of the
    DataFrame before repartitioning across workers).
    """
    sizes = {len(v) for v in arrays.values()}
    if len(sizes) != 1:
        raise ValueError(f"columns have mismatched lengths: { {k: len(v) for k, v in arrays.items()} }")
    n = sizes.pop()
    perm = np.random.default_rng(seed).permutation(n)
    return {k: v[perm] for k, v in arrays.items()}


