#!/usr/bin/env python
"""Shim: the workflow example lives in the installed package
(``distkeras_tpu/examples/workflow.py``; console script
``distkeras-workflow``).  Kept here so `python examples/workflow.py`
keeps working from a source checkout."""

from distkeras_tpu.examples.workflow import main

if __name__ == "__main__":
    main()
