// Native columnar data loader: mmap + page warming + chunk prefetch.
//
// The reference's data plane was Spark's JVM reading HDFS partitions; this
// framework's host-side analogue is a flat columnar container ("DKCOL")
// that maps straight into the process: zero-copy column views, an optional
// background warm thread that touches pages ahead of training (so the
// first epoch doesn't stall on page faults), and madvise-based prefetch
// hooks the Python chunked feeder calls one chunk ahead.
//
// Container layout (little-endian, written by distkeras_tpu/data/colfile.py):
//   8  bytes magic "DKCOL1\0\0"
//   u32 ncols
//   per column:
//     u32 name_len, name bytes
//     u32 dtype_len, dtype bytes (numpy dtype.str, e.g. "<f4")
//     u32 ndim, ndim * i64 dims
//     u64 offset (from file start, 64-aligned), u64 nbytes
//
// C ABI (ctypes, no pybind11 in this environment):
//   dk_dl_open / dk_dl_close / dk_dl_error
//   dk_dl_ncols / dk_dl_col_name / dk_dl_col_dtype / dk_dl_col_ndim /
//   dk_dl_col_dim / dk_dl_col_nbytes / dk_dl_col_data
//   dk_dl_prefetch (madvise WILLNEED on a byte range of a column)
//   dk_dl_warmed_bytes (progress of the warm thread)

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Col {
  std::string name;
  std::string dtype;
  std::vector<int64_t> dims;
  uint64_t offset = 0;
  uint64_t nbytes = 0;
};

struct Handle {
  int fd = -1;
  uint8_t* base = nullptr;
  uint64_t size = 0;
  std::vector<Col> cols;
  std::thread warmer;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> warmed{0};

  ~Handle() {
    stop.store(true);
    if (warmer.joinable()) warmer.join();
    if (base) munmap(base, size);
    if (fd >= 0) close(fd);
  }
};

thread_local std::string g_error;

bool read_exact(const uint8_t*& p, const uint8_t* end, void* out, size_t n) {
  if (p + n > end) return false;
  std::memcpy(out, p, n);
  p += n;
  return true;
}

void warm_pages(Handle* h) {
  // touch one byte per page sequentially; volatile defeats dead-read
  // elimination.  This pulls the file through the page cache ahead of the
  // training loop's first pass.
  const long page = sysconf(_SC_PAGESIZE);
  volatile uint8_t sink = 0;
  for (uint64_t off = 0; off < h->size; off += static_cast<uint64_t>(page)) {
    if (h->stop.load(std::memory_order_relaxed)) return;
    sink ^= h->base[off];
    h->warmed.store(off + page, std::memory_order_relaxed);
  }
  (void)sink;
  h->warmed.store(h->size, std::memory_order_relaxed);
}

}  // namespace

extern "C" {

const char* dk_dl_error() { return g_error.c_str(); }

void* dk_dl_open(const char* path, int warm) {
  g_error.clear();
  auto h = new Handle();
  h->fd = open(path, O_RDONLY);
  if (h->fd < 0) {
    g_error = std::string("open failed: ") + strerror(errno);
    delete h;
    return nullptr;
  }
  struct stat st;
  if (fstat(h->fd, &st) != 0 || st.st_size < 12) {
    g_error = "stat failed or file too small";
    delete h;
    return nullptr;
  }
  h->size = static_cast<uint64_t>(st.st_size);
  void* m = mmap(nullptr, h->size, PROT_READ, MAP_SHARED, h->fd, 0);
  if (m == MAP_FAILED) {
    g_error = std::string("mmap failed: ") + strerror(errno);
    delete h;
    return nullptr;
  }
  h->base = static_cast<uint8_t*>(m);
  madvise(h->base, h->size, MADV_SEQUENTIAL);

  const uint8_t* p = h->base;
  const uint8_t* end = h->base + h->size;
  if (std::memcmp(p, "DKCOL1\0\0", 8) != 0) {
    g_error = "bad magic: not a DKCOL1 container";
    delete h;
    return nullptr;
  }
  p += 8;
  uint32_t ncols = 0;
  if (!read_exact(p, end, &ncols, 4) || ncols > 4096) {
    g_error = "bad column count";
    delete h;
    return nullptr;
  }
  for (uint32_t i = 0; i < ncols; ++i) {
    Col c;
    uint32_t nlen = 0, dlen = 0, ndim = 0;
    if (!read_exact(p, end, &nlen, 4) || nlen > 4096) goto corrupt;
    c.name.resize(nlen);
    if (!read_exact(p, end, c.name.data(), nlen)) goto corrupt;
    if (!read_exact(p, end, &dlen, 4) || dlen > 64) goto corrupt;
    c.dtype.resize(dlen);
    if (!read_exact(p, end, c.dtype.data(), dlen)) goto corrupt;
    if (!read_exact(p, end, &ndim, 4) || ndim > 32) goto corrupt;
    c.dims.resize(ndim);
    if (!read_exact(p, end, c.dims.data(), 8 * ndim)) goto corrupt;
    for (int64_t d : c.dims)
      if (d < 0) goto corrupt;  // negative dims would let numpy infer shapes
    if (!read_exact(p, end, &c.offset, 8)) goto corrupt;
    if (!read_exact(p, end, &c.nbytes, 8)) goto corrupt;
    // overflow-safe bounds check: offset + nbytes could wrap in uint64
    if (c.offset > h->size || c.nbytes > h->size - c.offset) goto corrupt;
    h->cols.push_back(std::move(c));
  }
  if (warm) h->warmer = std::thread(warm_pages, h);
  return h;
corrupt:
  g_error = "corrupt DKCOL header";
  delete h;
  return nullptr;
}

void dk_dl_close(void* handle) { delete static_cast<Handle*>(handle); }

// Release the handle WITHOUT unmapping: stops the warm thread and closes
// the fd, but leaves the mapping alive for the process lifetime so numpy
// views handed out earlier can never dangle (file-backed clean pages are
// reclaimable by the kernel, so the "leak" costs address space, not RAM).
void dk_dl_release(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  h->stop.store(true);
  if (h->warmer.joinable()) h->warmer.join();
  if (h->fd >= 0) { close(h->fd); h->fd = -1; }
  h->base = nullptr;  // ~Handle skips munmap
  delete h;
}

int32_t dk_dl_ncols(void* handle) {
  return static_cast<int32_t>(static_cast<Handle*>(handle)->cols.size());
}

const char* dk_dl_col_name(void* handle, int32_t i) {
  return static_cast<Handle*>(handle)->cols[i].name.c_str();
}

const char* dk_dl_col_dtype(void* handle, int32_t i) {
  return static_cast<Handle*>(handle)->cols[i].dtype.c_str();
}

int32_t dk_dl_col_ndim(void* handle, int32_t i) {
  return static_cast<int32_t>(static_cast<Handle*>(handle)->cols[i].dims.size());
}

int64_t dk_dl_col_dim(void* handle, int32_t i, int32_t j) {
  return static_cast<Handle*>(handle)->cols[i].dims[j];
}

int64_t dk_dl_col_nbytes(void* handle, int32_t i) {
  return static_cast<int64_t>(static_cast<Handle*>(handle)->cols[i].nbytes);
}

const uint8_t* dk_dl_col_data(void* handle, int32_t i) {
  auto* h = static_cast<Handle*>(handle);
  return h->base + h->cols[i].offset;
}

// madvise(WILLNEED) a byte range of column i — the chunked feeder calls
// this for chunk k+1 while the trainer consumes chunk k.
void dk_dl_prefetch(void* handle, int32_t i, int64_t start, int64_t nbytes) {
  auto* h = static_cast<Handle*>(handle);
  const Col& c = h->cols[i];
  if (start < 0 || nbytes <= 0 ||
      static_cast<uint64_t>(start + nbytes) > c.nbytes)
    return;
  const long page = sysconf(_SC_PAGESIZE);
  uint64_t abs = c.offset + static_cast<uint64_t>(start);
  uint64_t aligned = abs & ~static_cast<uint64_t>(page - 1);
  uint64_t len = abs + static_cast<uint64_t>(nbytes) - aligned;
  madvise(h->base + aligned, len, MADV_WILLNEED);
}

int64_t dk_dl_warmed_bytes(void* handle) {
  return static_cast<int64_t>(static_cast<Handle*>(handle)->warmed.load());
}

}  // extern "C"
