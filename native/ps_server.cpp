// Native parameter-server hub — C++ implementation of the framed tensor
// protocol in distkeras_tpu/runtime/networking.py (the executable spec is
// the Python SocketParameterServer; both speak identical bytes).
//
// Reference parity: distkeras/parameter_servers.py ran this hub as Python
// threads, so every commit serialized on the GIL (SURVEY.md §3.4).  Here
// accept/handler threads are native and the hub is at FEATURE PARITY with
// the production Python hub (ISSUE 11): row-sparse embedding traffic,
// Adasum flat-combining adaptive aggregation, the hot-standby replication
// feed (both sides), reconnect backpressure and health-report ingestion
// all run GIL-free, with the Python hub kept as the executable spec via
// the bit-parity test matrices in tests/.
//
// Wire format (all integers big-endian):
//   frame          := u64 payload_len, payload
//   tensor payload := u8 action, u32 num_tensors,
//                     num_tensors * (u64 nbytes, raw bytes)
//   actions: 'P' pull -> 'W' + center tensors
//            'C' commit (center-shaped f32 deltas) -> 'A'
//            'Q' int8 commit (per tensor: be f32 scale + int8 values) -> 'A'
//            'S' sparse pull: one int64 sorted-unique row-id blob per
//                sparse table -> 'V' + one blob per CENTER leaf (full f32
//                leaf for dense leaves, the requested [k, dim] row block
//                for sparse leaves)
//            'U' sparse f32 commit: per leaf in template order — one full
//                f32 blob for dense leaves, TWO blobs (int64 row ids, f32
//                [k, dim] row grads) for sparse leaves -> 'A'
//            'X' sparse int8 commit: same layout, every value blob a 'Q'
//                blob (the row block quantized as one unit) -> 'A'
//            'H' heartbeat -> 'A'
//            'M' health report (one JSON blob) -> 'A'; the report is
//                parked in a bounded ring the Python wrapper drains into
//                the process HealthCollector (runtime/native.py)
//            'T' trace-context announce -> 'T' + 8-byte monotonic ns
//            'G' reconnect announce -> 'Y' + 8-byte retry-after hint (ms;
//                nonzero only while an adaptive hub is shedding a storm)
//            'R' replication hello: this peer is a hot standby — it is
//                full-synced (one R frame: 9-byte header blob + the whole
//                center) and thereafter receives one R delta frame per
//                applied commit, written BEFORE the committing worker's
//                ack (the zero-acked-commit-loss contract of ISSUE 7)
//            'Z' shm attach request (u8 version + u64 capacity hint) ->
//                'Z' offer (two ring-file path blobs) or decline (zero
//                blobs); on offer the client confirms with one more 'Z'
//                (one 1-byte blob) and both sides switch the SAME byte
//                stream onto a pair of mmap rings — frames after the
//                confirm move over shared memory, byte-identical to the
//                socket encoding (ISSUE 18; opt-in, legacy peers never
//                see the action)
//            'B' bye -> connection closes
//
// Locking (the ISSUE-11 hot-path redesign):
//   - gate_ (std::shared_mutex): commits take it SHARED — many commits
//     apply concurrently — while pulls / snapshots / replica syncs /
//     restores take it EXCLUSIVE, so every snapshot is a consistent
//     (clock, center) pair exactly like the Python hub's single lock
//     gives, without serializing the commit plane behind it.
//   - meta_ (std::mutex): clock, fence, membership, every counter and the
//     commit log — held for nanoseconds per commit.
//   - stripes_[16]: per-leaf-group apply locks (leaf i -> stripe i % 16):
//     two concurrent commits walking the center pipeline through
//     different leaves instead of serializing on one center mutex.
//
// Receive path: one grow-once buffer per connection, filled with a single
// recv() per wakeup — a pipelined client's parked commit + pull request
// arrive in ONE syscall and are parsed back to back.  Acks for a parsed
// run of commits/heartbeats coalesce into one send (flushed before any
// other reply and before any blocking recv, so the client's max-inflight
// backpressure never deadlocks).  Weights replies leave via writev
// scatter-gather straight out of the snapshot buffer (header + per-tensor
// prefixes from a prebuilt arena) — the FlatFrameCodec layout without
// assembling a contiguous frame.
//
// Commit scaling modes (matching runtime/parameter_server.py):
//   0 delta:  center += d                (DOWNPOUR, elastic)
//   1 adag:   center += d / num_workers  (ADAG; elastic uses live members)
//   2 dynsgd: center += d / (staleness+1)
// Scales are computed in double and applied as float32, the exact
// arithmetic the Python hub's `delta * np.float32(scale)` performs (the
// bit-parity pins depend on it; the build also pins -ffp-contract=off so
// no FMA contraction can fuse the multiply-add differently).

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cstdio>

#include <cctype>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>

#include <algorithm>
#include <atomic>
#include <deque>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

int64_t mono_ns() {
  timespec ts{};
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000000000 + int64_t(ts.tv_nsec);
}

uint64_t be64_decode(const unsigned char* b) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | b[i];
  return v;
}

void be64_encode(uint64_t v, unsigned char* b) {
  for (int i = 7; i >= 0; --i) { b[i] = v & 0xff; v >>= 8; }
}

uint32_t be32_decode(const unsigned char* b) {
  return (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) |
         (uint32_t(b[2]) << 8) | b[3];
}

void be32_encode(uint32_t v, unsigned char* b) {
  b[0] = (unsigned char)(v >> 24);
  b[1] = (v >> 16) & 0xff;
  b[2] = (v >> 8) & 0xff;
  b[3] = v & 0xff;
}

float bef32_decode(const unsigned char* b) {
  uint32_t raw = be32_decode(b);
  float f;
  std::memcpy(&f, &raw, sizeof(f));
  return f;
}

bool read_exact(int fd, void* buf, size_t n, bool* timed_out = nullptr) {
  auto* p = static_cast<unsigned char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r <= 0) {
      if (timed_out && r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
        *timed_out = true;
      return false;
    }
    got += size_t(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const unsigned char*>(buf);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, p + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += size_t(r);
  }
  return true;
}

// scatter-gather send with partial-write recovery (the weights-reply hot
// path: header + per-tensor prefixes + payload leave the kernel without
// ever being assembled into one contiguous frame)
bool writev_all(int fd, struct iovec* iov, int iovcnt) {
  int idx = 0;
  while (idx < iovcnt) {
    int batch = std::min(iovcnt - idx, 64);  // stay far under IOV_MAX
    ssize_t r = ::writev(fd, iov + idx, batch);
    if (r <= 0) return false;
    size_t left = size_t(r);
    while (left > 0 && idx < iovcnt) {
      if (left >= iov[idx].iov_len) {
        left -= iov[idx].iov_len;
        ++idx;
      } else {
        iov[idx].iov_base = static_cast<unsigned char*>(iov[idx].iov_base) + left;
        iov[idx].iov_len -= left;
        left = 0;
      }
    }
  }
  return true;
}

// minimal extraction of an integer JSON field (the 'T' announce blob is
// produced by our own client, so a full parser buys nothing)
int64_t json_int_field(const unsigned char* buf, size_t n, const char* key,
                       int64_t fallback) {
  std::string s(reinterpret_cast<const char*>(buf), n);
  std::string needle = std::string("\"") + key + "\"";
  size_t pos = s.find(needle);
  if (pos == std::string::npos) return fallback;
  pos = s.find(':', pos + needle.size());
  if (pos == std::string::npos) return fallback;
  ++pos;
  while (pos < s.size() && (s[pos] == ' ' || s[pos] == '\t')) ++pos;
  if (pos >= s.size() ||
      (s[pos] != '-' && !isdigit(static_cast<unsigned char>(s[pos]))))
    return fallback;
  return std::strtoll(s.c_str() + pos, nullptr, 10);
}

// bounded-time TCP connect (the standby feed loop's dial; a stopping
// standby must not park in connect() against a dead host for minutes)
int connect_to(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    // hostname form: keep it simple — loopback only resolution for
    // "localhost" (the deployment path passes numeric addresses)
    if (std::strcmp(host, "localhost") == 0) {
      ::inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
    } else {
      ::close(fd);
      return -1;
    }
  }
  // non-blocking connect + poll: bounded, interruptible-by-timeout
  int flags = ::fcntl(fd, F_GETFL, 0);
  ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc != 0 && errno == EINPROGRESS) {
    pollfd pfd{fd, POLLOUT, 0};
    if (::poll(&pfd, 1, timeout_ms) != 1) { ::close(fd); return -1; }
    int err = 0;
    socklen_t len = sizeof(err);
    ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
    if (err != 0) { ::close(fd); return -1; }
  } else if (rc != 0) {
    ::close(fd);
    return -1;
  }
  ::fcntl(fd, F_SETFL, flags);
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// -- shared-memory frame ring (ISSUE 18) --------------------------------------
// mmap-backed SPSC byte ring, layout-identical to networking.ShmFrameRing
// (the Python side maps the same file).  Native-endian header:
//   u64 magic @0, u64 capacity @8, u64 head @64, u64 tail @128,
//   u32 producer_closed @192, u32 consumer_closed @196, data @4096.
// head/tail are free-running TOTAL byte counters (capacity is a power of
// two; position = counter & (capacity-1)).  The producer stores only head,
// the consumer only tail — the SPSC contract that needs no lock, just
// release on the writer's own counter and acquire on the peer's.  The ring
// carries the exact framed byte stream the socket would, so wire parity
// holds by construction.
struct ShmRing {
  static constexpr uint64_t kMagic = 0x646b2d72696e6731ULL;  // "dk-ring1"
  static constexpr size_t kHeaderBytes = 4096;
  static constexpr int kSpin = 200;  // busy iterations before parking

  unsigned char* base_ = nullptr;
  size_t map_len_ = 0;
  uint64_t capacity_ = 0;
  bool producer_ = false;
  std::atomic<uint64_t>* head_ = nullptr;
  std::atomic<uint64_t>* tail_ = nullptr;
  std::atomic<uint32_t>* producer_closed_ = nullptr;
  std::atomic<uint32_t>* consumer_closed_ = nullptr;
  unsigned char* data_ = nullptr;

  ~ShmRing() {
    if (base_) {
      // severing a live connection must WAKE a parked peer (the protocol
      // model's sever_wakes_ring_peer rule): raise BOTH flags, then unmap
      producer_closed_->store(1, std::memory_order_release);
      consumer_closed_->store(1, std::memory_order_release);
      ::munmap(base_, map_len_);
    }
  }

  void bind_header() {
    capacity_ = *reinterpret_cast<uint64_t*>(base_ + 8);
    head_ = reinterpret_cast<std::atomic<uint64_t>*>(base_ + 64);
    tail_ = reinterpret_cast<std::atomic<uint64_t>*>(base_ + 128);
    producer_closed_ = reinterpret_cast<std::atomic<uint32_t>*>(base_ + 192);
    consumer_closed_ = reinterpret_cast<std::atomic<uint32_t>*>(base_ + 196);
    data_ = base_ + kHeaderBytes;
  }

  static ShmRing* create(const char* path, bool producer, uint64_t capacity) {
    // round up to a power of two >= one page (the Python opener validates)
    uint64_t cap = 4096;
    while (cap < capacity) cap <<= 1;
    int fd = ::open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
    if (fd < 0) return nullptr;
    if (::ftruncate(fd, off_t(kHeaderBytes + cap)) != 0) {
      ::close(fd);
      ::unlink(path);
      return nullptr;
    }
    void* m = ::mmap(nullptr, size_t(kHeaderBytes + cap),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    ::close(fd);  // the mapping keeps the pages alive
    if (m == MAP_FAILED) {
      ::unlink(path);
      return nullptr;
    }
    auto* r = new ShmRing();
    r->base_ = static_cast<unsigned char*>(m);
    r->map_len_ = size_t(kHeaderBytes + cap);
    r->producer_ = producer;
    *reinterpret_cast<uint64_t*>(r->base_ + 8) = cap;
    r->bind_header();
    // magic stamped LAST (release): a racing opener either sees no magic
    // (not a ring yet) or a fully-initialized header
    reinterpret_cast<std::atomic<uint64_t>*>(r->base_)
        ->store(kMagic, std::memory_order_release);
    return r;
  }

  static ShmRing* open_existing(const char* path, bool producer) {
    int fd = ::open(path, O_RDWR);
    if (fd < 0) return nullptr;
    struct stat st {};
    if (::fstat(fd, &st) != 0 || size_t(st.st_size) <= kHeaderBytes) {
      ::close(fd);
      return nullptr;
    }
    void* m = ::mmap(nullptr, size_t(st.st_size), PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
    ::close(fd);
    if (m == MAP_FAILED) return nullptr;
    auto* base = static_cast<unsigned char*>(m);
    uint64_t magic = reinterpret_cast<std::atomic<uint64_t>*>(base)->load(
        std::memory_order_acquire);
    uint64_t cap = *reinterpret_cast<uint64_t*>(base + 8);
    if (magic != kMagic || cap == 0 || (cap & (cap - 1)) != 0 ||
        kHeaderBytes + cap != size_t(st.st_size)) {
      // not (yet) a ring: unmap WITHOUT touching the closed flags — this
      // mapping may be some other file entirely
      ::munmap(m, size_t(st.st_size));
      return nullptr;
    }
    auto* r = new ShmRing();
    r->base_ = base;
    r->map_len_ = size_t(st.st_size);
    r->producer_ = producer;
    r->bind_header();
    return r;
  }

  // busy-then-park: stay hot for kSpin iterations, then sleep with
  // exponential backoff 10us..1ms (the Python ring's exact policy).
  // false = deadline passed or the hub's stop flag cleared.
  static bool park(int* spins, int64_t started_ns, int timeout_ms,
                   const std::atomic<bool>* stop) {
    if (stop && !stop->load(std::memory_order_relaxed)) return false;
    if (timeout_ms > 0 &&
        mono_ns() - started_ns > int64_t(timeout_ms) * 1000000)
      return false;
    ++*spins;
    if (*spins <= kSpin) return true;
    int shift = *spins - kSpin;
    if (shift > 7) shift = 7;
    long ns = 10000L << shift;
    if (ns > 1000000L) ns = 1000000L;
    timespec ts{0, ns};
    ::nanosleep(&ts, nullptr);
    return true;
  }

  // sendall semantics; false = consumer gone / stop / deadline
  bool write(const unsigned char* p, size_t n, int timeout_ms,
             const std::atomic<bool>* stop) {
    uint64_t head = head_->load(std::memory_order_relaxed);
    size_t done = 0;
    int spins = 0;
    int64_t started = mono_ns();
    while (done < n) {
      if (consumer_closed_->load(std::memory_order_acquire)) return false;
      uint64_t tail = tail_->load(std::memory_order_acquire);
      uint64_t free_b = capacity_ - (head - tail);
      if (free_b == 0) {
        if (!park(&spins, started, timeout_ms, stop)) return false;
        continue;
      }
      spins = 0;
      uint64_t at = head & (capacity_ - 1);
      uint64_t chunk = uint64_t(n - done);
      if (chunk > free_b) chunk = free_b;
      if (chunk > capacity_ - at) chunk = capacity_ - at;
      std::memcpy(data_ + at, p + done, size_t(chunk));
      head += chunk;
      // payload first, head AFTER (release): the consumer's acquire load
      // of head can only ever observe fully-copied bytes
      head_->store(head, std::memory_order_release);
      done += size_t(chunk);
    }
    return true;
  }

  // one recv()-shaped read: >0 bytes out, 0 = clean EOF (producer closed
  // and drained), -1 = deadline / stop (deadline sets *timed_out)
  ssize_t read_some(unsigned char* p, size_t n, int timeout_ms,
                    const std::atomic<bool>* stop, bool* timed_out) {
    uint64_t tail = tail_->load(std::memory_order_relaxed);
    int spins = 0;
    int64_t started = mono_ns();
    for (;;) {
      uint64_t head = head_->load(std::memory_order_acquire);
      if (head != tail) {
        uint64_t at = tail & (capacity_ - 1);
        uint64_t chunk = head - tail;
        if (chunk > uint64_t(n)) chunk = uint64_t(n);
        if (chunk > capacity_ - at) chunk = capacity_ - at;
        std::memcpy(p, data_ + at, size_t(chunk));
        tail_->store(tail + chunk, std::memory_order_release);
        return ssize_t(chunk);
      }
      if (producer_closed_->load(std::memory_order_acquire)) {
        // one re-check: bytes published before the flag must drain first
        if (head_->load(std::memory_order_acquire) != tail) continue;
        return 0;
      }
      if (!park(&spins, started, timeout_ms, stop)) {
        if (timed_out && !(stop && !stop->load(std::memory_order_relaxed)))
          *timed_out = true;  // genuine deadline, not a hub shutdown
        return -1;
      }
    }
  }
};

// per-connection I/O endpoint: a TCP fd, optionally switched onto a ring
// pair mid-life by the 'Z' attach handshake.  The byte stream is identical
// either way — the rings carry the exact frames the socket would.
struct ConnIo {
  int fd = -1;
  ShmRing* rx = nullptr;  // client->hub ring (this side consumes)
  ShmRing* tx = nullptr;  // hub->client ring (this side produces)
  int timeout_ms = 0;     // ring deadline, mirroring SO_RCVTIMEO/SO_SNDTIMEO
  const std::atomic<bool>* stop = nullptr;  // hub running_ flag (wakes parks)
};

ssize_t io_recv_some(ConnIo& io, unsigned char* buf, size_t n,
                     bool* timed_out) {
  if (io.rx)
    return io.rx->read_some(buf, n, io.timeout_ms, io.stop, timed_out);
  ssize_t r = ::recv(io.fd, buf, n, 0);
  if (r < 0 && timed_out && (errno == EAGAIN || errno == EWOULDBLOCK))
    *timed_out = true;
  return r;
}

bool io_write_all(ConnIo& io, const void* buf, size_t n) {
  if (io.tx)
    return io.tx->write(static_cast<const unsigned char*>(buf), n,
                        io.timeout_ms, io.stop);
  return write_all(io.fd, buf, n);
}

bool io_writev_all(ConnIo& io, struct iovec* iov, int iovcnt) {
  if (!io.tx) return writev_all(io.fd, iov, iovcnt);
  // a ring write is a memcpy, not a syscall: segment-at-a-time keeps the
  // byte stream identical with zero gather cost
  for (int i = 0; i < iovcnt; ++i)
    if (!io.tx->write(static_cast<const unsigned char*>(iov[i].iov_base),
                      iov[i].iov_len, io.timeout_ms, io.stop))
      return false;
  return true;
}

// R-frame header kinds (first blob, 9 bytes big-endian: u64 clock, u8 kind)
constexpr int kReplDelta = 0;
constexpr int kReplSync = 1;
constexpr int kReplHello = 2;
// sparse row-delta frame (ISSUE 15): blobs past the header carry the
// U-commit layout (dense leaves whole, sparse leaves as ids + scaled
// rows).  Sent only to replicas whose hello announced kReplCapSparse
// (optional 10th header byte); legacy replicas keep the dense stream.
constexpr int kReplSparse = 3;
constexpr int kReplCapSparse = 1;

// one leaf of an incoming commit, aliasing the connection's receive buffer
// (or its dequantize scratch) — consumed before the next frame lands, the
// same zero-copy contract the Python hub's wire views follow
struct PartView {
  bool sparse = false;
  const float* vals = nullptr;   // dense: `size` floats; sparse: k*dim grads
  const int64_t* ids = nullptr;  // sparse only: k sorted-unique row ids
  int64_t k = 0;
};

// one leaf of a scaled/merged commit with owned storage (the adaptive
// combiner's working representation; Python's _scale_parts twin)
struct OwnedPart {
  bool sparse = false;
  std::vector<float> vals;
  std::vector<int64_t> ids;
};

// -- Adasum (arXiv:2006.02924) over per-leaf parts -----------------------------
// One merge rule for dense and sparse commits: sparse x sparse pairs dot on
// their row intersection and merge on the union, so idle rows cost nothing.
// Accumulation in double, coefficients cast to float32 for the combine —
// the Python combiner's arithmetic shape (no bit pin exists for merged
// batches; batch-of-one never reaches this code).

double adasum_dot(const std::vector<OwnedPart>& a,
                  const std::vector<OwnedPart>& b, const int64_t* dims) {
  double total = 0.0;
  for (size_t i = 0; i < a.size(); ++i) {
    if (a[i].sparse) {
      int64_t dim = dims[i];
      size_t ia = 0, ib = 0;
      while (ia < a[i].ids.size() && ib < b[i].ids.size()) {
        if (a[i].ids[ia] < b[i].ids[ib]) ++ia;
        else if (a[i].ids[ia] > b[i].ids[ib]) ++ib;
        else {
          const float* ga = a[i].vals.data() + int64_t(ia) * dim;
          const float* gb = b[i].vals.data() + int64_t(ib) * dim;
          for (int64_t j = 0; j < dim; ++j)
            total += double(ga[j]) * double(gb[j]);
          ++ia;
          ++ib;
        }
      }
    } else {
      for (size_t j = 0; j < a[i].vals.size(); ++j)
        total += double(a[i].vals[j]) * double(b[i].vals[j]);
    }
  }
  return total;
}

double adasum_normsq(const std::vector<OwnedPart>& p) {
  double total = 0.0;
  for (const auto& part : p)
    for (float v : part.vals) total += double(v) * double(v);
  return total;
}

std::vector<OwnedPart> adasum_pair(const std::vector<OwnedPart>& a,
                                   const std::vector<OwnedPart>& b,
                                   const int64_t* dims) {
  double na = adasum_normsq(a);
  double nb = adasum_normsq(b);
  if (na == 0.0) return b;
  if (nb == 0.0) return a;
  double dot = adasum_dot(a, b, dims);
  float alpha = float(1.0 - dot / (2.0 * na));
  float beta = float(1.0 - dot / (2.0 * nb));
  std::vector<OwnedPart> merged(a.size());
  for (size_t i = 0; i < a.size(); ++i) {
    OwnedPart& out = merged[i];
    out.sparse = a[i].sparse;
    if (a[i].sparse) {
      int64_t dim = dims[i];
      out.ids.reserve(a[i].ids.size() + b[i].ids.size());
      size_t ia = 0, ib = 0;
      while (ia < a[i].ids.size() || ib < b[i].ids.size()) {
        int64_t id;
        if (ib >= b[i].ids.size() ||
            (ia < a[i].ids.size() && a[i].ids[ia] <= b[i].ids[ib]))
          id = a[i].ids[ia];
        else
          id = b[i].ids[ib];
        out.ids.push_back(id);
        out.vals.insert(out.vals.end(), size_t(dim), 0.0f);
        float* row = out.vals.data() + (out.ids.size() - 1) * size_t(dim);
        if (ia < a[i].ids.size() && a[i].ids[ia] == id) {
          const float* ga = a[i].vals.data() + int64_t(ia) * dim;
          for (int64_t j = 0; j < dim; ++j) row[j] += alpha * ga[j];
          ++ia;
        }
        if (ib < b[i].ids.size() && b[i].ids[ib] == id) {
          const float* gb = b[i].vals.data() + int64_t(ib) * dim;
          for (int64_t j = 0; j < dim; ++j) row[j] += beta * gb[j];
          ++ib;
        }
      }
    } else {
      out.vals.resize(a[i].vals.size());
      for (size_t j = 0; j < out.vals.size(); ++j)
        out.vals[j] = alpha * a[i].vals[j] + beta * b[i].vals[j];
    }
  }
  return merged;
}

// balanced pairwise-tree reduction, the exact pairing Python's
// adasum_merge produces: (0,1), (2,3), ... with an odd tail carried up
std::vector<OwnedPart> adasum_merge(std::vector<std::vector<OwnedPart>>& items,
                                    const int64_t* dims) {
  while (items.size() > 1) {
    std::vector<std::vector<OwnedPart>> nxt;
    for (size_t i = 0; i + 1 < items.size(); i += 2)
      nxt.push_back(adasum_pair(items[i], items[i + 1], dims));
    if (items.size() % 2) nxt.push_back(std::move(items.back()));
    items = std::move(nxt);
  }
  return std::move(items[0]);
}

// true when any leaf is carried sparse by one commit and dense by another
// — the combiner applies such batches sequentially (merging would densify
// whole tables), matching Python's _mixed_repr rule
bool mixed_repr(const std::vector<std::vector<OwnedPart>>& commits) {
  for (size_t i = 0; i < commits[0].size(); ++i)
    for (size_t c = 1; c < commits.size(); ++c)
      if (commits[c][i].sparse != commits[0][i].sparse) return true;
  return false;
}

class ParameterServer {
 public:
  // stats() slot layout — runtime/native.py names these; keep in sync
  enum StatSlot {
    S_COMMITS = 0, S_PULLS, S_COMMIT_BYTES, S_PULL_BYTES, S_FENCED,
    S_LIVE_WORKERS, S_IDLE_EVICTIONS, S_CLOCK, S_LOG_DROPPED,
    S_SPARSE_ROWS_PULLED, S_SPARSE_ROWS_COMMITTED, S_SPARSE_WIRE_SAVED,
    S_REPLICAS_CONNECTED, S_REPLICAS_ATTACHED, S_REPLICA_DISCONNECTS,
    S_MERGE_BATCHES, S_MERGED_COMMITS, S_MAX_MERGE_BATCH,
    S_BACKPRESSURE_HINTS, S_REPL_FRAMES, S_PROMOTIONS,
    S_HEALTH_DROPPED, S_IS_STANDBY, S_PROMOTED, S_PROMOTED_AT_CLOCK,
    S_SYNCED, S_REPL_SPARSE_BYTES, S_REPL_SPARSE_SAVED, kStatCount
  };
  static constexpr int kStaleSlots = 64;   // exact small-int histograms
  static constexpr int kStripes = 16;      // apply-lock striping
  static constexpr int64_t kLogCapacity = 8192;
  static constexpr size_t kHealthRingCap = 256;

  ParameterServer(int port, int num_tensors, const int64_t* sizes, int mode,
                  int num_workers, int elastic, int idle_timeout_ms,
                  int num_sparse, const int32_t* sparse_leaves,
                  const int64_t* sparse_dims, int adaptive,
                  int64_t max_payload)
      : requested_port_(port), mode_(mode), num_workers_(num_workers),
        elastic_(elastic != 0), adaptive_(adaptive != 0),
        idle_timeout_ms_(idle_timeout_ms) {
    sizes_.assign(sizes, sizes + num_tensors);
    offsets_.resize(sizes_.size());
    sparse_dim_.assign(sizes_.size(), 0);
    int64_t total = 0;
    for (size_t i = 0; i < sizes_.size(); ++i) {
      offsets_[i] = total;
      total += sizes_[i];
    }
    for (int s = 0; s < num_sparse; ++s) {
      int leaf = int(sparse_leaves[s]);
      sparse_dim_[size_t(leaf)] = sparse_dims[s];
      sparse_leaves_.push_back(leaf);
    }
    for (int leaf : sparse_leaves_) {
      int64_t rows = sizes_[size_t(leaf)] / sparse_dim_[size_t(leaf)];
      sparse_touch_.emplace_back(size_t(rows), 0.0f);
      hot_rows_.push_back(0);
    }
    center_.assign(size_t(total), 0.0f);
    center_bytes_ = total * int64_t(sizeof(float));
    // request bound: passed down from Python (networking.max_request_payload)
    // so both hubs reject the exact same oversized prefixes
    max_payload_ = uint64_t(max_payload);
    // dense frame constants for the sparse wire-savings accounting
    // (mirrors the Python hub's _frame_bytes / _q_payload_bytes)
    dense_payload_f32_ = 5;
    q_payload_bytes_ = 5;
    for (int64_t s : sizes_) {
      dense_payload_f32_ += 8 + 4 * s;
      q_payload_bytes_ += 8 + 4 + s;
    }
    // prebuilt weights-reply skeleton for the writev send path: the
    // 13-byte header (len, 'W', count) + one 8-byte big-endian length
    // prefix per tensor, all constant for a fixed schema
    w_hdr_.resize(13);
    be64_encode(uint64_t(dense_payload_f32_), w_hdr_.data());
    w_hdr_[8] = 'W';
    be32_encode(uint32_t(sizes_.size()), w_hdr_.data() + 9);
    w_prefix_.resize(8 * sizes_.size());
    for (size_t i = 0; i < sizes_.size(); ++i)
      be64_encode(uint64_t(sizes_[i]) * 4, w_prefix_.data() + 8 * i);
  }

  ~ParameterServer() { stop(); }

  // enable the shm attach path: rings for 'Z'-capable clients are created
  // under this directory.  Call before start() (the Python wrapper does)
  void set_shm_dir(const char* dir) { shm_dir_ = dir ? dir : ""; }

  void set_replica_of(const char* host, int port, int retries,
                      int backoff_ms) {
    replica_host_ = host;
    replica_port_ = port;
    replica_retries_ = retries;
    replica_backoff_ms_ = backoff_ms;
    standby_.store(true);
  }

  // returns the bound port, or -1 on failure
  int start() {
    // bind on a local fd, publish into the atomic only once listening:
    // stop() (another thread) shuts the published fd down to wake the
    // accept loop, so the handoff itself must be race-free (TSAN-pinned
    // by the ISSUE-14 stress cell)
    int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (lfd < 0) return -1;
    int one = 1;
    ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(uint16_t(requested_port_));
    if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(lfd, 128) != 0) {
      ::close(lfd);
      return -1;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &len);
    bound_port_ = ntohs(addr.sin_port);
    listen_fd_.store(lfd);
    running_.store(true);
    accept_thread_ = std::thread([this] { accept_loop(); });
    if (replica_port_ >= 0) {
      replica_stop_.store(false);
      replica_thread_ = std::thread([this] { replica_loop(); });
    }
    return bound_port_;
  }

  void stop() {
    // one mutex serializes the WHOLE teardown: concurrent stop() calls
    // (user stop racing a destructor) must not both reach the thread
    // joins — joining the same std::thread twice is UB
    std::lock_guard<std::mutex> stop_guard(stop_mtx_);
    bool was_running = running_.exchange(false);
    if (!was_running && listen_fd_.load() < 0 && !replica_thread_.joinable())
      return;
    replica_stop_.store(true);
    stopped_.store(true);
    int rfd = replica_fd_.load();
    if (rfd >= 0) ::shutdown(rfd, SHUT_RDWR);
    // shutdown ONLY here — shutdown() wakes the blocked accept()
    // (EINVAL) but keeps the descriptor NUMBER reserved, so no accept()
    // call (nor this shutdown) can ever hit a kernel-reused fd.  The
    // close happens below, AFTER the accept thread is joined — the only
    // point where provably nothing references the descriptor.
    int lfd = listen_fd_.load();
    if (lfd >= 0) ::shutdown(lfd, SHUT_RDWR);
    {
      std::lock_guard<std::mutex> g(conn_mutex_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    if (feed_) feed_->close_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    // exchange claims the close exactly once, after the join: the
    // accept loop has exited, so the fd is provably unreferenced
    lfd = listen_fd_.exchange(-1);
    if (lfd >= 0) ::close(lfd);
    if (replica_thread_.joinable()) replica_thread_.join();
    for (auto& t : handler_threads_)
      if (t.joinable()) t.join();
    handler_threads_.clear();
  }

  void get_weights(float* out) {
    std::unique_lock<std::shared_mutex> g(gate_);
    std::memcpy(out, center_.data(), center_.size() * sizeof(float));
  }

  void set_weights(const float* in) {
    std::unique_lock<std::shared_mutex> g(gate_);
    std::memcpy(center_.data(), in, center_.size() * sizeof(float));
  }

  int64_t num_updates() const { return num_updates_.load(); }
  int port() const { return bound_port_; }
  int64_t time_ns() const { return mono_ns(); }

  // restore a hub snapshot: center + commit clock + update count, with the
  // clock FENCE armed at the restored clock (PR-4 restore semantics)
  void restore(const float* flat, int64_t clock, int64_t num_updates) {
    std::unique_lock<std::shared_mutex> g(gate_);
    std::lock_guard<std::mutex> m(meta_);
    std::memcpy(center_.data(), flat, center_.size() * sizeof(float));
    clock_ = clock;
    clock_fence_ = clock;
    num_updates_.store(num_updates);
  }

  // -- standby surface (replica_of; mirrors SocketParameterServer) -----------
  bool is_standby() const { return standby_.load(); }
  bool promoted() const { return promoted_flag_.load(); }

  int64_t promoted_at_clock() {
    std::lock_guard<std::mutex> m(meta_);
    return promoted_at_clock_;
  }

  bool wait_synced(int64_t timeout_ms) {
    // bounded poll on the sync/stop atomics.  This was a condvar, but
    // libstdc++'s wait_for lowers to pthread_cond_clockwait, which
    // gcc-10-era libtsan does not intercept — every TSAN run read the
    // wakeup as a phantom double-lock.  wait_synced is a once-per-attach
    // latency path, so millisecond polling granularity costs nothing
    // and keeps the hub condvar-free.
    const auto deadline = std::chrono::steady_clock::now() +
                          std::chrono::milliseconds(timeout_ms);
    while (!synced_.load() && !stopped_.load()) {
      if (timeout_ms >= 0 && std::chrono::steady_clock::now() >= deadline)
        break;
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
    return synced_.load();
  }

  // promote a standby: arm the clock fence at the replicated clock and
  // stop applying feed frames forever.  Idempotent; true if we promoted.
  bool promote() {
    {
      std::lock_guard<std::mutex> m(meta_);
      if (!standby_.load() || promoted_flag_.load()) return false;
      promoted_flag_.store(true);
      standby_.store(false);
      clock_fence_ = clock_;
      promoted_at_clock_ = clock_;
      ++promotions_;
    }
    replica_stop_.store(true);
    int fd = replica_fd_.load();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
    return true;
  }

  // -- in-process transport (transport="inproc") ------------------------------
  int64_t pull_direct(float* out) {
    std::unique_lock<std::shared_mutex> g(gate_);
    int64_t clock;
    {
      std::lock_guard<std::mutex> m(meta_);
      clock = clock_;
      ++pulls_;
      pull_bytes_ += center_bytes_;
    }
    std::memcpy(out, center_.data(), center_.size() * sizeof(float));
    return clock;
  }

  // pull_direct minus the telemetry (HubSnapshotter's uncounted read)
  int64_t snapshot_direct(float* out) {
    std::unique_lock<std::shared_mutex> g(gate_);
    std::lock_guard<std::mutex> m(meta_);
    std::memcpy(out, center_.data(), center_.size() * sizeof(float));
    return clock_;
  }

  // 0 = applied; 1 = refused (never-synced standby); 2 = refused (standby
  // probing a connected primary) — runtime/native.py raises on nonzero,
  // matching the Python hub's commit_direct standby errors
  int commit_direct(const float* flat, int64_t last_pull_clock,
                    int64_t worker = -1) {
    if (standby_.load()) {
      if (!synced_.load()) return 1;
      int fd = replica_fd_.load();
      if (fd >= 0) {
        ::shutdown(fd, SHUT_RDWR);
        return 2;
      }
      promote();  // feed down: its owner considers this the live hub
    }
    std::vector<PartView> parts(sizes_.size());
    const float* p = flat;
    for (size_t i = 0; i < sizes_.size(); ++i) {
      parts[i].vals = p;
      p += sizes_[i];
    }
    commit_parts(parts, &last_pull_clock, worker, center_bytes_, 0, 0);
    return 0;
  }

  // -- sparse in-process transport (ISSUE 15) ---------------------------------
  // pull_sparse_direct minus the frame (the S/V exchange): ``ids`` is the
  // concatenation of each sparse table's sorted-unique row ids
  // (``counts[s]`` per table, sparse_leaves_ order); ``out`` receives the
  // per-leaf values in template order — dense leaves whole, sparse
  // leaves their [k, dim] row blocks.  Returns the snapshot clock,
  // -1 = never-synced standby refusal, -2 = invalid row ids.
  int64_t pull_sparse_direct(const int64_t* ids, const int64_t* counts,
                             float* out) {
    if (standby_.load() && !synced_.load()) return -1;
    {
      const int64_t* p = ids;
      for (size_t s = 0; s < sparse_leaves_.size(); ++s) {
        if (!check_row_ids(p, counts[s], size_t(sparse_leaves_[s])))
          return -2;
        p += counts[s];
      }
    }
    std::unique_lock<std::shared_mutex> g(gate_);
    const int64_t* ip = ids;
    float* op = out;
    int64_t rows_pulled = 0, raw = 0;
    size_t s = 0;
    for (size_t i = 0; i < sizes_.size(); ++i) {
      const float* c = center_.data() + offsets_[i];
      if (sparse_dim_[i] > 0) {
        int64_t dim = sparse_dim_[i];
        int64_t k = counts[s];
        for (int64_t r = 0; r < k; ++r)
          std::memcpy(op + r * dim, c + ip[r] * dim, size_t(dim) * 4);
        ip += k;
        op += k * dim;
        rows_pulled += k;
        raw += k * dim * 4;
        ++s;
      } else {
        std::memcpy(op, c, size_t(sizes_[i]) * 4);
        op += sizes_[i];
        raw += sizes_[i] * 4;
      }
    }
    int64_t clock;
    {
      std::lock_guard<std::mutex> m(meta_);
      clock = clock_;
      ++pulls_;
      pull_bytes_ += raw;
      sparse_rows_pulled_ += rows_pulled;
      {
        const int64_t* tp = ids;
        for (size_t t = 0; t < sparse_leaves_.size(); ++t) {
          touch_ids_locked(t, tp, counts[t]);
          tp += counts[t];
        }
        fold_touch_locked();
      }
    }
    return clock;
  }

  // commit_sparse_direct minus the frame (the U exchange): ``vals`` is
  // the concatenation of per-leaf payloads in template order (full f32
  // delta for dense leaves, [k, dim] row grads for sparse ones), ids/
  // counts as in pull_sparse_direct.  0 = applied, 1 = refused (never-
  // synced standby), 2 = refused (standby probing a live primary),
  // 3 = invalid row ids — runtime/native.py raises on nonzero.
  int commit_sparse_direct(const float* vals, const int64_t* ids,
                           const int64_t* counts, int64_t last_pull_clock,
                           int64_t worker) {
    std::vector<PartView> parts(sizes_.size());
    const float* vp = vals;
    const int64_t* ip = ids;
    size_t s = 0;
    int64_t rows = 0, raw = 0;
    for (size_t i = 0; i < sizes_.size(); ++i) {
      if (sparse_dim_[i] > 0) {
        int64_t k = counts[s];
        if (!check_row_ids(ip, k, i)) return 3;
        parts[i].sparse = true;
        parts[i].ids = ip;
        parts[i].k = k;
        parts[i].vals = vp;
        ip += k;
        vp += k * sparse_dim_[i];
        rows += k;
        raw += k * (8 + sparse_dim_[i] * 4);
        ++s;
      } else {
        parts[i].vals = vp;
        vp += sizes_[i];
        raw += sizes_[i] * 4;
      }
    }
    if (standby_.load()) {
      if (!synced_.load()) return 1;
      int fd = replica_fd_.load();
      if (fd >= 0) {
        ::shutdown(fd, SHUT_RDWR);
        return 2;
      }
      promote();  // feed down: its owner considers this the live hub
    }
    commit_parts(parts, &last_pull_clock, worker, raw, rows, 0);
    return 0;
  }

  // -- telemetry exports ------------------------------------------------------
  void stats(int64_t out[kStatCount]) {
    std::lock_guard<std::mutex> m(meta_);
    out[S_COMMITS] = commits_;
    out[S_PULLS] = pulls_;
    out[S_COMMIT_BYTES] = commit_bytes_;
    out[S_PULL_BYTES] = pull_bytes_;
    out[S_FENCED] = fenced_commits_;
    out[S_LIVE_WORKERS] = live_members_;
    out[S_IDLE_EVICTIONS] = idle_evictions_;
    out[S_CLOCK] = clock_;
    out[S_LOG_DROPPED] = log_dropped_;
    out[S_SPARSE_ROWS_PULLED] = sparse_rows_pulled_;
    out[S_SPARSE_ROWS_COMMITTED] = sparse_rows_committed_;
    out[S_SPARSE_WIRE_SAVED] = sparse_wire_saved_;
    out[S_REPLICAS_CONNECTED] = feed_ ? feed_->count_.load() : 0;
    out[S_REPLICAS_ATTACHED] = replicas_attached_;
    out[S_REPLICA_DISCONNECTS] = replica_disconnects_;
    out[S_MERGE_BATCHES] = merge_batches_;
    out[S_MERGED_COMMITS] = merged_commits_;
    out[S_MAX_MERGE_BATCH] = max_merge_batch_;
    out[S_BACKPRESSURE_HINTS] = backpressure_hints_;
    out[S_REPL_FRAMES] = repl_frames_;
    out[S_PROMOTIONS] = promotions_;
    out[S_HEALTH_DROPPED] = health_dropped_;
    out[S_IS_STANDBY] = standby_.load() ? 1 : 0;
    out[S_PROMOTED] = promoted_flag_.load() ? 1 : 0;
    out[S_PROMOTED_AT_CLOCK] = promoted_at_clock_;
    out[S_SYNCED] = synced_.load() ? 1 : 0;
    out[S_REPL_SPARSE_BYTES] = repl_sparse_bytes_;
    out[S_REPL_SPARSE_SAVED] = repl_sparse_saved_;
  }

  // decayed per-table hot-set estimates (one int64 per sparse leaf, in
  // sparse_leaves_ order) — dk_ps_hot_rows
  void hot_rows(int64_t* out) {
    std::lock_guard<std::mutex> m(meta_);
    for (size_t s = 0; s < hot_rows_.size(); ++s) out[s] = hot_rows_[s];
  }

  void staleness_hist(int64_t out[kStaleSlots + 1]) {
    std::lock_guard<std::mutex> m(meta_);
    std::memcpy(out, stale_hist_, sizeof(stale_hist_));
  }

  void merge_hist(int64_t out[kStaleSlots + 1]) {
    std::lock_guard<std::mutex> m(meta_);
    std::memcpy(out, merge_hist_, sizeof(merge_hist_));
  }

  int64_t drain_commits(int64_t* out, int64_t max_records) {
    std::lock_guard<std::mutex> m(meta_);
    int64_t n = 0;
    while (n < max_records && log_count_ > 0) {
      const CommitRecord& r = commit_log_[size_t(log_head_)];
      out[n * 5 + 0] = r.clock;
      out[n * 5 + 1] = r.worker;
      out[n * 5 + 2] = r.staleness;
      out[n * 5 + 3] = r.t_ns;
      out[n * 5 + 4] = r.dur_ns;
      log_head_ = (log_head_ + 1) % kLogCapacity;
      --log_count_;
      ++n;
    }
    return n;
  }

  // pop one parked health report (action 'M' payload) into out; returns
  // its length, 0 when the ring is empty, -1 when it exceeded cap (the
  // report is dropped and counted — never silently wedged)
  int64_t next_health(unsigned char* out, int64_t cap) {
    std::lock_guard<std::mutex> m(meta_);
    if (health_ring_.empty()) return 0;
    std::string& front = health_ring_.front();
    if (int64_t(front.size()) > cap) {
      health_ring_.pop_front();
      ++health_dropped_;
      return -1;
    }
    int64_t n = int64_t(front.size());
    std::memcpy(out, front.data(), front.size());
    health_ring_.pop_front();
    return n;
  }

  // -- adaptive controls (driven from runtime/native.py) ----------------------
  // per-worker multiplicative commit scale with an expiry deadline: the
  // Python-side AdaptiveRateController pushes its verdicts here, and an
  // expired verdict reads as 1.0 — so a dead controller can never pin a
  // worker's scale forever
  void set_rate_scale(int64_t worker, double scale, int64_t expires_ns) {
    std::lock_guard<std::mutex> g(rate_mtx_);
    rate_scales_[worker] = {scale, expires_ns};
  }

  void set_storm_params(int hellos, int window_ms, int shed_ms, int base_ms,
                        int cap_ms) {
    std::lock_guard<std::mutex> g(bp_mtx_);
    storm_hellos_ = hellos;
    storm_window_ns_ = int64_t(window_ms) * 1000000;
    storm_shed_ns_ = int64_t(shed_ms) * 1000000;
    retry_base_ms_ = base_ms;
    retry_cap_ms_ = cap_ms;
  }

  // arm shedding from an external storm verdict (the Python wrapper's
  // health-monitor subscription), mirroring the hub's _on_health_event
  void arm_storm() {
    std::lock_guard<std::mutex> g(bp_mtx_);
    int64_t now = mono_ns();
    if (now >= storm_until_ns_) retry_seq_ = 0;
    storm_until_ns_ = std::max(storm_until_ns_, now + storm_shed_ns_);
  }

 private:
  struct CommitRecord {
    int64_t clock, worker, staleness, t_ns, dur_ns;
  };

  // one queued adaptive commit: the submitter's stack owns it, the drain
  // winner fills in the verdict fields before releasing the drain lock
  // (the Python combiner's entry dict, minus the dict)
  struct CommitEntry {
    const std::vector<PartView>* parts;
    int64_t lpc, worker, wire_bytes, rows_committed, wire_saved;
    int64_t staleness = 0, rebased_lpc = 0;
    bool done = false;
  };

  // -- replication feed (primary side; Python's ReplicationFeed twin) --------
  // attach full-syncs under the write gate, publish streams one R delta
  // frame per applied commit BEFORE the worker's ack leaves.  A replica's
  // immutable attach-time sync clock filters deltas its sync covered;
  // its attach-time hello capability decides which frame KINDS it is
  // ever sent — row-sparse commits go to kReplCapSparse replicas as one
  // kReplSparse row-delta frame (cost ∝ touched rows) and to legacy
  // replicas as the dense-materialized kReplDelta.
  struct ReplFeed {
    explicit ReplFeed(ParameterServer* hub) : hub(hub) {}
    ParameterServer* hub;
    std::mutex lock_;  // serializes attach + publish (Python's feed lock)
    struct Rep {
      int fd;
      int64_t sync_clock;
      bool sparse_ok;
    };
    std::vector<Rep> conns_;
    std::atomic<int> count_{0};
    // legacy (dense-only) replicas attached: the commit path reads this
    // lock-free to decide whether a sparse commit must ALSO materialize
    // the center-shaped delta.  Racy by design: a legacy replica
    // attaching concurrently snapshots the center AFTER the commit
    // applied, so its sync clock covers the commit either way
    std::atomic<int> dense_count_{0};
    std::vector<unsigned char> tx_;
    std::vector<unsigned char> sp_tx_;
    std::vector<float> fb_dense_;  // densify-on-demand scratch (lock_)

    // what a kReplSparse frame is packed from: the plain path's wire
    // views (scaled while packing — the same `scale * g` float product
    // the apply computed) or the adaptive path's pre-scaled owned parts
    struct SparseSrc {
      const std::vector<PartView>* views = nullptr;
      const std::vector<OwnedPart>* owned = nullptr;
      float scale = 1.0f;
    };

    // frame: [u64 len][R][u32 1+L][u64 9][9-byte hdr][per leaf u64+f32s]
    void pack_frame(int64_t clock, int kind, const float* flat) {
      size_t payload = 5 + 8 + 9;
      for (int64_t s : hub->sizes_) payload += 8 + size_t(s) * 4;
      tx_.resize(8 + payload);
      unsigned char* p = tx_.data();
      be64_encode(payload, p);
      p[8] = 'R';
      be32_encode(uint32_t(1 + hub->sizes_.size()), p + 9);
      p += 13;
      be64_encode(9, p);
      p += 8;
      be64_encode(uint64_t(clock), p);
      p[8] = (unsigned char)kind;
      p += 9;
      for (size_t i = 0; i < hub->sizes_.size(); ++i) {
        uint64_t nbytes = uint64_t(hub->sizes_[i]) * 4;
        be64_encode(nbytes, p);
        p += 8;
        std::memcpy(p, flat + hub->offsets_[i], nbytes);
        p += nbytes;
      }
    }

    // row-delta frame (kReplSparse): header blob + the U-commit layout —
    // dense leaves whole, sparse leaves as (ids, scaled rows)
    void pack_sparse(int64_t clock, const SparseSrc& sp) {
      const auto& sizes = hub->sizes_;
      const auto& dims = hub->sparse_dim_;
      size_t payload = 5 + 8 + 9;
      for (size_t i = 0; i < sizes.size(); ++i) {
        int64_t k = sp.views ? ((*sp.views)[i].sparse ? (*sp.views)[i].k : -1)
                             : ((*sp.owned)[i].sparse
                                    ? int64_t((*sp.owned)[i].ids.size())
                                    : -1);
        if (k >= 0)
          payload += 8 + size_t(k) * 8 + 8 + size_t(k * dims[i]) * 4;
        else
          payload += 8 + size_t(sizes[i]) * 4;
      }
      sp_tx_.resize(8 + payload);
      unsigned char* p = sp_tx_.data();
      be64_encode(payload, p);
      p[8] = 'R';
      be32_encode(uint32_t(1 + sizes.size() + hub->sparse_leaves_.size()),
                  p + 9);
      p += 13;
      be64_encode(9, p);
      p += 8;
      be64_encode(uint64_t(clock), p);
      p[8] = (unsigned char)kReplSparse;
      p += 9;
      for (size_t i = 0; i < sizes.size(); ++i) {
        bool sparse = sp.views ? (*sp.views)[i].sparse
                               : (*sp.owned)[i].sparse;
        const int64_t* ids = nullptr;
        const float* vals;
        int64_t k = 0, nvals;
        if (sp.views) {
          const PartView& v = (*sp.views)[i];
          ids = v.ids;
          vals = v.vals;
          k = v.k;
          nvals = sparse ? k * dims[i] : sizes[i];
        } else {
          const OwnedPart& o = (*sp.owned)[i];
          ids = o.ids.data();
          vals = o.vals.data();
          k = int64_t(o.ids.size());
          nvals = int64_t(o.vals.size());
        }
        if (sparse) {
          be64_encode(uint64_t(k) * 8, p);
          p += 8;
          std::memcpy(p, ids, size_t(k) * 8);
          p += size_t(k) * 8;
        }
        be64_encode(uint64_t(nvals) * 4, p);
        p += 8;
        float* out = reinterpret_cast<float*>(p);
        if (sp.scale == 1.0f)
          std::memcpy(out, vals, size_t(nvals) * 4);
        else
          for (int64_t j = 0; j < nvals; ++j) out[j] = sp.scale * vals[j];
        p += size_t(nvals) * 4;
      }
    }

    bool attach(int fd, int caps) {
      timeval tv{30, 0};  // REPLICA_SEND_TIMEOUT: a stuck replica must
      ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));  // not
      std::lock_guard<std::mutex> l(lock_);  // park the commit plane
      int64_t clock;
      {
        // pack the center STRAIGHT into the sync frame under the write
        // gate (registration-before-snapshot is implicit here: publish
        // serializes behind this very lock, so no commit applying after
        // our snapshot can be acked before its delta is offered to us)
        std::unique_lock<std::shared_mutex> g(hub->gate_);
        {
          std::lock_guard<std::mutex> m(hub->meta_);
          clock = hub->clock_;
        }
        pack_frame(clock, kReplSync, hub->center_.data());
      }
      if (!write_all(fd, tx_.data(), tx_.size())) {
        ::close(fd);
        return false;
      }
      bool sparse_ok = (caps & kReplCapSparse) != 0;
      conns_.push_back({fd, clock, sparse_ok});
      count_.store(int(conns_.size()));
      if (!sparse_ok) dense_count_.fetch_add(1);
      {
        std::lock_guard<std::mutex> m(hub->meta_);
        ++hub->replicas_attached_;
      }
      return true;
    }

    // `dense` may be nullptr when the commit path observed only
    // sparse-capable replicas (then `sp` must be set); a LEGACY replica
    // whose attach raced that lock-free check is still served here by
    // densifying on demand under the feed lock — the Python feed's
    // exact contract (its registered sync clock snapshots BEFORE this
    // commit applied, so skipping it would lose the delta forever)
    void publish(int64_t clock, const float* dense,
                 const SparseSrc* sp = nullptr) {
      std::lock_guard<std::mutex> l(lock_);
      if (conns_.empty()) return;
      bool packed = false, sp_packed = false;
      std::vector<size_t> dead;
      for (size_t i = 0; i < conns_.size(); ++i) {
        if (conns_[i].sync_clock >= clock) continue;  // covered by sync
        bool ok;
        if (sp != nullptr && conns_[i].sparse_ok) {
          if (!sp_packed) {
            pack_sparse(clock, *sp);
            sp_packed = true;
          }
          ok = write_all(conns_[i].fd, sp_tx_.data(), sp_tx_.size());
          if (ok) {
            std::lock_guard<std::mutex> m(hub->meta_);
            hub->repl_sparse_bytes_ += int64_t(sp_tx_.size());
            int64_t dense_frame = 8 + 5 + 8 + 9;
            for (int64_t s : hub->sizes_) dense_frame += 8 + s * 4;
            int64_t saved = dense_frame - int64_t(sp_tx_.size());
            if (saved > 0) hub->repl_sparse_saved_ += saved;
          }
        } else {
          if (dense == nullptr) {
            // densify-on-demand: scatter the scaled sparse parts into a
            // center-shaped scratch (materialize_* take no locks; the
            // commit's part views stay valid — publish is synchronous
            // within the committing call)
            fb_dense_.assign(hub->center_.size(), 0.0f);
            if (sp->views != nullptr)
              hub->materialize_views(*sp->views, sp->scale,
                                     fb_dense_.data());
            else
              hub->materialize_owned(*sp->owned, fb_dense_.data());
            dense = fb_dense_.data();
          }
          if (!packed) {
            pack_frame(clock, kReplDelta, dense);
            packed = true;
          }
          ok = write_all(conns_[i].fd, tx_.data(), tx_.size());
        }
        if (!ok) dead.push_back(i);
      }
      for (size_t d = dead.size(); d > 0; --d) {
        size_t i = dead[d - 1];
        ::close(conns_[i].fd);
        if (!conns_[i].sparse_ok) dense_count_.fetch_sub(1);
        conns_.erase(conns_.begin() + long(i));
        std::lock_guard<std::mutex> m(hub->meta_);
        ++hub->replica_disconnects_;
      }
      count_.store(int(conns_.size()));
    }

    void close_all() {
      std::lock_guard<std::mutex> l(lock_);
      for (auto& r : conns_) {
        ::shutdown(r.fd, SHUT_RDWR);
        ::close(r.fd);
      }
      conns_.clear();
      count_.store(0);
      dense_count_.store(0);
    }
  };

  // -- scaling rules ----------------------------------------------------------
  // the scalar a commit is multiplied by, in double (cast to float32 at
  // the apply — `np.float32(commit_scale(staleness))` exactly).  Caller
  // holds meta_ (live_members_)
  double commit_scale_locked(int64_t staleness) {
    if (mode_ == 1) {
      int n = num_workers_;
      if (elastic_) {
        n = live_members_;
        if (n < 1) n = num_workers_;
        if (n > num_workers_) n = num_workers_;
      }
      return 1.0 / double(n);
    }
    if (mode_ == 2) return 1.0 / double(staleness + 1);
    return 1.0;
  }

  // the live per-worker adaptive rate (1.0 when unknown/expired/uncontexted)
  double rate_scale(int64_t worker) {
    if (worker < 0 || !adaptive_) return 1.0;
    std::lock_guard<std::mutex> g(rate_mtx_);
    auto it = rate_scales_.find(worker);
    if (it == rate_scales_.end()) return 1.0;
    if (mono_ns() >= it->second.second) {
      rate_scales_.erase(it);
      return 1.0;
    }
    return it->second.first;
  }

  // -- row-touch telemetry (ISSUE 15; caller holds meta_) ---------------------
  // per-table exponentially-decayed touch counters: +1 per touched row
  // per sparse request, halved every kTouchDecayEvery folds; rows still
  // >= 1 then estimate the live hot set (dk_ps_hot_rows — the wrapper
  // surfaces them as ps.sparse_hot_rows{table=} gauges)
  static constexpr int kTouchDecayEvery = 64;

  void fold_touch_locked() {
    if (++touch_folds_ < kTouchDecayEvery) return;
    touch_folds_ = 0;
    for (size_t s = 0; s < sparse_touch_.size(); ++s) {
      int64_t hot = 0;
      for (float& v : sparse_touch_[s]) {
        v *= 0.5f;
        if (v >= 1.0f) ++hot;
      }
      hot_rows_[s] = hot;
    }
  }

  void touch_ids_locked(size_t table, const int64_t* ids, int64_t k) {
    auto& t = sparse_touch_[table];
    for (int64_t r = 0; r < k; ++r) t[size_t(ids[r])] += 1.0f;
  }

  void touch_rows_locked(const std::vector<PartView>& parts) {
    bool any = false;
    size_t s = 0;
    for (size_t i = 0; i < parts.size(); ++i) {
      if (sparse_dim_[i] <= 0) continue;
      if (parts[i].sparse) {
        touch_ids_locked(s, parts[i].ids, parts[i].k);
        any = true;
      }
      ++s;
    }
    if (any) fold_touch_locked();
  }

  // caller holds meta_: one commit-log record + the exact staleness count.
  // `clock` is the commit's OWN post-increment clock, captured in the
  // critical section that advanced it — re-reading clock_ here would
  // misattribute records under concurrent commits
  void record_commit_locked(int64_t clock, int64_t worker, int64_t staleness,
                            int64_t t0_ns, int64_t dur_ns) {
    ++commits_;
    int slot = staleness < 0 ? 0
               : (staleness >= kStaleSlots ? kStaleSlots : int(staleness));
    ++stale_hist_[slot];
    CommitRecord r{clock, worker, staleness, t0_ns, dur_ns};
    size_t idx = size_t((log_head_ + log_count_) % kLogCapacity);
    commit_log_[idx] = r;
    if (log_count_ == kLogCapacity) {
      log_head_ = (log_head_ + 1) % kLogCapacity;
      ++log_dropped_;
    } else {
      ++log_count_;
    }
  }

  // -- apply primitives (stripe-locked) ---------------------------------------
  // scale*delta added leaf by leaf under that leaf's stripe lock: two
  // commits touching different leaves apply concurrently, same-leaf adds
  // serialize (adds commute, so order is the async-SGD tolerance class)
  void apply_views(const std::vector<PartView>& parts, float scale) {
    for (size_t i = 0; i < parts.size(); ++i) {
      std::lock_guard<std::mutex> s(stripes_[i % kStripes]);
      float* c = center_.data() + offsets_[i];
      const PartView& p = parts[i];
      if (p.sparse) {
        int64_t dim = sparse_dim_[i];
        for (int64_t r = 0; r < p.k; ++r) {
          float* row = c + p.ids[r] * dim;
          const float* g = p.vals + r * dim;
          for (int64_t j = 0; j < dim; ++j) row[j] += scale * g[j];
        }
      } else {
        for (int64_t j = 0; j < sizes_[i]; ++j) c[j] += scale * p.vals[j];
      }
    }
  }

  void apply_owned(const std::vector<OwnedPart>& parts) {
    for (size_t i = 0; i < parts.size(); ++i) {
      std::lock_guard<std::mutex> s(stripes_[i % kStripes]);
      float* c = center_.data() + offsets_[i];
      const OwnedPart& p = parts[i];
      if (p.sparse) {
        int64_t dim = sparse_dim_[i];
        for (size_t r = 0; r < p.ids.size(); ++r) {
          float* row = c + p.ids[r] * dim;
          const float* g = p.vals.data() + int64_t(r) * dim;
          for (int64_t j = 0; j < dim; ++j) row[j] += g[j];
        }
      } else {
        for (int64_t j = 0; j < sizes_[i]; ++j) c[j] += p.vals[j];
      }
    }
  }

  // center += flat (the replicated path: the center applies EXACTLY the
  // bytes the R frame carries, so primary and replica perform identical
  // float additions — Python's `c += full` materialized-delta idiom)
  void add_from_flat(const float* flat) {
    for (size_t i = 0; i < sizes_.size(); ++i) {
      std::lock_guard<std::mutex> s(stripes_[i % kStripes]);
      float* c = center_.data() + offsets_[i];
      const float* d = flat + offsets_[i];
      for (int64_t j = 0; j < sizes_[i]; ++j) c[j] += d[j];
    }
  }

  // scaled center-shaped materialization of one commit (replication)
  void materialize_views(const std::vector<PartView>& parts, float scale,
                         float* flat) {
    for (size_t i = 0; i < parts.size(); ++i) {
      float* d = flat + offsets_[i];
      const PartView& p = parts[i];
      if (p.sparse) {
        int64_t dim = sparse_dim_[i];
        for (int64_t r = 0; r < p.k; ++r) {
          float* row = d + p.ids[r] * dim;
          const float* g = p.vals + r * dim;
          for (int64_t j = 0; j < dim; ++j) row[j] += scale * g[j];
        }
      } else {
        for (int64_t j = 0; j < sizes_[i]; ++j) d[j] += scale * p.vals[j];
      }
    }
  }

  void materialize_owned(const std::vector<OwnedPart>& parts, float* flat) {
    for (size_t i = 0; i < parts.size(); ++i) {
      float* d = flat + offsets_[i];
      const OwnedPart& p = parts[i];
      if (p.sparse) {
        int64_t dim = sparse_dim_[i];
        for (size_t r = 0; r < p.ids.size(); ++r) {
          float* row = d + p.ids[r] * dim;
          const float* g = p.vals.data() + int64_t(r) * dim;
          for (int64_t j = 0; j < dim; ++j) row[j] += g[j];
        }
      } else {
        for (int64_t j = 0; j < sizes_[i]; ++j) d[j] += p.vals[j];
      }
    }
  }

  // -- the ONE commit dispatch (plain or adaptive) ----------------------------
  void commit_parts(const std::vector<PartView>& parts,
                    int64_t* last_pull_clock, int64_t worker,
                    int64_t wire_bytes, int64_t rows_committed,
                    int64_t wire_saved) {
    if (adaptive_) {
      CommitEntry entry{&parts, *last_pull_clock, worker, wire_bytes,
                        rows_committed, wire_saved};
      commit_adaptive(entry);
      *last_pull_clock = entry.rebased_lpc;
      return;
    }
    std::shared_lock<std::shared_mutex> g(gate_);
    bool replicate;
    int64_t staleness, commit_clock;
    double dscale;
    {
      std::lock_guard<std::mutex> m(meta_);
      if (*last_pull_clock < clock_fence_) {
        *last_pull_clock = clock_fence_;
        ++fenced_commits_;
      }
      staleness = clock_ - *last_pull_clock;
      dscale = commit_scale_locked(staleness);
      replicate = feed_ && feed_->count_.load() > 0;
      ++clock_;
      commit_clock = clock_;
    }
    float scale = float(dscale);
    int64_t t0 = mono_ns();
    bool sparse_commit = false;
    for (const PartView& p : parts)
      if (p.sparse) {
        sparse_commit = true;
        break;
      }
    // a row-sparse commit applies in its native form (touched rows only,
    // the Python hub's idiom) and is FRAMED sparse for capable replicas;
    // the center-shaped materialization now exists only when a legacy
    // (dense-stream) replica is actually attached.  Dense commits keep
    // the pre-ISSUE-15 path byte for byte
    bool need_dense =
        replicate && (!sparse_commit || feed_->dense_count_.load() > 0);
    std::vector<float> repl;
    if (sparse_commit) {
      apply_views(parts, scale);
      if (need_dense) {
        repl.assign(center_.size(), 0.0f);
        materialize_views(parts, scale, repl.data());
      }
    } else if (replicate) {
      repl.assign(center_.size(), 0.0f);
      materialize_views(parts, scale, repl.data());
      add_from_flat(repl.data());
    } else {
      apply_views(parts, scale);
    }
    int64_t dur = mono_ns() - t0;
    {
      std::lock_guard<std::mutex> m(meta_);
      record_commit_locked(commit_clock, worker, staleness, t0, dur);
      commit_bytes_ += wire_bytes;
      sparse_rows_committed_ += rows_committed;
      sparse_wire_saved_ += wire_saved;
      if (sparse_commit)
        touch_rows_locked(parts);
    }
    g.unlock();
    // the ack leaves only after this returns — the acked-commit-is-
    // kernel-owned replication contract (publish before ack)
    if (replicate) {
      ReplFeed::SparseSrc sp;
      if (sparse_commit) {
        sp.views = &parts;
        sp.scale = scale;
      }
      feed_->publish(commit_clock, need_dense ? repl.data() : nullptr,
                     sparse_commit ? &sp : nullptr);
    }
    num_updates_.fetch_add(1);
  }

  // flat-combining submit: enqueue, race for the drain lock, the winner
  // takes everything queued as one batch (Python _AdaptiveCombiner.commit)
  void commit_adaptive(CommitEntry& entry) {
    {
      std::lock_guard<std::mutex> q(comb_qlock_);
      comb_queue_.push_back(&entry);
    }
    std::lock_guard<std::mutex> d(comb_drain_);
    if (entry.done) return;  // a predecessor's batch already applied us
    std::vector<CommitEntry*> batch;
    {
      std::lock_guard<std::mutex> q(comb_qlock_);
      batch.swap(comb_queue_);
    }
    apply_batch(batch);
  }

  void apply_batch(std::vector<CommitEntry*>& batch) {
    std::shared_lock<std::shared_mutex> g(gate_);
    size_t K = batch.size();
    bool replicate;
    int64_t clock0, commit_clock;
    std::vector<double> dscales(K);
    int64_t t0 = mono_ns();
    {
      std::lock_guard<std::mutex> m(meta_);
      replicate = feed_ && feed_->count_.load() > 0;
      clock0 = clock_;
      for (size_t i = 0; i < K; ++i) {
        CommitEntry* e = batch[i];
        int64_t lpc = e->lpc;
        if (lpc < clock_fence_) {
          lpc = clock_fence_;
          ++fenced_commits_;
        }
        e->rebased_lpc = lpc;
        e->staleness = clock0 - lpc;
        dscales[i] = commit_scale_locked(e->staleness) * rate_scale(e->worker);
      }
      // a batch of K still advances the clock by K: staleness
      // bookkeeping, elastic denominators and the failover bound keep
      // their meaning (all members see the same base clock)
      clock_ += int64_t(K);
      commit_clock = clock_;
      ++merge_batches_;
      if (int64_t(K) > max_merge_batch_) max_merge_batch_ = int64_t(K);
      if (K > 1) merged_commits_ += int64_t(K) - 1;
      int slot = K >= size_t(kStaleSlots) ? kStaleSlots : int(K);
      ++merge_hist_[slot];
    }
    // scale each member by its own commit_scale x adaptive rate (owned
    // copies — the submitters' views alias their receive buffers)
    std::vector<std::vector<OwnedPart>> scaled(K);
    for (size_t i = 0; i < K; ++i) {
      const std::vector<PartView>& src = *batch[i]->parts;
      float fs = float(dscales[i]);
      scaled[i].resize(src.size());
      for (size_t l = 0; l < src.size(); ++l) {
        OwnedPart& o = scaled[i][l];
        o.sparse = src[l].sparse;
        if (o.sparse) {
          o.ids.assign(src[l].ids, src[l].ids + src[l].k);
          int64_t nv = src[l].k * sparse_dim_[l];
          o.vals.resize(size_t(nv));
          for (int64_t j = 0; j < nv; ++j) o.vals[j] = fs * src[l].vals[j];
        } else {
          o.vals.resize(size_t(sizes_[l]));
          for (int64_t j = 0; j < sizes_[l]; ++j)
            o.vals[j] = fs * src[l].vals[j];
        }
      }
    }
    // one Adasum tree merge for the batch — or sequential application for
    // a batch of one and the RARE mixed dense/sparse batch (merging the
    // latter would densify whole tables under the apply)
    std::vector<std::vector<OwnedPart>> applied;
    if (K > 1 && !mixed_repr(scaled)) {
      applied.push_back(adasum_merge(scaled, sparse_dim_.data()));
    } else {
      applied = std::move(scaled);
    }
    // ONE applied commit (uncontended, or the whole batch Adasum-merged)
    // that carries row-sparse parts applies sparse and streams as a
    // kReplSparse row-union frame; the dense materialization exists only
    // for the RARE sequential batch or an attached legacy replica
    bool sparse_single = false;
    if (applied.size() == 1)
      for (const OwnedPart& p : applied[0])
        if (p.sparse) {
          sparse_single = true;
          break;
        }
    bool need_dense =
        replicate && (!sparse_single || feed_->dense_count_.load() > 0);
    std::vector<float> repl;
    if (sparse_single) {
      apply_owned(applied[0]);
      if (need_dense) {
        repl.assign(center_.size(), 0.0f);
        materialize_owned(applied[0], repl.data());
      }
    } else if (replicate) {
      repl.assign(center_.size(), 0.0f);
      for (const auto& parts : applied) materialize_owned(parts, repl.data());
      add_from_flat(repl.data());
    } else {
      for (const auto& parts : applied) apply_owned(parts);
    }
    int64_t dur = mono_ns() - t0;
    {
      std::lock_guard<std::mutex> m(meta_);
      for (CommitEntry* e : batch) {
        record_commit_locked(commit_clock, e->worker, e->staleness, t0, dur);
        commit_bytes_ += e->wire_bytes;
        sparse_rows_committed_ += e->rows_committed;
        sparse_wire_saved_ += e->wire_saved;
        touch_rows_locked(*e->parts);
      }
    }
    g.unlock();
    // ONE R frame for the whole batch at its final clock, before any
    // member is acked.  Like the Python hub, publish happens after the
    // apply lock is released: cross-thread publish-order inversions only
    // reorder float additions (the feed's documented tolerance class)
    if (replicate) {
      ReplFeed::SparseSrc sp;
      if (sparse_single) sp.owned = &applied[0];
      feed_->publish(commit_clock, need_dense ? repl.data() : nullptr,
                     sparse_single ? &sp : nullptr);
    }
    num_updates_.fetch_add(int64_t(K));
    for (CommitEntry* e : batch) e->done = true;
  }

  // -- standby (replica_of) ---------------------------------------------------
  // wire-side split-brain guard: 0 = proceed (possibly just promoted),
  // 1 = drop the connection (commit refused).  Mirrors the Python hub's
  // _standby_commit_gate: a never-synced standby has nothing to take
  // over; a synced one with a CONNECTED feed severs it as a probe (a
  // live primary resyncs, a dead one fails the feed loop's reconnects
  // and promotes); a synced one with the feed already down promotes NOW
  int standby_commit_gate_wire() {
    if (!standby_.load()) return 0;
    if (!synced_.load()) return 1;
    int fd = replica_fd_.load();
    if (fd >= 0) {
      ::shutdown(fd, SHUT_RDWR);
      return 1;
    }
    promote();
    return 0;
  }

  // track the primary: connect, hello, apply the full sync then every
  // streamed delta under the write gate.  On feed loss, retry within
  // replica_retries_ (exponential backoff); once the budget is gone a
  // SYNCED standby promotes itself — a never-synced one keeps retrying
  // (promoting fresh init weights would discard the job)
  void replica_loop() {
    // a sparse-capable standby (this hub serves row-sparse tables)
    // announces kReplCapSparse and must parse VARIABLE-size kReplSparse
    // frames; a dense hub keeps the fixed-size stream byte for byte
    bool sparse_feed = !sparse_leaves_.empty();
    size_t expect = size_t(dense_payload_f32_) + 17;  // + (8 + 9) hdr blob
    size_t feed_limit = expect;
    for (int leaf : sparse_leaves_)
      feed_limit +=
          8 + 8 * size_t(sizes_[size_t(leaf)] / sparse_dim_[size_t(leaf)]);
    std::vector<unsigned char> frame(sparse_feed ? size_t(4096) : expect);
    std::vector<std::pair<const unsigned char*, uint64_t>> fblobs;
    std::vector<int64_t> fids;
    int failures = 0;
    while (!replica_stop_.load()) {
      int fd = connect_to(replica_host_.c_str(), replica_port_, 5000);
      if (fd >= 0 && replica_stop_.load()) {
        ::close(fd);
        return;
      }
      if (fd >= 0) {
        replica_fd_.store(fd);
        size_t hdr_len = sparse_feed ? 10 : 9;
        unsigned char hello[8 + 5 + 8 + 10];
        size_t hello_len = 8 + 5 + 8 + hdr_len;
        be64_encode(5 + 8 + hdr_len, hello);
        hello[8] = 'R';
        be32_encode(1, hello + 9);
        be64_encode(hdr_len, hello + 13);
        int64_t my_clock;
        {
          std::lock_guard<std::mutex> m(meta_);
          my_clock = clock_;
        }
        be64_encode(uint64_t(my_clock), hello + 21);
        hello[29] = (unsigned char)kReplHello;
        if (sparse_feed) hello[30] = (unsigned char)kReplCapSparse;
        bool ok = write_all(fd, hello, hello_len);
        while (ok && !replica_stop_.load()) {
          unsigned char hdr[8];
          if (!read_exact(fd, hdr, 8)) break;
          uint64_t n = be64_decode(hdr);
          if (sparse_feed ? (n > feed_limit || n < 22) : (n != expect))
            break;  // protocol: desync
          if (frame.size() < n) frame.resize(size_t(n));
          if (!read_exact(fd, frame.data(), size_t(n))) break;
          if (frame[0] != 'R') break;
          if (be64_decode(frame.data() + 5) != 9) break;
          int64_t fclock = int64_t(be64_decode(frame.data() + 13));
          int kind = frame[21];
          uint32_t nblobs = be32_decode(frame.data() + 1);
          if (kind == kReplSparse) {
            if (!sparse_feed) break;  // never announced the capability
            if (nblobs != 1 + sizes_.size() + sparse_leaves_.size()) break;
            if (!parse_blob_table(frame.data(), n, fblobs)) break;
          } else {
            // SYNC/DELTA are FIXED-size frames: pin the length exactly
            // (the dense apply loops below walk per-leaf prefixes
            // without re-bounding against n — a short frame must never
            // reach them)
            if (n != expect) break;
            if (nblobs != 1 + sizes_.size()) break;
          }
          const unsigned char* p = frame.data() + 22;
          {
            std::unique_lock<std::shared_mutex> g(gate_);
            std::lock_guard<std::mutex> m(meta_);
            if (promoted_flag_.load()) {
              replica_fd_.store(-1);
              ::close(fd);
              return;  // late frame post-promotion: never lands
            }
            if (kind == kReplSync) {
              float* c = center_.data();
              for (size_t i = 0; i < sizes_.size(); ++i) {
                if (be64_decode(p) != uint64_t(sizes_[i]) * 4) { ok = false; break; }
                std::memcpy(c + offsets_[i], p + 8, size_t(sizes_[i]) * 4);
                p += 8 + size_t(sizes_[i]) * 4;
              }
              if (!ok) break;
              clock_ = fclock;
              num_updates_.store(fclock);
              synced_.store(true);
            } else if (kind == kReplDelta) {
              float* c = center_.data();
              for (size_t i = 0; i < sizes_.size(); ++i) {
                if (be64_decode(p) != uint64_t(sizes_[i]) * 4) { ok = false; break; }
                const float* d = reinterpret_cast<const float*>(p + 8);
                float* dst = c + offsets_[i];
                for (int64_t j = 0; j < sizes_[i]; ++j) dst[j] += d[j];
                p += 8 + size_t(sizes_[i]) * 4;
              }
              if (!ok) break;
              if (fclock > clock_) clock_ = fclock;
              num_updates_.fetch_add(1);
            } else if (kind == kReplSparse) {
              // row-delta apply: center[ids] += rows for sparse leaves,
              // whole-leaf adds for dense ones (the U-commit layout
              // past the header blob)
              size_t b = 1;
              float* c = center_.data();
              for (size_t i = 0; ok && i < sizes_.size(); ++i) {
                if (sparse_dim_[i] > 0) {
                  uint64_t idb = fblobs[b].second;
                  if (idb % 8 != 0) { ok = false; break; }
                  int64_t k = int64_t(idb / 8);
                  fids.resize(size_t(k));
                  std::memcpy(fids.data(), fblobs[b].first, size_t(k) * 8);
                  if (!check_row_ids(fids.data(), k, i)) { ok = false; break; }
                  int64_t dim = sparse_dim_[i];
                  if (fblobs[b + 1].second != uint64_t(k * dim) * 4) {
                    ok = false;
                    break;
                  }
                  const float* rows =
                      reinterpret_cast<const float*>(fblobs[b + 1].first);
                  float* dst = c + offsets_[i];
                  for (int64_t r = 0; r < k; ++r) {
                    float* row = dst + fids[size_t(r)] * dim;
                    const float* gsrc = rows + r * dim;
                    for (int64_t j = 0; j < dim; ++j) row[j] += gsrc[j];
                  }
                  b += 2;
                } else {
                  if (fblobs[b].second != uint64_t(sizes_[i]) * 4) {
                    ok = false;
                    break;
                  }
                  const float* d =
                      reinterpret_cast<const float*>(fblobs[b].first);
                  float* dst = c + offsets_[i];
                  for (int64_t j = 0; j < sizes_[i]; ++j) dst[j] += d[j];
                  b += 1;
                }
              }
              if (!ok) break;
              if (fclock > clock_) clock_ = fclock;
              num_updates_.fetch_add(1);
            } else {
              break;
            }
            ++repl_frames_;
          }
          failures = 0;  // a live stream resets the loss budget
        }
        replica_fd_.store(-1);
        ::close(fd);
      }
      if (replica_stop_.load() || promoted_flag_.load()) return;
      ++failures;
      if (failures > replica_retries_) {
        if (synced_.load()) {
          promote();  // primary presumed dead: take over behind the fence
          return;
        }
        failures = replica_retries_;  // never synced: cap backoff, keep trying
      }
      int64_t wait_ms = int64_t(replica_backoff_ms_) << (failures - 1);
      int64_t waited = 0;
      while (waited < wait_ms && !replica_stop_.load()) {
        struct timespec ts{0, 20 * 1000 * 1000};
        nanosleep(&ts, nullptr);
        waited += 20;
      }
    }
  }

  // -- reconnect backpressure (actions G/Y) -----------------------------------
  // every hub answers G; only an adaptive hub in a live storm hints
  // nonzero, and only to announcers that have not already waited a slot
  // this episode — Python's _retry_after_ms verbatim
  int64_t retry_after_ms(int64_t waits_taken) {
    if (!adaptive_) return 0;
    int64_t now = mono_ns();
    std::lock_guard<std::mutex> g(bp_mtx_);
    if (waits_taken <= 0) hello_times_.push_back(now);
    while (!hello_times_.empty() &&
           now - hello_times_.front() > storm_window_ns_)
      hello_times_.pop_front();
    if (now >= storm_until_ns_ &&
        int64_t(hello_times_.size()) >= int64_t(storm_hellos_)) {
      storm_until_ns_ = now + storm_shed_ns_;
      retry_seq_ = 0;
    }
    int64_t hint = 0;
    if (now < storm_until_ns_ && waits_taken <= 0) {
      ++retry_seq_;
      hint = std::min<int64_t>(retry_cap_ms_,
                               int64_t(retry_base_ms_) * retry_seq_);
      std::lock_guard<std::mutex> m(meta_);
      ++backpressure_hints_;
    }
    return hint;
  }

  // -- serving loop -----------------------------------------------------------
  void accept_loop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_.load(), nullptr, nullptr);
      if (fd < 0) break;  // listener shut down by stop()
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // kernel buffers sized to one full weights/commit frame (clamped to
      // [64 KiB, 8 MiB], matching networking.configure_socket)
      int64_t want = 8 + dense_payload_f32_ + 4096;
      int bufsz = int(std::min<int64_t>(std::max<int64_t>(want, 64 << 10), 8 << 20));
      ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
      ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
      if (idle_timeout_ms_ > 0) {
        // half-open liveness both directions (Python's conn.settimeout)
        timeval tv{};
        tv.tv_sec = idle_timeout_ms_ / 1000;
        tv.tv_usec = (idle_timeout_ms_ % 1000) * 1000;
        ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      }
      std::lock_guard<std::mutex> g(conn_mutex_);
      conn_fds_.push_back(fd);
      handler_threads_.emplace_back([this, fd] { handle_connection(fd); });
    }
    // NO close here: a spontaneous accept() failure (EMFILE storm)
    // exits this loop while stop() may still be about to shutdown the
    // fd it loaded — stop() owns the close, after joining this thread
  }

  // -- payload parsing --------------------------------------------------------
  bool parse_blob_table(const unsigned char* payload, uint64_t n,
                        std::vector<std::pair<const unsigned char*, uint64_t>>&
                            blobs) {
    if (n < 5) return false;
    uint32_t count = be32_decode(payload + 1);
    blobs.clear();
    uint64_t off = 5;
    for (uint32_t i = 0; i < count; ++i) {
      if (off + 8 > n) return false;
      uint64_t nbytes = be64_decode(payload + off);
      off += 8;
      if (off + nbytes > n) return false;
      blobs.emplace_back(payload + off, nbytes);
      off += nbytes;
    }
    return off == n;
  }

  // int64 row ids: in-bounds, strictly ascending (sorted AND unique — what
  // makes the fancy-indexed row apply exact), the Python _check_row_ids
  bool check_row_ids(const int64_t* ids, int64_t k, size_t leaf) {
    if (k == 0) return true;
    int64_t rows = sizes_[leaf] / sparse_dim_[leaf];
    if (ids[0] < 0 || ids[k - 1] >= rows) return false;
    for (int64_t r = 1; r < k; ++r)
      if (ids[r] <= ids[r - 1]) return false;
    return true;
  }

  // 'C'/'Q' payload -> dense PartViews (Q dequantized into qbuf, identical
  // math to the Python hub's _decode_qdelta: float(int8) * scale)
  bool parse_dense_commit(const unsigned char* payload, uint64_t n,
                          bool quantized, std::vector<float>& qbuf,
                          std::vector<std::pair<const unsigned char*, uint64_t>>& blobs,
                          std::vector<PartView>& parts) {
    if (!parse_blob_table(payload, n, blobs)) return false;
    if (blobs.size() != sizes_.size()) return false;
    parts.assign(sizes_.size(), PartView{});
    if (quantized) {
      int64_t total = 0;
      for (int64_t s : sizes_) total += s;
      qbuf.resize(size_t(total));
    }
    float* dst = qbuf.data();
    for (size_t i = 0; i < sizes_.size(); ++i) {
      if (quantized) {
        if (blobs[i].second != uint64_t(4 + sizes_[i])) return false;
        float scale = bef32_decode(blobs[i].first);
        const auto* q = reinterpret_cast<const signed char*>(blobs[i].first + 4);
        for (int64_t j = 0; j < sizes_[i]; ++j) dst[j] = float(q[j]) * scale;
        parts[i].vals = dst;
        dst += sizes_[i];
      } else {
        if (blobs[i].second != uint64_t(sizes_[i]) * 4) return false;
        parts[i].vals = reinterpret_cast<const float*>(blobs[i].first);
      }
    }
    return true;
  }

  // 'U'/'X' payload -> per-leaf PartViews: one blob for dense leaves, TWO
  // (ids, grads) for sparse leaves.  Row ids are copied into idsbuf (the
  // wire offset is unaligned); X value blobs dequantize into qbuf.
  bool parse_sparse_commit(const unsigned char* payload, uint64_t n,
                           bool quantized, std::vector<float>& qbuf,
                           std::vector<int64_t>& idsbuf,
                           std::vector<std::pair<const unsigned char*, uint64_t>>& blobs,
                           std::vector<PartView>& parts, int64_t* rows_out,
                           int64_t* wire_out) {
    if (!parse_blob_table(payload, n, blobs)) return false;
    if (blobs.size() != sizes_.size() + sparse_leaves_.size()) return false;
    // first pass: sizes (qbuf/idsbuf must not reallocate under pointers)
    size_t need_ids = 0, need_floats = 0, b = 0;
    int64_t wire = 0;
    for (size_t i = 0; i < sizes_.size(); ++i) {
      if (sparse_dim_[i] > 0) {
        uint64_t idb = blobs[b].second;
        if (idb % 8 != 0) return false;
        int64_t k = int64_t(idb / 8);
        wire += int64_t(idb);
        need_ids += size_t(k);
        uint64_t vb = blobs[b + 1].second;
        int64_t nv = k * sparse_dim_[i];
        if (quantized ? vb != uint64_t(4 + nv) : vb != uint64_t(nv) * 4)
          return false;
        wire += int64_t(vb);
        if (quantized) need_floats += size_t(nv);
        b += 2;
      } else {
        uint64_t vb = blobs[b].second;
        if (quantized ? vb != uint64_t(4 + sizes_[i])
                      : vb != uint64_t(sizes_[i]) * 4)
          return false;
        wire += int64_t(vb);
        if (quantized) need_floats += size_t(sizes_[i]);
        b += 1;
      }
    }
    idsbuf.resize(need_ids);
    qbuf.resize(need_floats);
    parts.assign(sizes_.size(), PartView{});
    int64_t* idst = idsbuf.data();
    float* dst = qbuf.data();
    int64_t rows = 0;
    b = 0;
    for (size_t i = 0; i < sizes_.size(); ++i) {
      if (sparse_dim_[i] > 0) {
        int64_t k = int64_t(blobs[b].second / 8);
        std::memcpy(idst, blobs[b].first, size_t(k) * 8);
        if (!check_row_ids(idst, k, i)) return false;
        parts[i].sparse = true;
        parts[i].ids = idst;
        parts[i].k = k;
        idst += k;
        rows += k;
        int64_t nv = k * sparse_dim_[i];
        if (quantized) {
          float scale = bef32_decode(blobs[b + 1].first);
          const auto* q =
              reinterpret_cast<const signed char*>(blobs[b + 1].first + 4);
          for (int64_t j = 0; j < nv; ++j) dst[j] = float(q[j]) * scale;
          parts[i].vals = dst;
          dst += nv;
        } else {
          parts[i].vals = reinterpret_cast<const float*>(blobs[b + 1].first);
        }
        b += 2;
      } else {
        if (quantized) {
          float scale = bef32_decode(blobs[b].first);
          const auto* q =
              reinterpret_cast<const signed char*>(blobs[b].first + 4);
          for (int64_t j = 0; j < sizes_[i]; ++j) dst[j] = float(q[j]) * scale;
          parts[i].vals = dst;
          dst += sizes_[i];
        } else {
          parts[i].vals = reinterpret_cast<const float*>(blobs[b].first);
        }
        b += 1;
      }
    }
    *rows_out = rows;
    *wire_out = wire;
    return true;
  }

  // -- replies ----------------------------------------------------------------
  bool send_weights(ConnIo& io, const float* snap) {
    std::vector<struct iovec> iov(1 + 2 * sizes_.size());
    iov[0].iov_base = w_hdr_.data();
    iov[0].iov_len = 13;
    for (size_t i = 0; i < sizes_.size(); ++i) {
      iov[1 + 2 * i].iov_base = w_prefix_.data() + 8 * i;
      iov[1 + 2 * i].iov_len = 8;
      iov[2 + 2 * i].iov_base =
          const_cast<float*>(snap + offsets_[i]);
      iov[2 + 2 * i].iov_len = size_t(sizes_[i]) * 4;
    }
    return io_writev_all(io, iov.data(), int(iov.size()));
  }

  bool send_u64_reply(ConnIo& io, char action, uint64_t value) {
    unsigned char buf[8 + 5 + 8 + 8];
    be64_encode(5 + 8 + 8, buf);
    buf[8] = (unsigned char)action;
    be32_encode(1, buf + 9);
    be64_encode(8, buf + 13);
    be64_encode(value, buf + 21);
    return io_write_all(io, buf, sizeof(buf));
  }

  void handle_connection(int fd) {
    int64_t last_pull_clock;
    {
      std::lock_guard<std::mutex> m(meta_);
      last_pull_clock = clock_fence_;  // born-after-restore semantics
    }
    bool joined = false;
    bool handoff = false;   // socket ownership moved to the replication feed
    bool timed_out = false;
    int64_t ctx_worker = -1;
    int pending_acks = 0;
    std::vector<float> snapf(center_.size());
    std::vector<float> qbuf;
    std::vector<int64_t> idsbuf;
    std::vector<unsigned char> sp_tx;
    std::vector<std::pair<const unsigned char*, uint64_t>> blobs;
    std::vector<PartView> parts;
    // batched receive: one grow-once buffer, one recv() per wakeup — a
    // pipelined client's parked commit + pull request arrive together
    std::vector<unsigned char> rx(4096);
    size_t rx_begin = 0, rx_end = 0;
    // shm transport (ISSUE 18): after a completed 'Z' handshake the SAME
    // byte stream continues over this ring pair; every read/write below
    // routes through io so the switch is invisible to the protocol code
    ConnIo io{fd, nullptr, nullptr, idle_timeout_ms_, &running_};
    std::unique_ptr<ShmRing> shm_rx, shm_tx;

    auto flush_acks = [&]() -> bool {
      if (pending_acks == 0) return true;
      std::vector<unsigned char> acks(size_t(pending_acks) * 13);
      for (int i = 0; i < pending_acks; ++i) {
        unsigned char* p = acks.data() + size_t(i) * 13;
        be64_encode(5, p);
        p[8] = 'A';
        be32_encode(0, p + 9);
      }
      pending_acks = 0;
      return io_write_all(io, acks.data(), acks.size());
    };
    auto ensure = [&](size_t need) -> bool {
      while (rx_end - rx_begin < need) {
        // the client may be gating its next send on these acks
        // (max-inflight backpressure): never block in recv holding them
        if (!flush_acks()) return false;
        if (rx_begin + need > rx.size()) {
          std::memmove(rx.data(), rx.data() + rx_begin, rx_end - rx_begin);
          rx_end -= rx_begin;
          rx_begin = 0;
          if (need > rx.size()) rx.resize(need);
        }
        ssize_t r = io_recv_some(io, rx.data() + rx_end, rx.size() - rx_end,
                                 &timed_out);
        if (r <= 0) return false;
        rx_end += size_t(r);
      }
      return true;
    };

    while (running_.load()) {
      if (!ensure(8)) break;
      uint64_t n = be64_decode(rx.data() + rx_begin);
      if (n > max_payload_ || n < 5) break;  // garbage prefix: drop peer
      if (!ensure(8 + size_t(n))) break;
      const unsigned char* payload = rx.data() + rx_begin + 8;
      rx_begin += 8 + size_t(n);
      char action = char(payload[0]);

      if (action == 'P') {
        if (standby_.load() && !synced_.load()) break;  // no job state yet
        if (!flush_acks()) break;
        {
          // clock read and center snapshot are ONE consistent view: the
          // exclusive gate excludes every in-flight commit
          std::unique_lock<std::shared_mutex> g(gate_);
          {
            std::lock_guard<std::mutex> m(meta_);
            last_pull_clock = clock_;
            ++pulls_;
            pull_bytes_ += center_bytes_;
          }
          std::memcpy(snapf.data(), center_.data(),
                      center_.size() * sizeof(float));
        }
        if (!send_weights(io, snapf.data())) break;

      } else if (action == 'C' || action == 'Q') {
        if (!parse_dense_commit(payload, n, action == 'Q', qbuf, blobs, parts))
          break;
        if (standby_commit_gate_wire()) break;
        if (!joined) {
          joined = true;
          std::lock_guard<std::mutex> m(meta_);
          ++live_members_;
        }
        int64_t wire = int64_t(n) - 5 - 8 * int64_t(sizes_.size());
        commit_parts(parts, &last_pull_clock, ctx_worker, wire, 0, 0);
        ++pending_acks;

      } else if (action == 'U' || action == 'X') {
        if (sparse_leaves_.empty()) break;  // no sparse tables registered
        int64_t rows = 0, wire = 0;
        if (!parse_sparse_commit(payload, n, action == 'X', qbuf, idsbuf,
                                 blobs, parts, &rows, &wire))
          break;
        if (standby_commit_gate_wire()) break;
        if (!joined) {
          joined = true;
          std::lock_guard<std::mutex> m(meta_);
          ++live_members_;
        }
        // wire savings vs the like-for-like dense commit (the Python
        // hub's dense_equiv accounting: full f32 payload for U, full
        // int8 Q payload for X)
        int64_t dense_equiv =
            action == 'U' ? dense_payload_f32_ : q_payload_bytes_;
        int64_t saved = dense_equiv - wire;
        if (saved < 0) saved = 0;
        commit_parts(parts, &last_pull_clock, ctx_worker, wire, rows, saved);
        ++pending_acks;

      } else if (action == 'S') {
        if (sparse_leaves_.empty()) break;
        if (standby_.load() && !synced_.load()) break;
        if (!parse_blob_table(payload, n, blobs)) break;
        if (blobs.size() != sparse_leaves_.size()) break;
        // validate every table's ids before touching the center
        size_t need_ids = 0;
        bool bad = false;
        for (auto& bl : blobs) {
          if (bl.second % 8 != 0) { bad = true; break; }
          need_ids += size_t(bl.second / 8);
        }
        if (bad) break;
        idsbuf.resize(need_ids);
        int64_t* idst = idsbuf.data();
        std::vector<std::pair<const int64_t*, int64_t>> req(blobs.size());
        int64_t rows_pulled = 0;
        for (size_t s = 0; s < blobs.size(); ++s) {
          int64_t k = int64_t(blobs[s].second / 8);
          std::memcpy(idst, blobs[s].first, size_t(k) * 8);
          if (!check_row_ids(idst, k, size_t(sparse_leaves_[s]))) {
            bad = true;
            break;
          }
          req[s] = {idst, k};
          idst += k;
          rows_pulled += k;
        }
        if (bad) break;
        if (!flush_acks()) break;
        // V reply: one blob per CENTER leaf — full f32 leaf for dense,
        // the requested [k, dim] row block for sparse (VarFrameEncoder's
        // exact bytes), packed under the gate, sent after release
        uint64_t vpayload = 5;
        {
          size_t s = 0;
          for (size_t i = 0; i < sizes_.size(); ++i) {
            int64_t nb = sparse_dim_[i] > 0 ? req[s].second * sparse_dim_[i] * 4
                                            : sizes_[i] * 4;
            if (sparse_dim_[i] > 0) ++s;
            vpayload += 8 + uint64_t(nb);
          }
        }
        sp_tx.resize(8 + vpayload);
        int64_t raw_bytes = 0;
        {
          std::unique_lock<std::shared_mutex> g(gate_);
          unsigned char* p = sp_tx.data();
          be64_encode(vpayload, p);
          p[8] = 'V';
          be32_encode(uint32_t(sizes_.size()), p + 9);
          p += 13;
          size_t s = 0;
          for (size_t i = 0; i < sizes_.size(); ++i) {
            const float* c = center_.data() + offsets_[i];
            if (sparse_dim_[i] > 0) {
              int64_t dim = sparse_dim_[i];
              int64_t k = req[s].second;
              be64_encode(uint64_t(k * dim) * 4, p);
              p += 8;
              float* out = reinterpret_cast<float*>(p);
              for (int64_t r = 0; r < k; ++r)
                std::memcpy(out + r * dim, c + req[s].first[r] * dim,
                            size_t(dim) * 4);
              p += size_t(k * dim) * 4;
              raw_bytes += k * dim * 4;
              ++s;
            } else {
              be64_encode(uint64_t(sizes_[i]) * 4, p);
              p += 8;
              std::memcpy(p, c, size_t(sizes_[i]) * 4);
              p += size_t(sizes_[i]) * 4;
              raw_bytes += sizes_[i] * 4;
            }
          }
          {
            std::lock_guard<std::mutex> m(meta_);
            last_pull_clock = clock_;
            ++pulls_;
            pull_bytes_ += raw_bytes;  // raw tensor bytes, the dense basis
            sparse_rows_pulled_ += rows_pulled;
            int64_t saved =
                (8 + dense_payload_f32_) - int64_t(8 + vpayload);
            if (saved > 0) sparse_wire_saved_ += saved;
            for (size_t s = 0; s < req.size(); ++s)
              touch_ids_locked(s, req[s].first, req[s].second);
            fold_touch_locked();
          }
        }
        if (!io_write_all(io, sp_tx.data(), sp_tx.size())) break;

      } else if (action == 'H') {  // heartbeat: liveness proof, acked
        ++pending_acks;

      } else if (action == 'M') {
        // health report: park the JSON blob for the Python wrapper's
        // drain (runtime/native.py folds it into the HealthCollector);
        // malformed frames are ignored, never fatal — health must not
        // take down a training connection
        if (parse_blob_table(payload, n, blobs) && blobs.size() == 1) {
          std::lock_guard<std::mutex> m(meta_);
          if (health_ring_.size() >= kHealthRingCap) {
            health_ring_.pop_front();
            ++health_dropped_;
          }
          health_ring_.emplace_back(
              reinterpret_cast<const char*>(blobs[0].first),
              size_t(blobs[0].second));
        }
        ++pending_acks;

      } else if (action == 'T') {
        // trace-context announce: remember the worker for commit-log
        // attribution, reply with this hub's monotonic clock
        if (parse_blob_table(payload, n, blobs) && blobs.size() >= 1)
          ctx_worker = json_int_field(blobs[0].first, size_t(blobs[0].second),
                                      "worker_id", -1);
        if (!flush_acks()) break;
        if (!send_u64_reply(io, 'T', uint64_t(mono_ns()))) break;

      } else if (action == 'G') {
        // reconnect announce: answer with a retry-after hint (0 =
        // proceed); the blob carries the waits already taken this episode
        int64_t waits = 0;
        if (parse_blob_table(payload, n, blobs) && blobs.size() >= 1 &&
            blobs[0].second >= 8)
          waits = int64_t(be64_decode(blobs[0].first));
        if (!flush_acks()) break;
        if (!send_u64_reply(io, 'Y', uint64_t(retry_after_ms(waits)))) break;

      } else if (action == 'R') {
        // replica handshake: this peer is a hot standby, not a worker —
        // attach it to the replication feed and hand the socket over.
        // A 10th header byte (optional — legacy hellos are 9 bytes)
        // carries the standby's frame-kind capabilities
        if (io.rx) break;  // the feed owns a raw fd; no hello after attach
        if (!parse_blob_table(payload, n, blobs) || blobs.size() != 1 ||
            (blobs[0].second != 9 && blobs[0].second != 10))
          break;
        if (blobs[0].first[8] != kReplHello) break;
        int repl_caps = blobs[0].second >= 10 ? int(blobs[0].first[9]) : 0;
        if (!flush_acks()) break;
        {
          std::lock_guard<std::mutex> m(meta_);
          if (!feed_) feed_.reset(new ReplFeed(this));
        }
        {
          std::lock_guard<std::mutex> g(conn_mutex_);
          conn_fds_.erase(std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
                          conn_fds_.end());
        }
        handoff = true;
        feed_->attach(fd, repl_caps);  // on failure attach closes the fd
        return;

      } else if (action == 'Z') {
        // shm attach handshake (ISSUE 18), resolved ENTIRELY inside this
        // dispatch arm: request (this frame) -> offer/decline + confirm
        // (both still over TCP) -> switch.  TCP FIFO makes the switch
        // point exact — the first post-confirm frame is already on the
        // ring — so the stream is never torn (the protocol model's
        // SHM_RULES walk every interleaving of this exchange)
        if (!parse_blob_table(payload, n, blobs) || blobs.size() != 1 ||
            blobs[0].second != 9)
          break;
        unsigned version = blobs[0].first[0];
        uint64_t cap_hint = be64_decode(blobs[0].first + 1);
        if (!flush_acks()) break;
        std::unique_ptr<ShmRing> cand_rx, cand_tx;
        std::string c2h_path, h2c_path;
        if (!shm_dir_.empty() && version == 1 && io.rx == nullptr) {
          char name[96];
          std::snprintf(name, sizeof(name), "/ring-%d-%llu", bound_port_,
                        (unsigned long long)shm_seq_.fetch_add(1));
          c2h_path = shm_dir_ + name + ".c2h";
          h2c_path = shm_dir_ + name + ".h2c";
          uint64_t cap = cap_hint;
          uint64_t floor_b = uint64_t(2) * uint64_t(8 + dense_payload_f32_);
          if (cap < floor_b) cap = floor_b;
          if (cap < (uint64_t(1) << 20)) cap = uint64_t(1) << 20;
          cand_rx.reset(ShmRing::create(c2h_path.c_str(), false, cap));
          if (cand_rx) cand_tx.reset(ShmRing::create(h2c_path.c_str(), true, cap));
          if (!cand_rx || !cand_tx) {  // dir vanished / ENOSPC -> decline
            cand_rx.reset();
            cand_tx.reset();
            ::unlink(c2h_path.c_str());
            ::unlink(h2c_path.c_str());
          }
        }
        bool offered = bool(cand_rx) && bool(cand_tx);
        {
          // offer: 'Z' + the two ring-file paths; decline: 'Z' + 0 blobs
          uint64_t zpay = 5;
          if (offered)
            zpay += 8 + c2h_path.size() + 8 + h2c_path.size();
          std::vector<unsigned char> zb(8 + size_t(zpay));
          be64_encode(zpay, zb.data());
          zb[8] = 'Z';
          be32_encode(offered ? 2u : 0u, zb.data() + 9);
          if (offered) {
            unsigned char* p = zb.data() + 13;
            be64_encode(c2h_path.size(), p);
            p += 8;
            std::memcpy(p, c2h_path.data(), c2h_path.size());
            p += c2h_path.size();
            be64_encode(h2c_path.size(), p);
            p += 8;
            std::memcpy(p, h2c_path.data(), h2c_path.size());
          }
          if (!io_write_all(io, zb.data(), zb.size())) {
            if (offered) {
              ::unlink(c2h_path.c_str());
              ::unlink(h2c_path.c_str());
            }
            break;
          }
        }
        if (!offered) continue;  // declined: the connection stays pure TCP
        // the next TCP frame MUST be the client's 'Z' confirm
        bool ok = ensure(8);
        bool attached = false;
        if (ok) {
          uint64_t n2 = be64_decode(rx.data() + rx_begin);
          ok = n2 >= 5 && n2 <= max_payload_ && ensure(8 + size_t(n2));
          if (ok) {
            const unsigned char* p2 = rx.data() + rx_begin + 8;
            rx_begin += 8 + size_t(n2);
            ok = char(p2[0]) == 'Z' && parse_blob_table(p2, n2, blobs) &&
                 blobs.size() == 1 && blobs[0].second == 1;
            attached = ok && blobs[0].first[0] == 1;
          }
        }
        // ring files are transient rendezvous: unlink as soon as the
        // handshake resolves — live mappings keep the memory alive
        ::unlink(c2h_path.c_str());
        ::unlink(h2c_path.c_str());
        if (!ok) break;  // torn handshake: drop peer (rings unmap + wake)
        if (attached) {
          if (rx_end != rx_begin) break;  // frames batched past the confirm
          shm_rx = std::move(cand_rx);
          shm_tx = std::move(cand_tx);
          io.rx = shm_rx.get();
          io.tx = shm_tx.get();
        }
        // confirm=0 (client mmap failed): rings destruct, stay on TCP

      } else {  // 'B' or unknown -> close
        break;
      }
    }
    if (timed_out) {
      std::lock_guard<std::mutex> m(meta_);
      ++idle_evictions_;
    }
    if (joined) {
      std::lock_guard<std::mutex> m(meta_);
      --live_members_;
    }
    if (!handoff) {
      ::close(fd);
      std::lock_guard<std::mutex> g(conn_mutex_);
      conn_fds_.erase(std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
                      conn_fds_.end());
    }
  }

  // -- configuration ----------------------------------------------------------
  int requested_port_;
  int bound_port_ = -1;
  int mode_;
  int num_workers_;
  bool elastic_;
  bool adaptive_;
  int idle_timeout_ms_;
  uint64_t max_payload_ = 0;
  std::vector<int64_t> sizes_;
  std::vector<int64_t> offsets_;
  std::vector<int64_t> sparse_dim_;  // per leaf; 0 = dense
  std::vector<int> sparse_leaves_;   // ascending sparse leaf indices
  int64_t center_bytes_ = 0;
  int64_t dense_payload_f32_ = 0;  // payload bytes of a full f32 frame
  int64_t q_payload_bytes_ = 0;    // payload bytes of a full int8 Q commit
  std::vector<unsigned char> w_hdr_;     // prebuilt 'W' header (13 bytes)
  std::vector<unsigned char> w_prefix_;  // prebuilt per-tensor prefixes

  // -- center + clocks --------------------------------------------------------
  std::vector<float> center_;
  std::shared_mutex gate_;           // commits shared / snapshots exclusive
  std::mutex stripes_[kStripes];     // per-leaf-group apply locks
  std::mutex meta_;                  // clock, fence, counters, log, ring
  int64_t clock_ = 0;
  int64_t clock_fence_ = 0;
  std::atomic<int64_t> num_updates_{0};

  // -- telemetry (guarded by meta_) -------------------------------------------
  int64_t commits_ = 0, pulls_ = 0;
  int64_t commit_bytes_ = 0, pull_bytes_ = 0;
  int64_t fenced_commits_ = 0, idle_evictions_ = 0;
  int live_members_ = 0;
  int64_t sparse_rows_pulled_ = 0, sparse_rows_committed_ = 0;
  int64_t sparse_wire_saved_ = 0;
  int64_t repl_sparse_bytes_ = 0, repl_sparse_saved_ = 0;
  std::vector<std::vector<float>> sparse_touch_;  // per table, per row
  std::vector<int64_t> hot_rows_;                 // per table, decayed est.
  int64_t touch_folds_ = 0;
  int64_t replicas_attached_ = 0, replica_disconnects_ = 0;
  int64_t merge_batches_ = 0, merged_commits_ = 0, max_merge_batch_ = 0;
  int64_t backpressure_hints_ = 0;
  int64_t repl_frames_ = 0, promotions_ = 0;
  int64_t health_dropped_ = 0;
  int64_t promoted_at_clock_ = -1;
  int64_t stale_hist_[kStaleSlots + 1] = {};
  int64_t merge_hist_[kStaleSlots + 1] = {};
  std::vector<CommitRecord> commit_log_ =
      std::vector<CommitRecord>(size_t(kLogCapacity));
  int64_t log_head_ = 0, log_count_ = 0, log_dropped_ = 0;
  std::deque<std::string> health_ring_;

  // -- adaptive state ---------------------------------------------------------
  std::mutex comb_qlock_, comb_drain_;
  std::vector<CommitEntry*> comb_queue_;
  std::mutex rate_mtx_;
  std::unordered_map<int64_t, std::pair<double, int64_t>> rate_scales_;
  std::mutex bp_mtx_;
  std::deque<int64_t> hello_times_;
  int64_t storm_until_ns_ = 0;
  int64_t retry_seq_ = 0;
  int storm_hellos_ = 3;               // SocketParameterServer.STORM_HELLOS
  int64_t storm_window_ns_ = 5000000000;   // STORM_WINDOW_S
  int64_t storm_shed_ns_ = 3000000000;     // STORM_SHED_S
  int retry_base_ms_ = 50, retry_cap_ms_ = 2000;

  // -- replication ------------------------------------------------------------
  std::unique_ptr<ReplFeed> feed_;  // created under meta_ on first hello
  std::string replica_host_;
  int replica_port_ = -1;
  int replica_retries_ = 3;
  int replica_backoff_ms_ = 200;
  std::atomic<int> replica_fd_{-1};
  std::atomic<bool> replica_stop_{false};
  std::atomic<bool> standby_{false};
  std::atomic<bool> promoted_flag_{false};
  std::atomic<bool> synced_{false};
  std::atomic<bool> stopped_{false};
  std::mutex stop_mtx_;  // serializes concurrent stop() teardowns (join is UB twice)
  std::thread replica_thread_;

  // -- shm transport (ISSUE 18) -----------------------------------------------
  std::string shm_dir_;               // empty = never offer the 'Z' attach
  std::atomic<uint64_t> shm_seq_{0};  // ring-file name uniquifier

  // -- serving ----------------------------------------------------------------
  std::atomic<bool> running_{false};
  std::atomic<int> listen_fd_{-1};
  std::thread accept_thread_;
  std::mutex conn_mutex_;
  std::vector<int> conn_fds_;
  std::vector<std::thread> handler_threads_;
};

}  // namespace

extern "C" {

void* dk_ps_create(int port, int num_tensors, const int64_t* sizes, int mode,
                   int num_workers, int elastic, int idle_timeout_ms,
                   int num_sparse, const int32_t* sparse_leaves,
                   const int64_t* sparse_dims, int adaptive,
                   int64_t max_payload) {
  return new ParameterServer(port, num_tensors, sizes, mode, num_workers,
                             elastic, idle_timeout_ms, num_sparse,
                             sparse_leaves, sparse_dims, adaptive,
                             max_payload);
}

void dk_ps_set_replica_of(void* ps, const char* host, int port, int retries,
                          int backoff_ms) {
  static_cast<ParameterServer*>(ps)->set_replica_of(host, port, retries,
                                                    backoff_ms);
}

int dk_ps_start(void* ps) { return static_cast<ParameterServer*>(ps)->start(); }
void dk_ps_stop(void* ps) { static_cast<ParameterServer*>(ps)->stop(); }
void dk_ps_get_weights(void* ps, float* out) {
  static_cast<ParameterServer*>(ps)->get_weights(out);
}
void dk_ps_set_weights(void* ps, const float* in) {
  static_cast<ParameterServer*>(ps)->set_weights(in);
}
int64_t dk_ps_num_updates(void* ps) {
  return static_cast<ParameterServer*>(ps)->num_updates();
}
int dk_ps_port(void* ps) { return static_cast<ParameterServer*>(ps)->port(); }
int64_t dk_ps_pull(void* ps, float* out) {
  return static_cast<ParameterServer*>(ps)->pull_direct(out);
}
int64_t dk_ps_snapshot(void* ps, float* out) {
  return static_cast<ParameterServer*>(ps)->snapshot_direct(out);
}
// 0 = applied, 1 = refused (never-synced standby), 2 = refused (standby
// probing a connected primary) — the wrapper raises on nonzero
int dk_ps_commit(void* ps, const float* flat, int64_t last_pull_clock) {
  return static_cast<ParameterServer*>(ps)->commit_direct(flat,
                                                          last_pull_clock);
}
int dk_ps_commit_ctx(void* ps, const float* flat, int64_t last_pull_clock,
                     int64_t worker) {
  return static_cast<ParameterServer*>(ps)->commit_direct(flat,
                                                          last_pull_clock,
                                                          worker);
}
// sparse direct pair (ISSUE 15): the S/V/U exchanges minus the frame —
// ids/counts concatenate each sparse table's sorted-unique row ids in
// sparse-leaf order; values concatenate per-leaf payloads in template
// order (dense whole, sparse [k, dim]).  GIL released by ctypes.
int64_t dk_ps_pull_sparse(void* ps, const int64_t* ids, const int64_t* counts,
                          float* out) {
  return static_cast<ParameterServer*>(ps)->pull_sparse_direct(ids, counts,
                                                               out);
}
int dk_ps_commit_sparse(void* ps, const float* vals, const int64_t* ids,
                        const int64_t* counts, int64_t last_pull_clock,
                        int64_t worker) {
  return static_cast<ParameterServer*>(ps)->commit_sparse_direct(
      vals, ids, counts, last_pull_clock, worker);
}
void dk_ps_hot_rows(void* ps, int64_t* out) {
  static_cast<ParameterServer*>(ps)->hot_rows(out);
}
void dk_ps_stats(void* ps, int64_t* out) {
  static_cast<ParameterServer*>(ps)->stats(out);
}
void dk_ps_staleness_hist(void* ps, int64_t* out65) {
  static_cast<ParameterServer*>(ps)->staleness_hist(out65);
}
void dk_ps_merge_hist(void* ps, int64_t* out65) {
  static_cast<ParameterServer*>(ps)->merge_hist(out65);
}
int64_t dk_ps_drain_commits(void* ps, int64_t* out, int64_t max_records) {
  return static_cast<ParameterServer*>(ps)->drain_commits(out, max_records);
}
int64_t dk_ps_next_health(void* ps, unsigned char* out, int64_t cap) {
  return static_cast<ParameterServer*>(ps)->next_health(out, cap);
}
void dk_ps_set_rate_scale(void* ps, int64_t worker, double scale,
                          int64_t expires_ns) {
  static_cast<ParameterServer*>(ps)->set_rate_scale(worker, scale, expires_ns);
}
void dk_ps_set_storm_params(void* ps, int hellos, int window_ms, int shed_ms,
                            int base_ms, int cap_ms) {
  static_cast<ParameterServer*>(ps)->set_storm_params(hellos, window_ms,
                                                      shed_ms, base_ms,
                                                      cap_ms);
}
void dk_ps_arm_storm(void* ps) {
  static_cast<ParameterServer*>(ps)->arm_storm();
}
int dk_ps_is_standby(void* ps) {
  return static_cast<ParameterServer*>(ps)->is_standby() ? 1 : 0;
}
int dk_ps_promoted(void* ps) {
  return static_cast<ParameterServer*>(ps)->promoted() ? 1 : 0;
}
int64_t dk_ps_promoted_at_clock(void* ps) {
  return static_cast<ParameterServer*>(ps)->promoted_at_clock();
}
int dk_ps_promote(void* ps) {
  return static_cast<ParameterServer*>(ps)->promote() ? 1 : 0;
}
int dk_ps_wait_synced(void* ps, int64_t timeout_ms) {
  return static_cast<ParameterServer*>(ps)->wait_synced(timeout_ms) ? 1 : 0;
}
int64_t dk_ps_time_ns(void* ps) {
  return static_cast<ParameterServer*>(ps)->time_ns();
}
void dk_ps_restore(void* ps, const float* flat, int64_t clock,
                   int64_t num_updates) {
  static_cast<ParameterServer*>(ps)->restore(flat, clock, num_updates);
}
void dk_ps_destroy(void* ps) { delete static_cast<ParameterServer*>(ps); }

// -- shm transport (ISSUE 18) -------------------------------------------------
// enable the hub's 'Z' attach path: rings are created under `dir` (empty
// or NULL disables).  Must be called before dk_ps_start.
void dk_ps_shm_attach(void* ps, const char* dir) {
  static_cast<ParameterServer*>(ps)->set_shm_dir(dir);
}

// standalone ring handles: the TSAN stress legs and the cross-language
// layout pin drive the EXACT ring code the hub serves with
void* dk_shm_ring_create(const char* path, int producer, uint64_t capacity) {
  return ShmRing::create(path, producer != 0, capacity);
}
void* dk_shm_ring_open(const char* path, int producer) {
  return ShmRing::open_existing(path, producer != 0);
}
// sendall semantics: n on success, -1 on peer-gone/timeout
long long dk_shm_ring_write(void* ring, const void* buf, long long n,
                            int timeout_ms) {
  auto* r = static_cast<ShmRing*>(ring);
  return r->write(static_cast<const unsigned char*>(buf), size_t(n),
                  timeout_ms, nullptr)
             ? n
             : -1;
}
// recv semantics: bytes read, 0 = clean EOF (producer closed + drained),
// -1 = timeout
long long dk_shm_ring_read(void* ring, void* buf, long long cap,
                           int timeout_ms) {
  auto* r = static_cast<ShmRing*>(ring);
  return (long long)r->read_some(static_cast<unsigned char*>(buf),
                                 size_t(cap), timeout_ms, nullptr, nullptr);
}
// raise only THIS side's closed flag (peer drains then sees EOF /
// peer-gone); the mapping stays valid until dk_shm_ring_destroy
void dk_shm_ring_close(void* ring) {
  auto* r = static_cast<ShmRing*>(ring);
  (r->producer_ ? r->producer_closed_ : r->consumer_closed_)
      ->store(1, std::memory_order_release);
}
// raise BOTH flags (wake a parked peer), unmap, free the handle
void dk_shm_ring_destroy(void* ring) { delete static_cast<ShmRing*>(ring); }

}  // extern "C"
