// Native parameter-server hub — C++ implementation of the framed tensor
// protocol in distkeras_tpu/runtime/networking.py (the executable spec is
// the Python SocketParameterServer; both speak identical bytes).
//
// Reference parity: distkeras/parameter_servers.py ran this hub as Python
// threads, so every commit serialized on the GIL (SURVEY.md §3.4 — "one
// thread per worker connection + one global lock, effectively serialized
// by the GIL").  Here accept/handler threads are native, commits apply
// under one std::mutex with vectorizable float loops, and the Python
// process only touches the hub at start/stop/get_weights.
//
// Wire format (all integers big-endian):
//   frame          := u64 payload_len, payload
//   tensor payload := u8 action, u32 num_tensors,
//                     num_tensors * (u64 nbytes, raw bytes)
//   actions: 'P' pull -> 'W' + center tensors
//            'C' commit (center-shaped f32 deltas) -> 'A'
//            'Q' int8 commit (per tensor: be f32 scale + int8 values,
//                dequantized here, then the same scaling rules) -> 'A'
//            'H' heartbeat (liveness proof while idle) -> 'A'
//            'T' trace-context announce (one JSON blob: job_id/worker_id/
//                span_id) -> 'T' + one 8-byte blob = this hub's
//                CLOCK_MONOTONIC nanoseconds (the NTP-style midpoint
//                sample the client's clock-offset estimate is built from;
//                Python's time.perf_counter_ns() reads the same clock on
//                Linux, so offsets are directly meaningful)
//            'B' bye -> connection closes
//
// Telemetry (dk_ps_stats / dk_ps_staleness_hist / dk_ps_drain_commits):
// the hub counts commits/pulls/payload bytes/fenced commits/idle
// evictions, keeps an exact small-integer staleness histogram, and logs
// every applied commit (clock, announcing worker, staleness, monotonic
// timestamp, apply duration) into a bounded ring.  The Python wrapper
// (runtime/native.py :: sync_telemetry) drains these into the SAME
// registry names the Python hub emits, so Prometheus/punchcard output is
// hub-implementation-agnostic.
//
// Commit scaling modes (matching runtime/parameter_server.py):
//   0 delta:  center += d                (DOWNPOUR, elastic)
//   1 adag:   center += d / num_workers  (ADAG)
//   2 dynsgd: center += d / (staleness+1), staleness = clock - last_pull_clock

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cctype>
#include <cerrno>
#include <ctime>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

int64_t mono_ns() {
  timespec ts{};
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000000000 + int64_t(ts.tv_nsec);
}

uint64_t be64_decode(const unsigned char* b) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | b[i];
  return v;
}

void be64_encode(uint64_t v, unsigned char* b) {
  for (int i = 7; i >= 0; --i) { b[i] = v & 0xff; v >>= 8; }
}

uint32_t be32_decode(const unsigned char* b) {
  return (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) | (uint32_t(b[2]) << 8) | b[3];
}

void be32_encode(uint32_t v, unsigned char* b) {
  b[0] = v >> 24; b[1] = (v >> 16) & 0xff; b[2] = (v >> 8) & 0xff; b[3] = v & 0xff;
}

bool read_exact(int fd, void* buf, size_t n, bool* timed_out = nullptr) {
  auto* p = static_cast<unsigned char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r <= 0) {
      // distinguish SO_RCVTIMEO expiry (idle eviction) from EOF/reset so
      // the eviction counter matches the Python hub's semantics
      if (timed_out && r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
        *timed_out = true;
      return false;
    }
    got += size_t(r);
  }
  return true;
}

// minimal extraction of an integer JSON field (the 'T' announce blob is
// produced by our own client, so a full parser buys nothing): returns
// fallback when the key is absent/malformed
int64_t json_int_field(const unsigned char* buf, size_t n, const char* key,
                       int64_t fallback) {
  std::string s(reinterpret_cast<const char*>(buf), n);
  std::string needle = std::string("\"") + key + "\"";
  size_t pos = s.find(needle);
  if (pos == std::string::npos) return fallback;
  pos = s.find(':', pos + needle.size());
  if (pos == std::string::npos) return fallback;
  ++pos;
  while (pos < s.size() && (s[pos] == ' ' || s[pos] == '\t')) ++pos;
  if (pos >= s.size() || (s[pos] != '-' && !isdigit(static_cast<unsigned char>(s[pos]))))
    return fallback;
  return std::strtoll(s.c_str() + pos, nullptr, 10);
}

bool write_all(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const unsigned char*>(buf);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, p + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += size_t(r);
  }
  return true;
}

class ParameterServer {
 public:
  ParameterServer(int port, int num_tensors, const int64_t* sizes, int mode, int num_workers,
                  int elastic, int idle_timeout_ms)
      : requested_port_(port), mode_(mode), num_workers_(num_workers),
        elastic_(elastic != 0), idle_timeout_ms_(idle_timeout_ms) {
    sizes_.assign(sizes, sizes + num_tensors);
    int64_t total = 0;
    for (int64_t s : sizes_) total += s;
    center_.assign(size_t(total), 0.0f);
    center_bytes_ = total * int64_t(sizeof(float));
    // largest VALID payload a peer may declare: per tensor the larger of
    // the f32 blob (4*size) and the int8 Q blob (4+size, bigger for
    // scalar leaves).  recv_payload caps against this, so a garbage
    // length prefix is a dropped connection, not a multi-GiB resize
    // (matching the Python hub's _max_payload)
    max_payload_ = 5;
    for (int64_t s : sizes_)
      max_payload_ += 8 + uint64_t(std::max(s * int64_t(sizeof(float)), 4 + s));
  }

  ~ParameterServer() { stop(); }

  // returns the bound port, or -1 on failure
  int start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(uint16_t(requested_port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return -1;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    bound_port_ = ntohs(addr.sin_port);
    running_.store(true);
    accept_thread_ = std::thread([this] { accept_loop(); });
    return bound_port_;
  }

  void stop() {
    bool was_running = running_.exchange(false);
    if (!was_running && listen_fd_ < 0) return;
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    {
      std::lock_guard<std::mutex> g(conn_mutex_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : handler_threads_)
      if (t.joinable()) t.join();
    handler_threads_.clear();
  }

  void get_weights(float* out) {
    std::lock_guard<std::mutex> g(center_mutex_);
    std::memcpy(out, center_.data(), center_.size() * sizeof(float));
  }

  void set_weights(const float* in) {
    std::lock_guard<std::mutex> g(center_mutex_);
    std::memcpy(center_.data(), in, center_.size() * sizeof(float));
  }

  int64_t num_updates() const { return num_updates_.load(); }
  int port() const { return bound_port_; }

  // restore a hub snapshot: center + commit clock + update count, with the
  // clock FENCE armed at the restored clock so any pre-restart pull clock
  // a caller presents is clamped to the restart point (matching the
  // Python hub's restore_state semantics)
  void restore(const float* flat, int64_t clock, int64_t num_updates) {
    std::lock_guard<std::mutex> g(center_mutex_);
    std::memcpy(center_.data(), flat, center_.size() * sizeof(float));
    clock_ = clock;
    clock_fence_ = clock;
    num_updates_.store(num_updates);
  }

  // -- in-process transport (transport="inproc") ------------------------------
  // The direct-call twins of the 'P' and 'C' wire branches: co-located
  // Python workers (ctypes releases the GIL for the call) snapshot and
  // commit under the same mutex the socket handlers take, with the
  // staleness clock carried by the caller instead of a connection.

  int64_t pull_direct(float* out) {
    std::lock_guard<std::mutex> g(center_mutex_);
    std::memcpy(out, center_.data(), center_.size() * sizeof(float));
    // counted like the Python hub's pull_direct (inproc pulls land in
    // ps_pulls_total); snapshot reads use snapshot_direct instead, which
    // the Python hub's snapshot_state also leaves uncounted
    ++pulls_;
    pull_bytes_ += center_bytes_;
    return clock_;
  }

  // pull_direct minus the telemetry: the HubSnapshotter's periodic center
  // read, which must not register as worker pull traffic (metric parity
  // with the Python hub, whose snapshot_state copies without counting)
  int64_t snapshot_direct(float* out) {
    std::lock_guard<std::mutex> g(center_mutex_);
    std::memcpy(out, center_.data(), center_.size() * sizeof(float));
    return clock_;
  }

  void commit_direct(const float* flat, int64_t last_pull_clock,
                     int64_t worker = -1) {
    std::vector<const float*> delta(sizes_.size());
    const float* p = flat;
    for (size_t i = 0; i < sizes_.size(); ++i) { delta[i] = p; p += sizes_[i]; }
    {
      std::lock_guard<std::mutex> g(center_mutex_);
      if (last_pull_clock < clock_fence_) {
        last_pull_clock = clock_fence_;
        ++fenced_commits_;
      }
      int64_t staleness = clock_ - last_pull_clock;
      int64_t t0 = mono_ns();
      apply_commit(delta.data(), staleness);
      record_commit_locked(worker, staleness, t0);
      commit_bytes_ += center_bytes_;
      ++clock_;
    }
    num_updates_.fetch_add(1);
  }

  // -- telemetry exports (all under center_mutex_ for a consistent view) ------
  // layout: [commits, pulls, commit_bytes, pull_bytes, fenced_commits,
  //          live_workers, idle_evictions, clock, commit_log_dropped]
  void stats(int64_t out[9]) {
    std::lock_guard<std::mutex> g(center_mutex_);
    out[0] = commits_;
    out[1] = pulls_;
    out[2] = commit_bytes_;
    out[3] = pull_bytes_;
    out[4] = fenced_commits_;
    out[5] = live_members_;
    out[6] = idle_evictions_;
    out[7] = clock_;
    out[8] = log_dropped_;
  }

  // exact small-integer staleness counts: slots 0..kStaleSlots-1, plus one
  // overflow slot (the Python wrapper replays deltas into the registry's
  // log-bucket ps_commit_staleness histogram)
  static constexpr int kStaleSlots = 64;
  void staleness_hist(int64_t out[kStaleSlots + 1]) {
    std::lock_guard<std::mutex> g(center_mutex_);
    std::memcpy(out, stale_hist_, sizeof(stale_hist_));
  }

  // drain up to max_records commit-log records (oldest first), 5 int64
  // each: clock, worker (announced via 'T'; -1 if none), staleness,
  // CLOCK_MONOTONIC ns at apply start, apply duration ns.  The ring is
  // bounded: with nobody draining it, it simply wraps (oldest records
  // overwritten), so an untelemetered hub holds steady memory.
  int64_t drain_commits(int64_t* out, int64_t max_records) {
    std::lock_guard<std::mutex> g(center_mutex_);
    int64_t n = 0;
    while (n < max_records && log_count_ > 0) {
      const CommitRecord& r = commit_log_[size_t(log_head_)];
      out[n * 5 + 0] = r.clock;
      out[n * 5 + 1] = r.worker;
      out[n * 5 + 2] = r.staleness;
      out[n * 5 + 3] = r.t_ns;
      out[n * 5 + 4] = r.dur_ns;
      log_head_ = (log_head_ + 1) % kLogCapacity;
      --log_count_;
      ++n;
    }
    return n;
  }

  int64_t time_ns() const { return mono_ns(); }

 private:
  void accept_loop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;  // listener closed by stop()
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // kernel buffers sized to one full weights/commit frame (clamped to
      // [64 KiB, 8 MiB], matching networking.configure_socket): a
      // pipelined client must be able to park a whole commit in flight
      int64_t want = 13 + 4096;
      for (int64_t s : sizes_) want += 8 + s * int64_t(sizeof(float));
      int bufsz = int(std::min<int64_t>(std::max<int64_t>(want, 64 << 10), 8 << 20));
      ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
      ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
      if (idle_timeout_ms_ > 0) {
        // half-open liveness: a peer that dies without FIN must not park
        // this handler in recv() forever — the timed-out recv reads as a
        // dead peer and the connection is evicted (clients heartbeat on
        // idle to prove liveness; matches the Python hub's idle_timeout)
        timeval tv{};
        tv.tv_sec = idle_timeout_ms_ / 1000;
        tv.tv_usec = (idle_timeout_ms_ % 1000) * 1000;
        ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        // and sends: a half-open peer with a full TCP window must not
        // park the handler (and its membership slot) in write_all for
        // the kernel's multi-minute retransmission timeout — Python's
        // conn.settimeout() bounds both directions, so match it
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      }
      std::lock_guard<std::mutex> g(conn_mutex_);
      conn_fds_.push_back(fd);
      handler_threads_.emplace_back([this, fd] { handle_connection(fd); });
    }
  }

  bool recv_payload(int fd, std::vector<unsigned char>& payload,
                    bool* timed_out = nullptr) {
    unsigned char hdr[8];
    if (!read_exact(fd, hdr, 8, timed_out)) return false;
    uint64_t n = be64_decode(hdr);
    if (n > max_payload_) return false;  // garbage/oversized prefix: drop peer
    payload.resize(size_t(n));
    return n == 0 || read_exact(fd, payload.data(), size_t(n), timed_out);
  }

  bool send_simple(int fd, char action) {
    unsigned char buf[8 + 1 + 4];
    be64_encode(5, buf);
    buf[8] = static_cast<unsigned char>(action);
    be32_encode(0, buf + 9);
    return write_all(fd, buf, sizeof(buf));
  }

  bool send_weights(int fd, const std::vector<float>& snap) {
    uint64_t payload_len = 1 + 4;
    for (int64_t s : sizes_) payload_len += 8 + uint64_t(s) * sizeof(float);
    std::vector<unsigned char> buf(8 + payload_len);
    be64_encode(payload_len, buf.data());
    size_t off = 8;
    buf[off++] = 'W';
    be32_encode(uint32_t(sizes_.size()), buf.data() + off);
    off += 4;
    const float* src = snap.data();
    for (int64_t s : sizes_) {
      uint64_t nbytes = uint64_t(s) * sizeof(float);
      be64_encode(nbytes, buf.data() + off);
      off += 8;
      std::memcpy(buf.data() + off, src, nbytes);
      off += nbytes;
      src += s;
    }
    return write_all(fd, buf.data(), buf.size());
  }

  // parse a commit payload: validates tensor count/sizes against center_
  bool parse_commit(const std::vector<unsigned char>& payload, const float** delta_out) {
    if (payload.size() < 5) return false;
    uint32_t count = be32_decode(payload.data() + 1);
    if (count != sizes_.size()) return false;
    size_t off = 5;
    for (uint32_t i = 0; i < count; ++i) {
      if (off + 8 > payload.size()) return false;
      uint64_t nbytes = be64_decode(payload.data() + off);
      off += 8;
      if (nbytes != uint64_t(sizes_[i]) * sizeof(float)) return false;
      if (off + nbytes > payload.size()) return false;
      delta_out[i] = reinterpret_cast<const float*>(payload.data() + off);
      off += nbytes;
    }
    return off == payload.size();
  }

  // parse an int8 commit (action 'Q'): each tensor blob is a big-endian
  // f32 scale + int8 values; dequantize into qbuf (reused per
  // connection) and point delta_out at the float rows — identical math
  // to the Python hub's _decode_qdelta, so both hubs accept one client
  bool parse_qcommit(const std::vector<unsigned char>& payload,
                     std::vector<float>& qbuf, const float** delta_out) {
    if (payload.size() < 5) return false;
    uint32_t count = be32_decode(payload.data() + 1);
    if (count != sizes_.size()) return false;
    int64_t total = 0;
    for (int64_t s : sizes_) total += s;
    qbuf.resize(size_t(total));
    float* dst = qbuf.data();
    size_t off = 5;
    for (uint32_t i = 0; i < count; ++i) {
      if (off + 8 > payload.size()) return false;
      uint64_t nbytes = be64_decode(payload.data() + off);
      off += 8;
      if (nbytes != 4 + uint64_t(sizes_[i])) return false;
      if (off + nbytes > payload.size()) return false;
      uint32_t scale_be = be32_decode(payload.data() + off);
      float scale;
      std::memcpy(&scale, &scale_be, sizeof(scale));
      const auto* q = reinterpret_cast<const signed char*>(payload.data() + off + 4);
      delta_out[i] = dst;
      for (int64_t j = 0; j < sizes_[i]; ++j) dst[j] = float(q[j]) * scale;
      dst += sizes_[i];
      off += nbytes;
    }
    return off == payload.size();
  }

  // called under center_mutex_: append one commit-log record + the exact
  // staleness count the wrapper replays into the registry histogram
  void record_commit_locked(int64_t worker, int64_t staleness, int64_t t0_ns) {
    ++commits_;
    int slot = staleness < 0 ? 0
               : (staleness >= kStaleSlots ? kStaleSlots : int(staleness));
    ++stale_hist_[slot];
    CommitRecord r{clock_, worker, staleness, t0_ns, mono_ns() - t0_ns};
    size_t idx = size_t((log_head_ + log_count_) % kLogCapacity);
    commit_log_[idx] = r;
    if (log_count_ == kLogCapacity) {
      log_head_ = (log_head_ + 1) % kLogCapacity;  // wrap: drop oldest
      ++log_dropped_;  // surfaced via stats(): a truncated commit log
                       // must be visible, never silent
    } else {
      ++log_count_;
    }
  }

  // called under center_mutex_ (live_members_ shares that lock)
  void apply_commit(const float** delta, int64_t staleness) {
    float scale = 1.0f;
    if (mode_ == 1) {
      int n = num_workers_;
      if (elastic_) {
        // elastic ADAG: normalize by the LIVE committer count (join on
        // first commit, leave at disconnect/eviction), clamped to
        // num_workers — a permanently dead worker stops diluting the
        // survivors' deltas.  Zero members means this commit came via
        // commit_direct (inproc bypasses connections): fall back to the
        // static denominator, never to 1/1
        n = live_members_;
        if (n < 1) n = num_workers_;
        if (n > num_workers_) n = num_workers_;
      }
      scale = 1.0f / float(n);
    } else if (mode_ == 2) scale = 1.0f / float(staleness + 1);
    float* c = center_.data();
    for (size_t i = 0; i < sizes_.size(); ++i) {
      const float* d = delta[i];
      int64_t n = sizes_[i];
      for (int64_t j = 0; j < n; ++j) c[j] += scale * d[j];
      c += n;
    }
  }

  // 'T' reply: action + one 8-byte tensor carrying this hub's
  // CLOCK_MONOTONIC nanoseconds, sampled as late as possible before the
  // send so the client's NTP-style midpoint estimate is tight
  bool send_time(int fd) {
    unsigned char buf[8 + 1 + 4 + 8 + 8];
    be64_encode(1 + 4 + 8 + 8, buf);
    buf[8] = 'T';
    be32_encode(1, buf + 9);
    be64_encode(8, buf + 13);
    be64_encode(uint64_t(mono_ns()), buf + 21);
    return write_all(fd, buf, sizeof(buf));
  }

  void handle_connection(int fd) {
    int64_t last_pull_clock;
    {
      // connections born after a restore start AT the fence: a commit
      // before the first pull is stale relative to the restart point,
      // not to clock zero of a previous incarnation
      std::lock_guard<std::mutex> g(center_mutex_);
      last_pull_clock = clock_fence_;
    }
    bool joined = false;
    int64_t ctx_worker = -1;  // trace context announced via 'T'
    std::vector<unsigned char> payload;
    std::vector<const float*> delta(sizes_.size());
    std::vector<float> qbuf;
    std::vector<float> snap;
    bool timed_out = false;
    while (running_.load()) {
      if (!recv_payload(fd, payload, &timed_out) || payload.empty()) break;
      char action = char(payload[0]);
      if (action == 'P') {
        {
          // clock read and center snapshot must be ONE critical section:
          // a commit landing between them would make the snapshot newer
          // than the recorded clock and overstate DynSGD staleness
          std::lock_guard<std::mutex> g(center_mutex_);
          last_pull_clock = clock_;
          snap = center_;
          ++pulls_;
          pull_bytes_ += center_bytes_;
        }
        if (!send_weights(fd, snap)) break;
      } else if (action == 'C' || action == 'Q') {
        if (action == 'C' ? !parse_commit(payload, delta.data())
                          : !parse_qcommit(payload, qbuf, delta.data())) break;
        {
          std::lock_guard<std::mutex> g(center_mutex_);
          if (!joined) {
            // first commit = this peer is a worker (pull-only readers
            // never join); membership drives the elastic denominator
            joined = true;
            ++live_members_;
          }
          int64_t staleness = clock_ - last_pull_clock;
          int64_t t0 = mono_ns();
          apply_commit(delta.data(), staleness);
          record_commit_locked(ctx_worker, staleness, t0);
          // payload bytes net of framing overhead (5-byte header + one
          // 8-byte prefix per tensor) — the Python hub's accounting
          commit_bytes_ += int64_t(payload.size()) - 5 - 8 * int64_t(sizes_.size());
          ++clock_;
        }
        num_updates_.fetch_add(1);
        if (!send_simple(fd, 'A')) break;
      } else if (action == 'H') {  // heartbeat: liveness proof, acked
        if (!send_simple(fd, 'A')) break;
      } else if (action == 'T') {
        // trace-context announce: remember the worker for commit-log
        // attribution, reply with this hub's monotonic clock (the
        // client's offset estimate rides the round trip)
        if (payload.size() > 13) {
          uint64_t blob_len = be64_decode(payload.data() + 5);
          if (13 + blob_len <= payload.size())
            ctx_worker = json_int_field(payload.data() + 13, size_t(blob_len),
                                        "worker_id", -1);
        }
        if (!send_time(fd)) break;
      } else {  // 'B' or unknown -> close
        break;
      }
    }
    if (timed_out) {
      std::lock_guard<std::mutex> g(center_mutex_);
      ++idle_evictions_;
    }
    if (joined) {
      std::lock_guard<std::mutex> g(center_mutex_);
      --live_members_;
    }
    ::close(fd);
    // forget the fd so stop() can't shutdown() a future unrelated socket
    // that reuses this descriptor number
    std::lock_guard<std::mutex> g(conn_mutex_);
    conn_fds_.erase(std::remove(conn_fds_.begin(), conn_fds_.end(), fd), conn_fds_.end());
  }

  int requested_port_;
  int bound_port_ = -1;
  int mode_;
  int num_workers_;
  bool elastic_;
  int idle_timeout_ms_;
  uint64_t max_payload_ = 0;
  int live_members_ = 0;  // guarded by center_mutex_
  // telemetry (all guarded by center_mutex_; drained via dk_ps_stats /
  // dk_ps_staleness_hist / dk_ps_drain_commits)
  struct CommitRecord {
    int64_t clock, worker, staleness, t_ns, dur_ns;
  };
  static constexpr int64_t kLogCapacity = 8192;
  int64_t commits_ = 0, pulls_ = 0;
  int64_t commit_bytes_ = 0, pull_bytes_ = 0;
  int64_t fenced_commits_ = 0, idle_evictions_ = 0;
  int64_t center_bytes_ = 0;
  int64_t stale_hist_[kStaleSlots + 1] = {};
  std::vector<CommitRecord> commit_log_ = std::vector<CommitRecord>(size_t(kLogCapacity));
  int64_t log_head_ = 0, log_count_ = 0, log_dropped_ = 0;
  std::vector<int64_t> sizes_;
  std::vector<float> center_;
  std::mutex center_mutex_;
  int64_t clock_ = 0;
  int64_t clock_fence_ = 0;  // guarded by center_mutex_; armed by restore()
  std::atomic<int64_t> num_updates_{0};
  std::atomic<bool> running_{false};
  int listen_fd_ = -1;
  std::thread accept_thread_;
  std::mutex conn_mutex_;
  std::vector<int> conn_fds_;
  std::vector<std::thread> handler_threads_;
};

}  // namespace

extern "C" {

void* dk_ps_create(int port, int num_tensors, const int64_t* sizes, int mode, int num_workers,
                   int elastic, int idle_timeout_ms) {
  return new ParameterServer(port, num_tensors, sizes, mode, num_workers, elastic,
                             idle_timeout_ms);
}

int dk_ps_start(void* ps) { return static_cast<ParameterServer*>(ps)->start(); }
void dk_ps_stop(void* ps) { static_cast<ParameterServer*>(ps)->stop(); }
void dk_ps_get_weights(void* ps, float* out) { static_cast<ParameterServer*>(ps)->get_weights(out); }
void dk_ps_set_weights(void* ps, const float* in) { static_cast<ParameterServer*>(ps)->set_weights(in); }
int64_t dk_ps_num_updates(void* ps) { return static_cast<ParameterServer*>(ps)->num_updates(); }
int dk_ps_port(void* ps) { return static_cast<ParameterServer*>(ps)->port(); }
int64_t dk_ps_pull(void* ps, float* out) { return static_cast<ParameterServer*>(ps)->pull_direct(out); }
int64_t dk_ps_snapshot(void* ps, float* out) {
  return static_cast<ParameterServer*>(ps)->snapshot_direct(out);
}
void dk_ps_commit(void* ps, const float* flat, int64_t last_pull_clock) {
  static_cast<ParameterServer*>(ps)->commit_direct(flat, last_pull_clock);
}
// commit_direct with the caller's trace-context worker id (inproc workers
// have no connection to announce 'T' on); dk_ps_commit stays as the
// uncontexted twin so pre-existing callers keep their ABI
void dk_ps_commit_ctx(void* ps, const float* flat, int64_t last_pull_clock,
                      int64_t worker) {
  static_cast<ParameterServer*>(ps)->commit_direct(flat, last_pull_clock, worker);
}
void dk_ps_stats(void* ps, int64_t* out8) { static_cast<ParameterServer*>(ps)->stats(out8); }
void dk_ps_staleness_hist(void* ps, int64_t* out65) {
  static_cast<ParameterServer*>(ps)->staleness_hist(out65);
}
int64_t dk_ps_drain_commits(void* ps, int64_t* out, int64_t max_records) {
  return static_cast<ParameterServer*>(ps)->drain_commits(out, max_records);
}
int64_t dk_ps_time_ns(void* ps) { return static_cast<ParameterServer*>(ps)->time_ns(); }
void dk_ps_restore(void* ps, const float* flat, int64_t clock, int64_t num_updates) {
  static_cast<ParameterServer*>(ps)->restore(flat, clock, num_updates);
}
void dk_ps_destroy(void* ps) { delete static_cast<ParameterServer*>(ps); }

}  // extern "C"
