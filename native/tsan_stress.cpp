// ThreadSanitizer stress driver for the C++ PS hub (ISSUE 14).
//
// Compiled TOGETHER with ps_server.cpp under -fsanitize=thread by the
// slow/tsan-marked cell in tests/test_analysis.py, then run: a
// sparse+adaptive primary with a hot-standby replica, hammered
// concurrently by inproc committers, raw-socket pull/commit clients, a
// sparse S/V/U client, a G/Y backpressure client, M health reports, a
// telemetry poller, two shm-ring clients ('Z' handshake then P/C over
// shared memory, ISSUE 18) and a raw SPSC ring producer/consumer pair —
// every production path of the native hub under one data-race
// microscope.  Any TSAN report fails the test (the cell runs with
// TSAN_OPTIONS=exitcode=66 and greps stderr).
//
// The driver only uses the extern "C" API plus the public wire format
// (frames byte-identical to networking.encode_tensors), so it compiles
// against ps_server.cpp without any header.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* dk_ps_create(int port, int num_tensors, const int64_t* sizes, int mode,
                   int num_workers, int elastic, int idle_timeout_ms,
                   int num_sparse, const int32_t* sparse_leaves,
                   const int64_t* sparse_dims, int adaptive,
                   int64_t max_payload);
void dk_ps_set_replica_of(void* ps, const char* host, int port, int retries,
                          int backoff_ms);
int dk_ps_start(void* ps);
void dk_ps_stop(void* ps);
int64_t dk_ps_pull(void* ps, float* out);
int dk_ps_commit_ctx(void* ps, const float* flat, int64_t last_pull_clock,
                     int64_t worker);
void dk_ps_stats(void* ps, int64_t* out);
void dk_ps_staleness_hist(void* ps, int64_t* out65);
int64_t dk_ps_drain_commits(void* ps, int64_t* out, int64_t max_records);
int64_t dk_ps_next_health(void* ps, unsigned char* out, int64_t cap);
void dk_ps_set_rate_scale(void* ps, int64_t worker, double scale,
                          int64_t expires_ns);
int64_t dk_ps_num_updates(void* ps);
int64_t dk_ps_time_ns(void* ps);
int dk_ps_wait_synced(void* ps, int64_t timeout_ms);
int dk_ps_promoted(void* ps);
void dk_ps_destroy(void* ps);
// shm transport (ISSUE 18)
void dk_ps_shm_attach(void* ps, const char* dir);
void* dk_shm_ring_create(const char* path, int producer, uint64_t capacity);
void* dk_shm_ring_open(const char* path, int producer);
long long dk_shm_ring_write(void* ring, const void* buf, long long n,
                            int timeout_ms);
long long dk_shm_ring_read(void* ring, void* buf, long long cap,
                           int timeout_ms);
void dk_shm_ring_close(void* ring);
void dk_shm_ring_destroy(void* ring);
}

namespace {

constexpr int64_t kSizes[2] = {32, 16 * 4};  // leaf 1 = 16x4 sparse table
constexpr int32_t kSparseLeaves[1] = {1};
constexpr int64_t kSparseDims[1] = {4};
constexpr int64_t kTotal = kSizes[0] + kSizes[1];

std::atomic<bool> g_stop{false};
std::atomic<int> g_errors{0};

void fail(const char* what) {
  std::fprintf(stderr, "driver error: %s\n", what);
  g_errors.fetch_add(1);
}

// -- minimal wire helpers (big-endian framing, encode_tensors layout) --------

void put_u64(std::string& b, uint64_t v) {
  for (int i = 7; i >= 0; --i) b.push_back(char((v >> (8 * i)) & 0xff));
}
void put_u32(std::string& b, uint32_t v) {
  for (int i = 3; i >= 0; --i) b.push_back(char((v >> (8 * i)) & 0xff));
}

std::string frame(char action, const std::vector<std::string>& blobs) {
  std::string payload;
  payload.push_back(action);
  put_u32(payload, uint32_t(blobs.size()));
  for (const auto& b : blobs) {
    put_u64(payload, b.size());
    payload += b;
  }
  std::string out;
  put_u64(out, payload.size());
  return out + payload;
}

bool send_all(int fd, const std::string& data) {
  size_t off = 0;
  while (off < data.size()) {
    ssize_t n = ::send(fd, data.data() + off, data.size() - off, 0);
    if (n <= 0) return false;
    off += size_t(n);
  }
  return true;
}

bool recv_all(int fd, char* out, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = ::recv(fd, out + off, n - off, 0);
    if (r <= 0) return false;
    off += size_t(r);
  }
  return true;
}

// receive one frame, returning just the action byte (payload discarded)
bool recv_frame_action(int fd, char* action) {
  char hdr[8];
  if (!recv_all(fd, hdr, 8)) return false;
  uint64_t len = 0;
  for (int i = 0; i < 8; ++i) len = (len << 8) | uint8_t(hdr[i]);
  if (len < 5 || len > (64u << 20)) return false;
  std::vector<char> payload(len);
  if (!recv_all(fd, payload.data(), len)) return false;
  *action = payload[0];
  return true;
}

// receive one frame keeping the whole payload (action + count + blobs) —
// the 'Z' handshake needs the offer's path blobs, not just the action byte
bool recv_frame(int fd, std::string* payload) {
  char hdr[8];
  if (!recv_all(fd, hdr, 8)) return false;
  uint64_t len = 0;
  for (int i = 0; i < 8; ++i) len = (len << 8) | uint8_t(hdr[i]);
  if (len < 5 || len > (64u << 20)) return false;
  payload->resize(len);
  return recv_all(fd, &(*payload)[0], len);
}

// -- shm ring helpers (dk_shm_ring_* extern "C" surface) ---------------------

bool ring_send_all(void* ring, const std::string& data) {
  return dk_shm_ring_write(ring, data.data(), (long long)data.size(), 5000) ==
         (long long)data.size();
}

bool ring_recv_all(void* ring, char* out, size_t n) {
  size_t off = 0;
  while (off < n) {
    long long r = dk_shm_ring_read(ring, out + off, (long long)(n - off), 5000);
    if (r <= 0) return false;
    off += size_t(r);
  }
  return true;
}

// ring twin of recv_frame_action: one frame off the ring, payload discarded
bool ring_recv_frame_action(void* ring, char* action) {
  char hdr[8];
  if (!ring_recv_all(ring, hdr, 8)) return false;
  uint64_t len = 0;
  for (int i = 0; i < 8; ++i) len = (len << 8) | uint8_t(hdr[i]);
  if (len < 5 || len > (64u << 20)) return false;
  std::vector<char> payload(len);
  if (!ring_recv_all(ring, payload.data(), len)) return false;
  *action = payload[0];
  return true;
}

int dial(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

std::string f32_blob(const std::vector<float>& v) {
  return std::string(reinterpret_cast<const char*>(v.data()),
                     v.size() * sizeof(float));
}

// -- stress legs -------------------------------------------------------------

void inproc_leg(void* ps, int64_t worker) {
  std::vector<float> buf(kTotal), delta(kTotal, 1e-3f);
  while (!g_stop.load(std::memory_order_relaxed)) {
    int64_t clock = dk_ps_pull(ps, buf.data());
    (void)dk_ps_commit_ctx(ps, delta.data(), clock, worker);
  }
}

void socket_leg(int port, bool with_health) {
  int fd = dial(port);
  if (fd < 0) return fail("socket_leg dial");
  const std::string pull = frame('P', {});
  const std::string commit =
      frame('C', {f32_blob(std::vector<float>(kSizes[0], 1e-3f)),
                  f32_blob(std::vector<float>(size_t(kSizes[1]), 1e-3f))});
  const std::string health = frame(
      'M', {std::string("{\"worker\": \"7\", \"windows_total\": 1, "
                        "\"window_wall_ms\": 1.0}")});
  char action = 0;
  int step = 0;
  while (!g_stop.load(std::memory_order_relaxed)) {
    if (!send_all(fd, pull) || !recv_frame_action(fd, &action) ||
        action != 'W')
      break;  // hub stopping under us is fine mid-run
    if (!send_all(fd, commit) || !recv_frame_action(fd, &action) ||
        action != 'A')
      break;
    if (with_health && (step++ % 8) == 0) {
      if (!send_all(fd, health) || !recv_frame_action(fd, &action) ||
          action != 'A')
        break;
    }
  }
  send_all(fd, frame('B', {}));
  ::close(fd);
}

void sparse_leg(int port) {
  int fd = dial(port);
  if (fd < 0) return fail("sparse_leg dial");
  int64_t ids[3] = {1, 5, 9};
  std::string id_blob(reinterpret_cast<const char*>(ids), sizeof(ids));
  const std::string spull = frame('S', {id_blob});
  // U commit: dense leaf full f32 blob, then (ids, rows) for the table
  const std::string scommit = frame(
      'U', {f32_blob(std::vector<float>(kSizes[0], 1e-3f)), id_blob,
            f32_blob(std::vector<float>(3 * 4, 1e-3f))});
  char action = 0;
  while (!g_stop.load(std::memory_order_relaxed)) {
    if (!send_all(fd, spull) || !recv_frame_action(fd, &action) ||
        action != 'V')
      break;
    if (!send_all(fd, scommit) || !recv_frame_action(fd, &action) ||
        action != 'A')
      break;
  }
  send_all(fd, frame('B', {}));
  ::close(fd);
}

void backpressure_leg(int port) {
  while (!g_stop.load(std::memory_order_relaxed)) {
    int fd = dial(port);
    if (fd < 0) return fail("backpressure_leg dial");
    std::string waits(8, '\0');  // 8-byte BE zero: a fresh announcer
    char action = 0;
    if (!send_all(fd, frame('G', {waits})) ||
        !recv_frame_action(fd, &action) || action != 'Y') {
      ::close(fd);
      break;
    }
    send_all(fd, frame('B', {}));
    ::close(fd);
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
}

// Raw SPSC ring under TSAN: a producer thread streaming a byte counter
// through a deliberately tiny ring (forcing wraparound and ring-full parks)
// while this thread consumes and verifies the sequence, then EOF via
// dk_shm_ring_close.  Exercises the head/tail acquire/release protocol and
// the closed-flag wakeups with no hub in the loop.
void ring_pair_leg(const std::string& path) {
  void* prod = dk_shm_ring_create(path.c_str(), /*producer=*/1,
                                  /*capacity=*/1 << 12);
  if (!prod) return fail("ring_pair create");
  void* cons = dk_shm_ring_open(path.c_str(), /*producer=*/0);
  if (!cons) {
    dk_shm_ring_destroy(prod);
    return fail("ring_pair open");
  }
  ::unlink(path.c_str());  // mappings keep the memory alive
  std::atomic<uint64_t> sent{0};
  std::thread producer([&] {
    char chunk[777];  // odd size so frames straddle the wrap point
    uint64_t seq = 0;
    while (!g_stop.load(std::memory_order_relaxed)) {
      for (auto& c : chunk) c = char(seq++ & 0xff);
      if (dk_shm_ring_write(prod, chunk, sizeof(chunk), 5000) < 0)
        return fail("ring_pair write");
      sent.fetch_add(sizeof(chunk));
    }
    dk_shm_ring_close(prod);  // producer EOF wakes the parked consumer
  });
  char buf[1024];
  uint64_t got = 0, expect = 0;
  bool ok = true;
  for (;;) {
    long long r = dk_shm_ring_read(cons, buf, sizeof(buf), 5000);
    if (r <= 0) break;  // 0 = producer closed and drained
    for (long long i = 0; i < r; ++i)
      if (uint8_t(buf[i]) != uint8_t(expect++ & 0xff)) ok = false;
    got += uint64_t(r);
  }
  producer.join();
  if (!ok) fail("ring_pair byte mismatch");
  if (got != sent.load()) fail("ring_pair byte count");
  dk_shm_ring_destroy(cons);
  dk_shm_ring_destroy(prod);
}

// Full 'Z' handshake client: negotiate rings over TCP, then run the same
// P/C traffic as socket_leg with every frame crossing shared memory — the
// hub's ring producer racing our consumer (and vice versa) under TSAN.
void shm_leg(int port) {
  int fd = dial(port);
  if (fd < 0) return fail("shm_leg dial");
  std::string req(1, '\x01');  // SHM_VERSION
  put_u64(req, 1 << 16);       // capacity hint
  std::string offer;
  if (!send_all(fd, frame('Z', {req})) || !recv_frame(fd, &offer) ||
      offer[0] != 'Z') {
    ::close(fd);
    return fail("shm_leg handshake");
  }
  uint32_t count = 0;
  for (int i = 1; i <= 4; ++i) count = (count << 8) | uint8_t(offer[i]);
  if (count != 2) {  // 0 blobs = hub declined; shm_dir was attached, so fail
    ::close(fd);
    return fail("shm_leg declined");
  }
  std::string paths[2];  // [0]=c2h (we produce), [1]=h2c (we consume)
  size_t off = 5;
  for (int b = 0; b < 2; ++b) {
    uint64_t blen = 0;
    for (int i = 0; i < 8; ++i) blen = (blen << 8) | uint8_t(offer[off + i]);
    off += 8;
    paths[b] = offer.substr(off, blen);
    off += blen;
  }
  void* tx = dk_shm_ring_open(paths[0].c_str(), /*producer=*/1);
  void* rx = dk_shm_ring_open(paths[1].c_str(), /*producer=*/0);
  if (!tx || !rx) {
    send_all(fd, frame('Z', {std::string(1, '\x00')}));
    if (tx) dk_shm_ring_destroy(tx);
    if (rx) dk_shm_ring_destroy(rx);
    ::close(fd);
    return fail("shm_leg ring open");
  }
  if (!send_all(fd, frame('Z', {std::string(1, '\x01')}))) {
    dk_shm_ring_destroy(tx);
    dk_shm_ring_destroy(rx);
    ::close(fd);
    return fail("shm_leg confirm");
  }
  const std::string pull = frame('P', {});
  const std::string commit =
      frame('C', {f32_blob(std::vector<float>(kSizes[0], 1e-3f)),
                  f32_blob(std::vector<float>(size_t(kSizes[1]), 1e-3f))});
  char action = 0;
  while (!g_stop.load(std::memory_order_relaxed)) {
    if (!ring_send_all(tx, pull) || !ring_recv_frame_action(rx, &action) ||
        action != 'W')
      break;  // hub stopping under us is fine mid-run
    if (!ring_send_all(tx, commit) || !ring_recv_frame_action(rx, &action) ||
        action != 'A')
      break;
  }
  ring_send_all(tx, frame('B', {}));
  dk_shm_ring_close(tx);  // producer EOF so the hub handler exits cleanly
  dk_shm_ring_destroy(tx);
  dk_shm_ring_destroy(rx);
  ::close(fd);
}

void telemetry_leg(void* ps) {
  int64_t stats[32], hist[65], recs[5 * 64];  // 26 StatSlots, 5-wide records
  unsigned char health[4096];
  int64_t worker = 0;
  while (!g_stop.load(std::memory_order_relaxed)) {
    dk_ps_stats(ps, stats);
    dk_ps_staleness_hist(ps, hist);
    (void)dk_ps_drain_commits(ps, recs, 64);
    while (dk_ps_next_health(ps, health, sizeof(health)) > 0) {
    }
    (void)dk_ps_num_updates(ps);
    dk_ps_set_rate_scale(ps, worker++ % 4, 0.5,
                         dk_ps_time_ns(ps) + 1000000000LL);
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
}

}  // namespace

int main() {
  char shm_template[] = "/dev/shm/dktsanXXXXXX";
  char tmp_template[] = "/tmp/dktsanXXXXXX";
  char* shm_dir = ::mkdtemp(shm_template);
  if (!shm_dir) shm_dir = ::mkdtemp(tmp_template);
  if (!shm_dir) {
    std::fprintf(stderr, "driver error: mkdtemp failed\n");
    return 2;
  }
  void* primary = dk_ps_create(0, 2, kSizes, /*mode=*/0, /*num_workers=*/4,
                               /*elastic=*/1, /*idle_timeout_ms=*/0,
                               /*num_sparse=*/1, kSparseLeaves, kSparseDims,
                               /*adaptive=*/1, /*max_payload=*/1 << 20);
  dk_ps_shm_attach(primary, shm_dir);  // enables the 'Z' arm for shm_leg
  int port = dk_ps_start(primary);
  if (port <= 0) {
    std::fprintf(stderr, "driver error: primary failed to bind\n");
    return 2;
  }
  void* standby = dk_ps_create(0, 2, kSizes, 0, 4, 1, 0, 1, kSparseLeaves,
                               kSparseDims, 0, 1 << 20);
  dk_ps_set_replica_of(standby, "127.0.0.1", port, /*retries=*/3,
                       /*backoff_ms=*/50);
  int sport = dk_ps_start(standby);
  if (sport <= 0) {
    std::fprintf(stderr, "driver error: standby failed to bind\n");
    return 2;
  }

  std::vector<std::thread> threads;
  threads.emplace_back(inproc_leg, primary, 0);
  threads.emplace_back(inproc_leg, primary, 1);
  threads.emplace_back(socket_leg, port, false);
  threads.emplace_back(socket_leg, port, true);
  threads.emplace_back(sparse_leg, port);
  threads.emplace_back(backpressure_leg, port);
  threads.emplace_back(telemetry_leg, primary);
  threads.emplace_back(shm_leg, port);
  threads.emplace_back(shm_leg, port);  // two shm attaches racing one hub
  threads.emplace_back(ring_pair_leg,
                       std::string(shm_dir) + "/ring-pair.raw");

  if (dk_ps_wait_synced(standby, 5000) != 1) fail("standby never synced");
  std::this_thread::sleep_for(std::chrono::milliseconds(1500));
  g_stop.store(true);
  for (auto& t : threads) t.join();

  if (dk_ps_promoted(standby) != 0) fail("standby promoted mid-stress");
  dk_ps_stop(standby);
  dk_ps_stop(primary);
  dk_ps_destroy(standby);
  dk_ps_destroy(primary);
  ::rmdir(shm_dir);  // ring files were unlinked at handshake/creation time
  if (g_errors.load() != 0) return 3;
  std::printf("tsan stress complete\n");
  return 0;
}
