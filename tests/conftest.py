"""Test harness: simulate an 8-chip slice on CPU.

This is the multi-node test strategy the reference never had (SURVEY §4):
``--xla_force_host_platform_device_count=8`` gives 8 virtual XLA devices,
so every mesh/collective path runs in CI without TPU hardware.  Must be
set before jax initializes — hence here, at conftest import time.
"""

import os
import sys
import time

# repo-root modules (bench.py, __graft_entry__.py) are test subjects too;
# make them importable regardless of the CWD pytest is invoked from
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# -- tier-1 budget tripwire (ISSUE 12 satellite) -------------------------------
# The 'not slow' subset runs under a hard 870 s timeout the ROADMAP flags
# as structurally thin (776 s measured at PR-10 HEAD).  Warn LOUDLY at
# 700 s so the margin erodes in plain sight instead of flaking first.
# DK_TIER1_WARN_S overrides the threshold (testing the tripwire itself).
TIER1_WARN_S = float(os.environ.get("DK_TIER1_WARN_S", "700"))
_session_t0 = time.monotonic()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    elapsed = time.monotonic() - _session_t0
    markexpr = str(config.getoption("markexpr", "") or "")
    if "not slow" in markexpr and elapsed > TIER1_WARN_S:
        terminalreporter.write_sep(
            "=", "tier-1 budget tripwire", yellow=True, bold=True)
        terminalreporter.write_line(
            f"WARNING: the 'not slow' suite took {elapsed:.0f}s — past the "
            f"{TIER1_WARN_S:.0f}s tripwire and closing on the 870s timeout "
            f"budget.  Slow-mark the newest heavyweight tests or split the "
            f"suite before it flakes (ROADMAP operational warning, PR 10).",
            yellow=True)

def require_tool(*names):
    """Shared skip-guard for cells that shell out to optional toolchain
    binaries (g++, cppcheck, clang-tidy, ...): skip — not fail — in
    containers that don't ship them.  One helper so the
    cppcheck/clang-tidy, -Wall/-Wextra/-Werror and TSAN cells can never
    drift on how 'tool missing' is decided (ISSUE 14 satellite)."""
    import shutil

    import pytest as _pytest

    for name in names:
        if shutil.which(name) is None:
            _pytest.skip(f"no {name} in this container")


from distkeras_tpu.platform import pin_cpu_devices  # noqa: E402

pin_cpu_devices(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def toy_classification():
    """Linearly-separable 2-class blobs: learnable in a few SGD steps."""
    rng = np.random.default_rng(0)
    n = 1024
    half = n // 2
    x0 = rng.normal(loc=-2.0, scale=1.0, size=(half, 8)).astype(np.float32)
    x1 = rng.normal(loc=+2.0, scale=1.0, size=(half, 8)).astype(np.float32)
    x = np.concatenate([x0, x1])
    y = np.concatenate([np.zeros(half, np.int32), np.ones(half, np.int32)])
    perm = rng.permutation(n)
    return x[perm], y[perm]


@pytest.fixture(scope="session")
def toy_dataset(toy_classification):
    from distkeras_tpu.data.dataset import Dataset

    x, y = toy_classification
    onehot = np.eye(2, dtype=np.float32)[y]
    return Dataset({"features": x, "label": onehot, "label_index": y})
