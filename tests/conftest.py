"""Test harness: simulate an 8-chip slice on CPU.

This is the multi-node test strategy the reference never had (SURVEY §4):
``--xla_force_host_platform_device_count=8`` gives 8 virtual XLA devices,
so every mesh/collective path runs in CI without TPU hardware.  Must be
set before jax initializes — hence here, at conftest import time.
"""

import os
import sys

# repo-root modules (bench.py, __graft_entry__.py) are test subjects too;
# make them importable regardless of the CWD pytest is invoked from
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distkeras_tpu.platform import pin_cpu_devices  # noqa: E402

pin_cpu_devices(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def toy_classification():
    """Linearly-separable 2-class blobs: learnable in a few SGD steps."""
    rng = np.random.default_rng(0)
    n = 1024
    half = n // 2
    x0 = rng.normal(loc=-2.0, scale=1.0, size=(half, 8)).astype(np.float32)
    x1 = rng.normal(loc=+2.0, scale=1.0, size=(half, 8)).astype(np.float32)
    x = np.concatenate([x0, x1])
    y = np.concatenate([np.zeros(half, np.int32), np.ones(half, np.int32)])
    perm = rng.permutation(n)
    return x[perm], y[perm]


@pytest.fixture(scope="session")
def toy_dataset(toy_classification):
    from distkeras_tpu.data.dataset import Dataset

    x, y = toy_classification
    onehot = np.eye(2, dtype=np.float32)[y]
    return Dataset({"features": x, "label": onehot, "label_index": y})
