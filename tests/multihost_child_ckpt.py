"""Child process for the 2-host CHECKPOINT + ENSEMBLE test (not collected
by pytest).

Closes the engine's last two multi-process NotImplementedErrors: a
checkpoint written mid-run on a 2-process mesh (all processes all-gather,
process 0 writes) must resume bit-exactly, and EnsembleTrainer must
return the full per-replica ensemble on EVERY process.

Usage: python multihost_child_ckpt.py <process_id> <num_processes> <port> <ckpt_dir>
"""

import json
import sys

proc_id, nprocs, port, ckdir = (int(sys.argv[1]), int(sys.argv[2]),
                                sys.argv[3], sys.argv[4])

from distkeras_tpu.runtime.launcher import initialize_multihost  # noqa: E402

initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=nprocs, process_id=proc_id,
                     cpu_devices_per_process=2)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from distkeras_tpu.checkpoint import Checkpointer  # noqa: E402
from distkeras_tpu.models.base import ModelSpec  # noqa: E402
from distkeras_tpu.trainers import ADAG, EnsembleTrainer  # noqa: E402
from distkeras_tpu.utils import flatten_weights  # noqa: E402
from tests.multihost_engine_common import make_toy  # noqa: E402

assert jax.process_count() == nprocs
dataset = make_toy()
spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                 input_shape=(8,))
kwargs = dict(loss="categorical_crossentropy", worker_optimizer="sgd",
              learning_rate=0.05, num_workers=2 * nprocs, batch_size=8,
              communication_window=2)


def center_sum(model):
    flat, _ = flatten_weights(model.params)
    return float(sum(np.abs(np.asarray(w)).sum() for w in flat))


# uninterrupted 3-epoch reference on this same 2-process mesh
ref = ADAG(spec, num_epoch=3, **kwargs)
ref_model = ref.train(dataset, shuffle=False)

# 1 epoch with a checkpoint (all processes gather, process 0 writes) ...
ck = Checkpointer(ckdir, keep=2)
ADAG(spec, num_epoch=1, **kwargs).train(dataset, shuffle=False, checkpointer=ck)
# ... then a FRESH trainer resumes from the shared spool to 3 epochs
resumed = ADAG(spec, num_epoch=3, **kwargs)
resumed_model = resumed.train(dataset, shuffle=False, checkpointer=ck)

# ensemble across the process boundary: every process gets every replica
ens = EnsembleTrainer(spec, num_epoch=2, **kwargs)
models = ens.train(dataset, shuffle=False)

print("RESULT " + json.dumps({
    "process": proc_id,
    "ref_losses": [round(float(x), 8) for x in ref.history],
    "resumed_losses": [round(float(x), 8) for x in resumed.history],
    "ref_center_sum": round(center_sum(ref_model), 6),
    "resumed_center_sum": round(center_sum(resumed_model), 6),
    "epochs_done": int(ck.metadata()["metadata"]["epochs_done"]),
    "ensemble_sums": [round(center_sum(m), 6) for m in models],
}), flush=True)
