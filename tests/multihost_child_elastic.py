"""Child process for the 2-host ELASTIC-FAMILY engine test (not collected
by pytest).

The round-3 verdict's weak #5: the multi-process engine proof covered ADAG
only — the elastic family's distinctive state (per-replica DIVERGENT local
weights, SURVEY §7 "hard parts") and DynSGD's per-replica rank-scaled
commits had never crossed a process boundary.  This child joins a
2-process CPU runtime and trains AEASGD and DynSGD on a 4-replica mesh
spanning the boundary, printing losses, center digests, and a replicated
per-replica local-norm vector the parent asserts against the
single-process reference.

Usage: python multihost_child_elastic.py <process_id> <num_processes> <port>
"""

import json
import sys

proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

from distkeras_tpu.runtime.launcher import initialize_multihost  # noqa: E402

initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=nprocs, process_id=proc_id,
                     cpu_devices_per_process=2)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from tests.multihost_engine_common import make_toy, run_engine  # noqa: E402

assert jax.process_count() == nprocs
assert len(jax.devices()) == 2 * nprocs

dataset = make_toy()
out = {"process": proc_id}
for kind in ("aeasgd", "dynsgd"):
    losses, center, local_norms = run_engine(kind, dataset,
                                             num_workers=2 * nprocs)
    out[kind] = {
        "losses": [round(float(x), 8) for x in losses],
        "center_sum": float(sum(np.abs(w).sum() for w in center)),
        "center_digest": [float(np.asarray(w).ravel()[:3].sum()) for w in center],
        "local_norms": [round(x, 6) for x in local_norms],
    }

print("RESULT " + json.dumps(out), flush=True)
