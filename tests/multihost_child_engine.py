"""Child process for the 2-host ENGINE test (not collected by pytest).

Joins a 2-process CPU runtime and trains the real sync trainer family
(ADAG through DistributedTrainer/WindowEngine) on a 4-replica mesh that
spans the process boundary — the round-2 verdict's gap: the engine had
only ever run single-process.  Prints per-epoch losses and a digest of the
trained center so the parent can assert multi-process == single-process.

Usage: python multihost_child_engine.py <process_id> <num_processes> <port>
"""

import json
import sys

proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

from distkeras_tpu.runtime.launcher import initialize_multihost  # noqa: E402

initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=nprocs, process_id=proc_id,
                     cpu_devices_per_process=2)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from tests.multihost_engine_common import make_toy, run_adag  # noqa: E402

assert jax.process_count() == nprocs
assert len(jax.devices()) == 2 * nprocs

dataset = make_toy()
losses, center = run_adag(dataset, num_workers=2 * nprocs)

# the OTHER multi-process engine paths: averaged_model's compiled
# cross-host mean reduction, and the in-program steady-state measurement
from distkeras_tpu.models.base import ModelSpec  # noqa: E402
from distkeras_tpu.trainers import AveragingTrainer  # noqa: E402

spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                 input_shape=(8,))
avg_trainer = AveragingTrainer(spec, loss="categorical_crossentropy",
                               worker_optimizer="sgd", learning_rate=0.05,
                               num_workers=2 * nprocs, batch_size=8,
                               num_epoch=2)
avg_model = avg_trainer.train(dataset, shuffle=False)
avg_sum = float(sum(np.abs(np.asarray(w)).sum()
                    for w in jax.tree.leaves(avg_model.params)))

engine = avg_trainer.engine
chunk = next(iter(dataset.chunked_epoch(8 * 2 * nprocs, ["features", "label"],
                                        window=1, chunk_windows=4)))
rate = engine.steady_state_rate(engine.init_state(avg_model),
                                chunk["features"], chunk["label"],
                                reps=2, repeat=1)

print("RESULT " + json.dumps({
    "process": proc_id,
    "losses": [round(float(x), 8) for x in losses],
    "center_sum": float(sum(np.abs(w).sum() for w in center)),
    "center_digest": [float(np.asarray(w).ravel()[:3].sum()) for w in center],
    "avg_sum": round(avg_sum, 6),
    "steady_rate_positive": bool(rate > 0),
}), flush=True)
