"""Child process for the 2-host ENGINE test (not collected by pytest).

Joins a 2-process CPU runtime and trains the real sync trainer family
(ADAG through DistributedTrainer/WindowEngine) on a 4-replica mesh that
spans the process boundary — the round-2 verdict's gap: the engine had
only ever run single-process.  Prints per-epoch losses and a digest of the
trained center so the parent can assert multi-process == single-process.

Usage: python multihost_child_engine.py <process_id> <num_processes> <port>
"""

import json
import sys

proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

from distkeras_tpu.runtime.launcher import initialize_multihost  # noqa: E402

initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=nprocs, process_id=proc_id,
                     cpu_devices_per_process=2)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from tests.multihost_engine_common import make_toy, run_adag  # noqa: E402

assert jax.process_count() == nprocs
assert len(jax.devices()) == 2 * nprocs

dataset = make_toy()
losses, center = run_adag(dataset, num_workers=2 * nprocs)
print("RESULT " + json.dumps({
    "process": proc_id,
    "losses": [round(float(x), 8) for x in losses],
    "center_sum": float(sum(np.abs(w).sum() for w in center)),
    "center_digest": [float(np.asarray(w).ravel()[:3].sum()) for w in center],
}), flush=True)
