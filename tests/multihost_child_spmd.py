"""Child process for the 2-host SPMD test (not collected by pytest).

Joins a 2-process CPU JAX runtime via the launcher, builds the global
replica mesh, and runs one data-parallel SGD step with a psum'd gradient —
asserting the collective really crossed the process boundary.

Usage: python multihost_child_spmd.py <process_id> <num_processes> <port>
"""

import sys

proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

from distkeras_tpu.runtime.launcher import initialize_multihost  # noqa: E402

initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=nprocs, process_id=proc_id,
                     cpu_devices_per_process=2)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from distkeras_tpu.parallel.mesh import create_mesh  # noqa: E402

assert jax.process_count() == nprocs, jax.process_count()
n_global = len(jax.devices())
assert n_global == 2 * nprocs, n_global

mesh = create_mesh(axis_name="replica")

# data-parallel SGD step on a tiny linear model: params replicated, batch
# sharded over all hosts' devices, gradient psum'd over the replica axis
def step(w, x, y):
    def loss_fn(w):
        err = x @ w - y
        return jnp.mean(err * err)

    loss, g = jax.value_and_grad(loss_fn)(w)
    g = jax.lax.pmean(g, "replica")
    loss = jax.lax.pmean(loss, "replica")
    return w - 0.1 * g, loss

sharded = jax.jit(jax.shard_map(step, mesh=mesh,
                                in_specs=(P(), P("replica"), P("replica")),
                                out_specs=(P(), P())))

rng = np.random.default_rng(0)  # same on both processes
w_true = rng.normal(size=(4,)).astype(np.float32)
x_all = rng.normal(size=(8 * n_global, 4)).astype(np.float32)
y_all = x_all @ w_true

data_sh = NamedSharding(mesh, P("replica"))
per = len(x_all) // nprocs
lo, hi = proc_id * per, (proc_id + 1) * per
x = jax.make_array_from_process_local_data(data_sh, x_all[lo:hi])
y = jax.make_array_from_process_local_data(data_sh, y_all[lo:hi])

w = jnp.zeros(4, jnp.float32)
losses = []
for _ in range(20):
    w, loss = step_out = sharded(w, x, y)
    losses.append(float(loss))

assert np.isfinite(losses).all(), losses
assert losses[-1] < losses[0] * 0.1, losses
# the replicated weights must agree with the full-batch solution direction:
# both processes print the same weights, proving the pmean crossed hosts
print(f"OK proc={proc_id} devices={n_global} loss0={losses[0]:.4f} "
      f"lossN={losses[-1]:.6f} w={np.asarray(w).round(3).tolist()}", flush=True)
