"""Child process for the distributed-tracing merge test (not collected by
pytest).

Plays one remote WORKER process of a fleet: connects a raw ``PSClient``
(no jax — the import stays light) to a hub owned by the parent process,
announces a trace context (wire action ``T``), runs a few
pull/span/commit rounds, and flushes its span ring + clock-offset
estimate to the shared ``DKT_TRACE_DIR`` for ``merge_traces``.

Usage: python multihost_child_trace.py <ps_port> <worker_id> <trace_dir>
"""

import sys
import time

import numpy as np

from distkeras_tpu import observability as obs
from distkeras_tpu.observability import distributed as dtrace
from distkeras_tpu.runtime.parameter_server import PSClient

ps_port, worker_id, trace_dir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

obs.enable()
ctx = dtrace.TraceContext(job_id="mergejob", worker_id=worker_id,
                          span_id=dtrace.new_span_id())
dtrace.activate(ctx)

templates = [np.zeros((4, 4), np.float32), np.zeros(3, np.float32)]
client = PSClient("127.0.0.1", ps_port, templates=templates, trace_context=ctx)
for w in range(5):
    with obs.span("async.window", worker=worker_id, window=w):
        pulled = client.pull()
        time.sleep(0.002 * (worker_id + 1))  # worker-distinct span widths
        client.commit([np.full_like(t, 0.01) for t in pulled])
client.close()

path = dtrace.flush_process_trace(trace_dir, job_id="mergejob", role="worker")
offset, error = dtrace.clock_sync_state()
print(f"OK worker={worker_id} path={path} offset_ns={offset} "
      f"error_ns={error}", flush=True)
