"""Child process for the async multi-host PS test (not collected by pytest).

Runs a worker-only AsyncDOWNPOUR trainer (``ps_address=``) against a
parameter-server hub owned by another process — the worker-host side of
the reference's driver/executor topology.

Usage: python multihost_child_worker.py <ps_port> <shard_idx> <num_shards> <npz_path>
"""

import sys

ps_port, shard_idx, num_shards, npz_path = (
    int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])

from distkeras_tpu.platform import pin_cpu_devices  # noqa: E402

pin_cpu_devices(1)

import numpy as np  # noqa: E402

from distkeras_tpu.data.dataset import Dataset  # noqa: E402
from distkeras_tpu.models.base import ModelSpec  # noqa: E402
from distkeras_tpu.runtime.async_trainer import AsyncDOWNPOUR  # noqa: E402

with np.load(npz_path) as z:
    ds = Dataset({k: z[k] for k in z.files}).shard(num_shards, shard_idx)

# must match the parent's spec/seed so the flat weight templates line up
spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                 input_shape=(8,))
trainer = AsyncDOWNPOUR(spec, num_workers=1, communication_window=2,
                        ps_address=("127.0.0.1", ps_port),
                        loss="categorical_crossentropy", worker_optimizer="sgd",
                        learning_rate=0.05, batch_size=16, num_epoch=2, seed=0)
model = trainer.train(ds)
assert len(trainer.history) > 0
assert np.isfinite(trainer.history).all()
print(f"OK shard={shard_idx} windows={len(trainer.history)} "
      f"loss0={trainer.history[0]:.4f} lossN={trainer.history[-1]:.4f}", flush=True)
