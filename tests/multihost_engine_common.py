"""Shared fixture for the multi-process engine parity test: the SAME
deterministic dataset + ADAG training run, imported by both the child
processes (multi-process mesh) and the parent (single-process reference),
so any divergence is the engine's, not the harness's."""

import numpy as np


def make_toy(n: int = 256, seed: int = 0):
    from distkeras_tpu.data.dataset import Dataset

    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n)
    x = (rng.normal(size=(n, 8)) + 2.5 * y[:, None]).astype(np.float32)
    return Dataset({"features": x,
                    "label": np.eye(2, dtype=np.float32)[y],
                    "label_index": y})


def run_adag(dataset, num_workers: int):
    """Train ADAG deterministically (shuffle off) and return
    (per-window losses, flattened center weights) — thin wrapper so the
    ADAG parity test and the elastic-family test share ONE hyperparameter
    set (a drifted copy would make them assert different configs)."""
    losses, center, _ = run_engine("adag", dataset, num_workers)
    return losses, center


def run_engine(kind: str, dataset, num_workers: int):
    """Train one sync trainer deterministically KEEPING the final engine
    state, so per-replica artifacts can be asserted across a process
    boundary.  Returns (per-window losses, flat center weights,
    per-replica local-weight L1 norms [R]).

    The norms come from a compiled reduction with a REPLICATED output
    (like ``WindowEngine.averaged_model``), so they are identical on every
    process even though the locals themselves live on different hosts —
    exactly the artifact that proves AEASGD's divergent locals and
    DynSGD's rank-scaled commits survived the process boundary.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distkeras_tpu import trainers
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.utils import flatten_weights

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    cls = {"adag": trainers.ADAG, "aeasgd": trainers.AEASGD,
           "dynsgd": trainers.DynSGD}[kind]
    kwargs = dict(loss="categorical_crossentropy", worker_optimizer="sgd",
                  learning_rate=0.05, num_workers=num_workers, batch_size=8,
                  num_epoch=3, communication_window=2)
    if kind == "aeasgd":
        kwargs["rho"] = 1.0
    trainer = cls(spec, **kwargs)
    trainer.record_training_start()
    state = trainer._run_epochs(dataset, shuffle=False)
    center = trainer.engine.center_model(state).params
    flat, _ = flatten_weights(center)

    def replica_norms(local):
        per_leaf = [jnp.abs(a).reshape(a.shape[0], -1).sum(axis=1)
                    for a in jax.tree.leaves(local)]
        return jnp.stack(per_leaf).sum(axis=0)

    norms = jax.jit(replica_norms,
                    out_shardings=NamedSharding(trainer.engine.mesh, P()))(state.local)
    return (trainer.history, [np.asarray(w) for w in flat],
            [float(x) for x in np.asarray(norms)])
