"""Shared fixture for the multi-process engine parity test: the SAME
deterministic dataset + ADAG training run, imported by both the child
processes (multi-process mesh) and the parent (single-process reference),
so any divergence is the engine's, not the harness's."""

import numpy as np


def make_toy(n: int = 256, seed: int = 0):
    from distkeras_tpu.data.dataset import Dataset

    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n)
    x = (rng.normal(size=(n, 8)) + 2.5 * y[:, None]).astype(np.float32)
    return Dataset({"features": x,
                    "label": np.eye(2, dtype=np.float32)[y],
                    "label_index": y})


def run_adag(dataset, num_workers: int):
    """Train ADAG deterministically (shuffle off) and return
    (per-window losses, flattened center weights)."""
    from distkeras_tpu.models.base import ModelSpec
    from distkeras_tpu.trainers import ADAG
    from distkeras_tpu.utils import flatten_weights

    spec = ModelSpec(name="mlp", config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = ADAG(spec, loss="categorical_crossentropy", worker_optimizer="sgd",
                   learning_rate=0.05, num_workers=num_workers, batch_size=8,
                   num_epoch=3, communication_window=2)
    model = trainer.train(dataset, shuffle=False)
    flat, _ = flatten_weights(model.params)
    return trainer.history, [np.asarray(w) for w in flat]
