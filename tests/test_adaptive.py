"""Issue-10 tests: telemetry-driven adaptive aggregation, staleness-scaled
rates, and hub backpressure.

Covers the Adasum merge rule itself (commutativity / order-invariance /
sparse-row composition), the flat-combining commit path, the
HealthMonitor subscription hook and the ``staleness_drift`` detector, the
event-driven per-worker rate controller, the reconnect-storm retry-after
protocol (including the bounded-accept-rate drill), the seeded
ChaosProxy slow-NIC mode, the wire-compat matrix (un-upgraded client vs
adaptive hub, byte-identical across plain / sharded / replicated
topologies), and the ``adaptive=False`` off-path guarantees (zero
adaptive machinery constructed, trajectories bit-equal).
"""

import threading
import time

import numpy as np
import pytest

from distkeras_tpu.observability import distributed as dtrace
from distkeras_tpu.observability import health as health_mod
from distkeras_tpu.observability.health import HealthCollector, HealthMonitor
from distkeras_tpu.runtime import networking as net
from distkeras_tpu.runtime.parameter_server import (
    ADAGParameterServer,
    AdaptiveRateController,
    DeltaParameterServer,
    DynSGDParameterServer,
    PSClient,
    ShardedParameterServer,
    ShardedPSClient,
    adasum_merge,
    adasum_pair,
    shard_plan,
)


@pytest.fixture
def fresh_health():
    """Clean process-default collector/monitor (the adaptive hub binds and
    subscribes to these at start())."""
    health_mod.reset_default()
    yield health_mod
    health_mod.reset_default()


def _weights():
    return [np.zeros((4, 4), np.float32), np.zeros((6,), np.float32)]


# -- the merge rule itself (satellite 3) ---------------------------------------

def test_adasum_orthogonal_sums_and_parallel_averages():
    a = [np.array([2.0, 0.0, 0.0], np.float32)]
    b = [np.array([0.0, 2.0, 0.0], np.float32)]
    np.testing.assert_allclose(adasum_pair(a, b)[0], [2.0, 2.0, 0.0])
    # parallel: adasum(g, g) = g (each side halves — one step, not two)
    np.testing.assert_allclose(adasum_pair(a, a)[0], [2.0, 0.0, 0.0])


def test_adasum_pair_is_commutative():
    rng = np.random.default_rng(3)
    a = [rng.normal(size=(4, 4)).astype(np.float32),
         rng.normal(size=(6,)).astype(np.float32)]
    b = [rng.normal(size=(4, 4)).astype(np.float32),
         rng.normal(size=(6,)).astype(np.float32)]
    ab, ba = adasum_pair(a, b), adasum_pair(b, a)
    for x, y in zip(ab, ba):
        np.testing.assert_allclose(x, y, rtol=1e-6)


def test_adasum_merge_order_invariance():
    """The order-invariance the rule actually guarantees: swapping the
    members WITHIN any tree pair changes nothing (pairwise commutativity
    lifted through the reduction), and a batch of mutually orthogonal
    commits merges to their plain sum under EVERY permutation (the
    reduction is only order-sensitive through the interference terms,
    which orthogonality zeroes)."""
    rng = np.random.default_rng(7)
    commits = [[rng.normal(size=(6,)).astype(np.float32)]
               for _ in range(4)]
    base = adasum_merge(commits)[0]
    swapped = adasum_merge([commits[1], commits[0],
                            commits[3], commits[2]])[0]
    np.testing.assert_allclose(swapped, base, rtol=1e-5, atol=1e-7)
    # orthogonal batch: permutation-invariant, exactly the sum
    ortho = [[np.eye(5, dtype=np.float32)[i] * (i + 1.0)] for i in range(4)]
    expected = np.sum([c[0] for c in ortho], axis=0)
    for perm in ([0, 1, 2, 3], [3, 1, 0, 2], [2, 3, 1, 0]):
        merged = adasum_merge([ortho[i] for i in perm])[0]
        np.testing.assert_allclose(merged, expected, rtol=1e-6)
    # and the magnitude never blows up past the triangle bound
    assert np.linalg.norm(base) <= sum(
        np.linalg.norm(c[0]) for c in commits) + 1e-5


def test_adasum_zero_norm_side_passes_other_through():
    a = [np.zeros(3, np.float32)]
    b = [np.array([1.0, 2.0, 3.0], np.float32)]
    np.testing.assert_array_equal(adasum_pair(a, b)[0], b[0])
    np.testing.assert_array_equal(adasum_pair(b, a)[0], b[0])


def test_adasum_sparse_matches_densified():
    """Sparse-row composition: merging two (ids, grads) commits on their
    row union equals merging their dense materializations — ONE rule for
    both commit forms."""
    rows, dim = 8, 3
    rng = np.random.default_rng(11)
    ids_a = np.array([1, 4, 6], np.int64)
    ids_b = np.array([2, 4, 7], np.int64)
    ga = rng.normal(size=(3, dim)).astype(np.float32)
    gb = rng.normal(size=(3, dim)).astype(np.float32)
    sparse = adasum_pair([(ids_a, ga)], [(ids_b, gb)])[0]
    da = np.zeros((rows, dim), np.float32)
    da[ids_a] = ga
    db = np.zeros((rows, dim), np.float32)
    db[ids_b] = gb
    dense = adasum_pair([da], [db])[0]
    ids, grads = sparse
    np.testing.assert_array_equal(ids, np.array([1, 2, 4, 6, 7], np.int64))
    full = np.zeros((rows, dim), np.float32)
    full[ids] = grads
    np.testing.assert_allclose(full, dense, rtol=1e-6)
    # untouched rows stay exactly zero in both forms
    np.testing.assert_array_equal(dense[[0, 3, 5]], 0.0)


def test_adasum_mixed_representation_refused():
    with pytest.raises(ValueError, match="densify"):
        adasum_pair([(np.array([0], np.int64),
                      np.ones((1, 2), np.float32))],
                    [np.ones((4, 2), np.float32)])


# -- the combiner (tentpole 1) -------------------------------------------------

def test_combiner_merges_queued_commits_one_batch(fresh_health):
    """Commits queued while another applies merge into ONE batch: clock
    and num_updates still advance by the commit count, and the combiner's
    counters record the fold."""
    ps = ADAGParameterServer([np.zeros(3, np.float32)], num_workers=4,
                             port=0, idle_timeout=None, adaptive=True)
    ps.start()
    try:
        comb = ps._combiner
        deltas = [np.eye(3, dtype=np.float32)[i % 3] * 4.0 for i in range(4)]
        comb._drain.acquire()  # park the drain: submitters must queue
        threads = [threading.Thread(target=ps.commit_direct, args=([d], 0))
                   for d in deltas]
        for t in threads:
            t.start()
        time.sleep(0.3)
        comb._drain.release()
        for t in threads:
            t.join(10)
        assert ps.num_updates == 4 and ps._clock == 4
        assert comb.max_batch == 4 and comb.merged_total == 3
        assert np.isfinite(ps.center[0]).all()
    finally:
        ps.stop()


def test_combiner_uncontended_matches_plain_hub_bitwise(fresh_health):
    """Serial (batch-of-one) adaptive applies are bit-identical to the
    plain hub across the scaling rules — the off-vs-on parity anchor at
    the center level."""
    for cls, kw in ((DeltaParameterServer, {}),
                    (ADAGParameterServer, {"num_workers": 3}),
                    (DynSGDParameterServer, {})):
        plain = cls([np.zeros((4, 4), np.float32)], port=0,
                    idle_timeout=None, **kw)
        adap = cls([np.zeros((4, 4), np.float32)], port=0,
                   idle_timeout=None, adaptive=True, **kw)
        plain.start()
        adap.start()
        try:
            rng = np.random.default_rng(5)
            for k in range(6):
                d = rng.normal(size=(4, 4)).astype(np.float32)
                # interleave pulls so DynSGD sees varied staleness
                clock_p = plain.pull_direct()[1] if k % 2 else 0
                clock_a = adap.pull_direct()[1] if k % 2 else 0
                plain.commit_direct([d], clock_p)
                adap.commit_direct([d], clock_a)
            np.testing.assert_array_equal(plain.center[0], adap.center[0])
        finally:
            plain.stop()
            adap.stop()


def test_combiner_sparse_commits_apply_and_replicate(fresh_health):
    """Sparse (ids, grads) commits ride the combiner natively, and a
    replicated adaptive primary streams the applied delta so the standby
    tracks bit for bit."""
    t = [np.zeros((8, 2), np.float32), np.zeros((3,), np.float32)]
    primary = DeltaParameterServer(t, port=0, idle_timeout=None,
                                   adaptive=True, sparse_leaves=(0,))
    primary.start()
    replica = DeltaParameterServer(t, idle_timeout=None,
                                   replica_of=("127.0.0.1", primary.port),
                                   sparse_leaves=(0,))
    replica.start()
    try:
        assert replica.wait_synced(timeout=10)
        ids = np.array([1, 5], np.int64)
        grads = np.ones((2, 2), np.float32)
        primary.commit_sparse_direct([(ids, grads),
                                      np.ones(3, np.float32)], 0)
        deadline = time.monotonic() + 10
        while replica._clock < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        np.testing.assert_array_equal(primary.center[0][ids], 1.0)
        np.testing.assert_array_equal(replica.center[0], primary.center[0])
        np.testing.assert_array_equal(replica.center[1], primary.center[1])
    finally:
        replica.stop()
        primary.stop()


def test_combiner_failed_batch_raises_everywhere_never_false_acks(
        fresh_health):
    """A batch whose apply raises must surface the error to EVERY
    submitter in it (their connections drop / their workers see it) —
    never a silent drop behind an ack — and must not corrupt the
    combiner for later commits."""
    ps = DeltaParameterServer([np.zeros(3, np.float32)], port=0,
                              idle_timeout=None, adaptive=True)
    ps.start()
    try:
        comb = ps._combiner
        results = {}

        def submit(key, parts):
            try:
                comb.commit(parts, 0)
                results[key] = None
            except Exception as e:  # noqa: BLE001 - recorded, asserted below
                results[key] = e

        comb._drain.acquire()  # both entries land in ONE batch
        threads = [
            threading.Thread(target=submit,
                             args=("bad", [np.ones(5, np.float32)])),
            threading.Thread(target=submit,
                             args=("good", [np.ones(3, np.float32)])),
        ]
        for t in threads:
            t.start()
        time.sleep(0.2)
        comb._drain.release()
        for t in threads:
            t.join(10)
        # the poisoned batch raised for BOTH members (the good commit was
        # not applied, so acking it would have been a lie)
        assert results["bad"] is not None and results["good"] is not None
        assert ps._clock == 0 and ps.num_updates == 0
        np.testing.assert_array_equal(ps.center[0], 0.0)
        # the combiner is intact: a fresh valid commit applies
        ps.commit_direct([np.ones(3, np.float32)], 0)
        assert ps._clock == 1
        np.testing.assert_array_equal(ps.center[0], 1.0)
    finally:
        ps.stop()


def test_admitted_hellos_are_not_storm_evidence(fresh_health):
    """A shed herd's paced returns (waits_taken > 0) must not re-arm the
    storm — otherwise the drain itself keeps shedding and a later lone
    reconnect gets punished on stale evidence."""
    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None,
                              adaptive=True)
    ps.start()
    try:
        # paced returns alone never start a storm
        for _ in range(5):
            assert ps._retry_after_ms(waits_taken=1) == 0
        assert len(ps._hello_times) == 0
        assert ps.backpressure_hints == 0
        # fresh arrivals still do
        hints = [ps._retry_after_ms(waits_taken=0) for _ in range(3)]
        assert hints[-1] > 0
    finally:
        ps.stop()


# -- subscription hook + drift detector (tentpole 2) ---------------------------

def test_monitor_subscribe_pushes_events_and_unsubscribes():
    c = HealthCollector()
    m = HealthMonitor(c, cooldown_s=0.0)
    seen = []
    bad_calls = []

    def bad(event):
        bad_calls.append(event)
        raise RuntimeError("broken subscriber")

    m.subscribe(bad)
    cb = m.subscribe(seen.append)
    ev = m.emit("straggler", worker="3", factor=2.5)
    assert ev is not None
    # the broken subscriber ran, raised, and neither blocked the emit nor
    # the other subscriber
    assert len(bad_calls) == 1 and len(seen) == 1
    assert seen[0].kind == "straggler" and seen[0].worker == "3"
    m.unsubscribe(cb)
    m.emit("straggler", worker="4", factor=2.0)
    assert len(seen) == 1
    # clear() keeps subscriptions (a run-boundary reset must not unhook a
    # live hub); the bad one is still attached and still harmless
    m.clear()
    m.emit("straggler", worker="5", factor=2.0)
    assert len(bad_calls) == 3


def test_staleness_drift_detector_is_fleet_relative():
    c = HealthCollector()
    m = HealthMonitor(c, cooldown_s=0.0, min_fleet=3, min_samples=3,
                      drift_factor=2.0, staleness_min=4.0)
    now = time.monotonic()
    for i in range(4):
        c.observe("0", "staleness", 1.0, ts=now - 4 + i)
        c.observe("1", "staleness", 2.0, ts=now - 4 + i)
        # worker 2 is ALWAYS behind — its own baseline is high, so the
        # spike detector never fires; drift must
        c.observe("2", "staleness", 9.0, ts=now - 4 + i)
    events = [e for e in m.check(now) if e.kind == "staleness_drift"]
    assert [e.worker for e in events] == ["2"]
    assert events[0].evidence["staleness_mean"] == 9.0
    assert events[0].evidence["fleet_median"] == 2.0
    # below the fleet floor nothing fires
    c2 = HealthCollector()
    m2 = HealthMonitor(c2, cooldown_s=0.0, min_fleet=3)
    for i in range(4):
        c2.observe("9", "staleness", 50.0, ts=now - 4 + i)
    assert m2.check(now) == []


def test_rate_controller_scales_and_expires():
    rc = AdaptiveRateController(floor=0.1, hold_s=0.2)

    class Ev:
        def __init__(self, kind, worker, **evidence):
            self.kind, self.worker, self.evidence = kind, worker, evidence

    rc.on_event(Ev("staleness_drift", "0", staleness_mean=9.0,
                   fleet_median=1.0))
    assert rc.scale_for("0") == pytest.approx(0.2)
    assert rc.scale_for(0) == pytest.approx(0.2)  # int/str key equivalence
    assert rc.scale_for("1") == 1.0 and rc.scale_for(None) == 1.0
    # a second, stricter verdict wins; a laxer one does not relax it
    rc.on_event(Ev("straggler", "0", factor=20.0))
    assert rc.scale_for("0") == pytest.approx(0.1)  # floored
    rc.on_event(Ev("staleness_spike", "0", staleness=1.0, baseline=1.0))
    assert rc.scale_for("0") == pytest.approx(0.1)
    time.sleep(0.25)
    assert rc.scale_for("0") == 1.0  # expired -> recovered
    assert rc.snapshot() == {}


def test_rate_controller_tracks_improving_evidence_per_kind():
    """A fresh event of one kind REPLACES that kind's verdict — a worker
    improving from severe to mild drift tracks down-penalty immediately
    instead of ratcheting at the historical minimum — while another
    kind's severe verdict keeps its own clock."""
    rc = AdaptiveRateController(floor=0.1, hold_s=0.3)

    class Ev:
        def __init__(self, kind, worker, **evidence):
            self.kind, self.worker, self.evidence = kind, worker, evidence

    rc.on_event(Ev("staleness_drift", "0", staleness_mean=39.0,
                   fleet_median=1.0))
    assert rc.scale_for("0") == pytest.approx(0.1)  # severe, floored
    rc.on_event(Ev("staleness_drift", "0", staleness_mean=3.0,
                   fleet_median=1.0))
    assert rc.scale_for("0") == pytest.approx(0.5)  # improved: tracked
    # a concurrent straggler verdict composes by min...
    rc.on_event(Ev("straggler", "0", factor=4.0))
    assert rc.scale_for("0") == pytest.approx(0.25)
    # ...and drift improving further does not erase the straggler verdict
    rc.on_event(Ev("staleness_drift", "0", staleness_mean=1.0,
                   fleet_median=1.0))
    assert rc.scale_for("0") == pytest.approx(0.25)
    time.sleep(0.35)
    assert rc.scale_for("0") == 1.0


def test_combiner_mixed_batch_applies_sequentially(fresh_health):
    """A batch mixing a full-delta (dense) commit with sparse-row commits
    applies in plain queue order — center equals the sum — instead of
    densifying the sparse sides under the lock to force a merge."""
    t = [np.zeros((6, 2), np.float32)]
    ps = DeltaParameterServer(t, port=0, idle_timeout=None, adaptive=True,
                              sparse_leaves=(0,))
    ps.start()
    try:
        comb = ps._combiner
        ids = np.array([1, 4], np.int64)
        comb._drain.acquire()  # both land in ONE batch
        threads = [
            threading.Thread(target=ps.commit_sparse_direct,
                             args=([(ids, np.ones((2, 2), np.float32))], 0)),
            threading.Thread(target=ps.commit_direct,
                             args=([np.full((6, 2), 2.0, np.float32)], 0)),
        ]
        for th in threads:
            th.start()
        time.sleep(0.2)
        comb._drain.release()
        for th in threads:
            th.join(10)
        assert comb.max_batch == 2 and ps.num_updates == 2
        expected = np.full((6, 2), 2.0, np.float32)
        expected[ids] += 1.0
        np.testing.assert_array_equal(ps.center[0], expected)
    finally:
        ps.stop()


def test_hub_reacts_to_staleness_event_without_polling(fresh_health):
    """The whole reaction chain: monitor event -> subscription -> rate
    controller -> scaled apply, with the committing worker named by its
    thread-local trace context (the inproc attribution path)."""
    ps = DeltaParameterServer([np.zeros(4, np.float32)], port=0,
                              idle_timeout=None, adaptive=True)
    ps.start()
    try:
        health_mod.monitor().emit("staleness_drift", worker="0",
                                  staleness_mean=9.0, fleet_median=1.0)
        dtrace.activate(dtrace.TraceContext(job_id="j", worker_id=0,
                                            span_id=dtrace.new_span_id()))
        try:
            ps.commit_direct([np.ones(4, np.float32)], 0)
        finally:
            dtrace.activate(None)
        np.testing.assert_allclose(ps.center[0], 0.2)
        # the applied scale joined the worker's live series (top/fleet
        # report read it from here)
        series = health_mod.collector().series("0", "adaptive_scale")
        assert series is not None and series.samples()[-1][1] == \
            pytest.approx(0.2)
    finally:
        ps.stop()


def test_fleet_report_adaptive_block(fresh_health):
    from distkeras_tpu.observability.distributed import fleet_report

    col = health_mod.collector()
    col.observe("0", "adaptive_scale", 0.25)
    col.observe("hub", "merge_queue_depth", 3.0)
    report = fleet_report(events=[], live=col)
    block = report["adaptive"]
    assert block["active"] is True
    assert block["worker_scales"]["0"]["last"] == 0.25
    assert block["merge_queue"]["hub"]["last"] == 3.0
    # no adaptive series -> no block (non-adaptive reports unchanged)
    health_mod.reset_default()
    col2 = health_mod.collector()
    col2.observe("0", "staleness", 1.0)
    assert "adaptive" not in fleet_report(events=[], live=col2)


def test_render_top_scale_and_mq_columns(fresh_health):
    from distkeras_tpu.observability.health import render_top

    c = health_mod.collector()
    c.observe("0", "adaptive_scale", 0.25)
    c.observe("hub", "merge_queue_depth", 3.0)
    frame = render_top({"fleet": c.snapshot(), "events": []})
    assert "SCALE" in frame and "MQ" in frame
    row0 = next(line for line in frame.splitlines()
                if line.strip().startswith("0 "))
    assert "0.25" in row0


# -- reconnect-storm backpressure (tentpole 3) ---------------------------------

def test_hub_answers_hello_zero_outside_storm(fresh_health):
    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None,
                              adaptive=True)
    ps.start()
    try:
        s = net.connect("127.0.0.1", ps.port)
        try:
            net.send_frame(s, net.encode_reconnect_payload(0))
            action, blobs = net.recv_tensors(s)
            assert action == net.ACTION_RETRY
            assert net.decode_retry_payload(blobs) == 0
        finally:
            s.close()
    finally:
        ps.stop()


def test_non_adaptive_hub_answers_hello_zero(fresh_health):
    """An adaptive client against a non-adaptive hub of this generation
    is admitted immediately — G is answered by every hub, hinted only by
    adaptive ones in a storm."""
    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None)
    ps.start()
    try:
        s = net.connect("127.0.0.1", ps.port)
        try:
            for _ in range(5):  # even a herd: never hinted
                net.send_frame(s, net.encode_reconnect_payload(0))
                action, blobs = net.recv_tensors(s)
                assert net.decode_retry_payload(blobs) == 0
        finally:
            s.close()
        assert ps.backpressure_hints == 0
    finally:
        ps.stop()


def test_storm_spreads_slots_and_admits_after_wait(fresh_health):
    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None,
                              adaptive=True)
    ps.RETRY_BASE_MS = 10
    ps.start()
    try:
        hints = []
        s = net.connect("127.0.0.1", ps.port)
        try:
            for _ in range(5):
                net.send_frame(s, net.encode_reconnect_payload(0))
                hints.append(net.decode_retry_payload(
                    net.recv_tensors(s)[1]))
            # a client announcing it already waited is admitted
            net.send_frame(s, net.encode_reconnect_payload(1))
            admitted = net.decode_retry_payload(net.recv_tensors(s)[1])
        finally:
            s.close()
        # first two hellos pre-storm (threshold 3), then increasing slots
        assert hints[:2] == [0, 0]
        assert hints[2:] == [10, 20, 30]
        assert admitted == 0
        assert ps.backpressure_hints == 3
        # the self-detected storm is an observable health event
        kinds = [e["kind"] for e in health_mod.monitor().events()]
        assert "reconnect_storm" in kinds
    finally:
        ps.stop()


def test_storm_event_from_monitor_arms_shedding(fresh_health):
    """A reconnect storm detected from worker health REPORTS (not from
    hellos) also sheds: the subscription closes the loop."""
    ps = DeltaParameterServer(_weights(), port=0, idle_timeout=None,
                              adaptive=True)
    ps.RETRY_BASE_MS = 10
    ps.start()
    try:
        health_mod.monitor().emit("reconnect_storm", "critical", worker="2",
                                  count=5)
        s = net.connect("127.0.0.1", ps.port)
        try:
            net.send_frame(s, net.encode_reconnect_payload(0))
            hint = net.decode_retry_payload(net.recv_tensors(s)[1])
        finally:
            s.close()
        assert hint == 10
    finally:
        ps.stop()


def test_reconnect_storm_drill_bounded_accept_zero_exceptions(fresh_health):
    """The acceptance drill: a 6-client herd severed at once reconnects
    against an adaptive hub — the hub paces the herd (increasing
    retry-after slots = bounded accept rate), every client recovers
    budget-neutrally, and no worker raises."""
    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None, adaptive=True)
    ps.RETRY_BASE_MS = 20
    ps.start()
    errors, recovered = [], []

    def worker(i):
        try:
            c = PSClient("127.0.0.1", ps.port, templates=t, adaptive=True,
                         max_reconnects=4, reconnect_backoff=0.01)
            c.pull()
            c.commit([np.ones_like(x) for x in t])
            c.sock.shutdown(2)  # the blip: every client severed at once
            c.pull()
            c.commit([np.ones_like(x) for x in t])
            c.drain()
            recovered.append((i, c.backpressure_waits, c.reconnects_used))
            c.close()
        except Exception as e:  # noqa: BLE001 - the drill records, asserts below
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join(30)
        assert not any(th.is_alive() for th in threads)
    finally:
        ps.stop()
    assert errors == [], errors
    assert len(recovered) == 6
    # the herd was paced: hints were issued with increasing slots...
    assert ps.backpressure_hints >= 1
    # ...every commit landed (2 per client)...
    assert ps.num_updates == 12
    # ...and hub-paced waits were refunded: nobody burned more than the
    # one real fault's worth of budget
    assert all(used <= 2 for _, _, used in recovered), recovered
    kinds = [e["kind"] for e in health_mod.monitor().events()]
    assert "reconnect_storm" in kinds


# -- ChaosProxy slow-NIC mode (satellite 1) ------------------------------------

def test_chaos_throttle_deterministic_under_seed():
    from distkeras_tpu.runtime.faults import ChaosProxy

    p1 = ChaosProxy("127.0.0.1", 1, jitter_delay_s=(0.01, 0.02), seed=9,
                    bandwidth_bytes_per_s=1e6)
    p2 = ChaosProxy("127.0.0.1", 1, jitter_delay_s=(0.01, 0.02), seed=9,
                    bandwidth_bytes_per_s=1e6)

    def seq(proxy):
        rng = np.random.default_rng((proxy.seed, 0, 1))
        return [proxy._frame_delay(rng, nbytes)
                for nbytes in (13, 1024, 13, 65536)]

    s1, s2 = seq(p1), seq(p2)
    assert s1 == s2
    # bandwidth term: the big frame pays proportionally more
    assert s1[3] >= 65536 / 1e6 + 0.01 - 1e-9
    assert all(0.01 <= d - nb / 1e6 <= 0.02
               for d, nb in zip(s1, (13, 1024, 13, 65536)))
    with pytest.raises(ValueError, match="lo <= hi"):
        ChaosProxy("127.0.0.1", 1, jitter_delay_s=(0.5, 0.1))


def test_chaos_slow_conns_throttles_only_named_ordinals(fresh_health,
                                                        monkeypatch):
    from distkeras_tpu.runtime import faults as faults_mod
    from distkeras_tpu.runtime.faults import ChaosProxy

    sleeps = []
    real_sleep = time.sleep
    monkeypatch.setattr(faults_mod.time, "sleep",
                        lambda s: (sleeps.append(s), real_sleep(0))[1])
    t = _weights()
    ps = DeltaParameterServer(t, port=0, idle_timeout=None)
    ps.start()
    proxy = ChaosProxy("127.0.0.1", ps.port, jitter_delay_s=(0.01, 0.02),
                       seed=3, slow_conns={0}).start()
    try:
        def session():
            with PSClient("127.0.0.1", proxy.port, templates=t) as c:
                c.pull()
                c.commit([np.ones_like(x) for x in t])
                c.drain()

        def settled():
            # the pump threads may still be flushing the session's last
            # frames (BYE, trailing replies) after the client returned —
            # wait until the recorded-sleep count is quiescent
            n = -1
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                cur = len(sleeps)
                if cur == n:
                    return cur
                n = cur
                real_sleep(0.1)
            return len(sleeps)

        session()          # conn 0: throttled
        first = settled()
        assert first > 0
        assert all(0.01 <= s <= 0.02 for s in sleeps)
        session()          # conn 1: clean
        assert settled() == first
    finally:
        proxy.stop()
        ps.stop()


# -- wire-compat matrix (satellite 2) ------------------------------------------

class _RecordingSock:
    def __init__(self, sock):
        self._sock = sock
        self.tx = bytearray()

    def sendall(self, data):
        self.tx += bytes(data)
        return self._sock.sendall(data)

    def __getattr__(self, name):
        return getattr(self._sock, name)


def _assert_no_adaptive_frames(stream: bytes) -> None:
    i = 0
    while i < len(stream):
        n = int.from_bytes(stream[i:i + 8], "big")
        assert stream[i + 8:i + 9] not in (net.ACTION_RECONNECT,
                                           net.ACTION_RETRY)
        i += 8 + n


def _session_bytes(port, templates):
    with PSClient("127.0.0.1", port, templates=templates) as c:
        rec = _RecordingSock(c.sock)
        c.sock = rec
        c.pull()
        c.commit([np.full_like(t, 0.5) for t in templates])
        c.pull()
        c.drain()
    return bytes(rec.tx)


def test_plain_client_bytes_identical_against_adaptive_hub(fresh_health):
    t = _weights()
    plain = DeltaParameterServer(t, port=0, idle_timeout=None)
    adaptive = DeltaParameterServer(t, port=0, idle_timeout=None,
                                    adaptive=True)
    plain.start()
    adaptive.start()
    try:
        baseline = _session_bytes(plain.port, t)
        against_adaptive = _session_bytes(adaptive.port, t)
    finally:
        plain.stop()
        adaptive.stop()
    assert baseline == against_adaptive
    _assert_no_adaptive_frames(baseline)


def test_plain_striped_client_bytes_identical_on_adaptive_shards(
        fresh_health):
    t = [np.zeros((4, 4), np.float32), np.zeros((6,), np.float32),
         np.zeros((3,), np.float32)]
    plan = shard_plan(t, 2)

    def make(adaptive):
        ps = ShardedParameterServer(
            t, plan, lambda w, sid: DeltaParameterServer(
                w, shard_id=sid, idle_timeout=None, adaptive=adaptive))
        ps.start()
        return ps

    def session(ps):
        with ShardedPSClient([("127.0.0.1", p) for p in ps.ports],
                             t, plan) as c:
            recs = []
            for sc in c.shards:
                rec = _RecordingSock(sc.sock)
                sc.sock = rec
                recs.append(rec)
            c.pull()
            c.commit([np.full_like(a, 0.5) for a in t])
            c.pull()
            c.drain()
        return [bytes(r.tx) for r in recs]

    plain, adaptive = make(False), make(True)
    try:
        base_streams = session(plain)
        adap_streams = session(adaptive)
    finally:
        plain.stop()
        adaptive.stop()
    assert base_streams == adap_streams
    for s in base_streams:
        _assert_no_adaptive_frames(s)


def test_plain_client_bytes_identical_against_replicated_adaptive_primary(
        fresh_health):
    t = _weights()

    def make(adaptive):
        primary = DeltaParameterServer(t, port=0, idle_timeout=None,
                                       adaptive=adaptive)
        primary.start()
        replica = DeltaParameterServer(
            t, idle_timeout=None, replica_of=("127.0.0.1", primary.port))
        replica.start()
        assert replica.wait_synced(timeout=10)
        return primary, replica

    p1, r1 = make(False)
    p2, r2 = make(True)
    try:
        baseline = _session_bytes(p1.port, t)
        against_adaptive = _session_bytes(p2.port, t)
        # the adaptive primary replicated the applied (merged) delta
        deadline = time.monotonic() + 10
        while r2._clock < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        np.testing.assert_array_equal(r2.center[0], p2.center[0])
    finally:
        for hub in (r1, p1, r2, p2):
            hub.stop()
    assert baseline == against_adaptive
    _assert_no_adaptive_frames(baseline)


# -- off-path parity + trainer integration -------------------------------------

@pytest.mark.parametrize("trainer_name", [
    # tier-1 keeps one cell per device_commit family (DOWNPOUR-delta and
    # elastic-difference); the other three ride the slow suite — the
    # PR-6 cheapest-cell convention
    "AsyncADAG",
    "AsyncAEASGD",
    pytest.param("AsyncDOWNPOUR", marks=pytest.mark.slow),
    pytest.param("AsyncDynSGD", marks=pytest.mark.slow),
    pytest.param("AsyncEAMSGD", marks=pytest.mark.slow),
])
def test_adaptive_off_constructs_zero_adaptive_machinery(
        trainer_name, toy_dataset, monkeypatch):
    """adaptive=False (the default) never touches the adaptive stack —
    combiner and controller construction are made to raise, and all five
    Async* trainers still train exactly as at HEAD."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime import parameter_server as ps_mod

    def boom(*a, **k):
        raise AssertionError("adaptive machinery constructed on the "
                             "adaptive=False path")

    monkeypatch.setattr(ps_mod._AdaptiveCombiner, "__init__", boom)
    monkeypatch.setattr(ps_mod.AdaptiveRateController, "__init__", boom)
    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    cls = getattr(dk, trainer_name)
    trainer = cls(Model.init(spec, seed=0),
                  loss="categorical_crossentropy", batch_size=16,
                  num_epoch=1, num_workers=2, communication_window=4,
                  learning_rate=0.05, seed=0)
    trainer.train(toy_dataset)
    assert trainer.history


def _native_mark():
    from distkeras_tpu.runtime.native import build_error, native_available

    return pytest.mark.skipif(not native_available(),
                              reason=f"native PS unavailable: {build_error()}")


# hub dimension (ISSUE 11): the C++ combiner's batch-of-one must equal
# the plain apply too.  Tier-1 keeps the cheapest native cell (PR-6
# convention); the second native cell rides the slow suite
@pytest.mark.parametrize("trainer_name,pipeline,native", [
    ("AsyncADAG", False, False),
    ("AsyncDynSGD", True, False),  # pipelined: nonzero self-staleness scales
    pytest.param("AsyncDynSGD", True, True, marks=_native_mark()),
    pytest.param("AsyncADAG", False, True,
                 marks=[_native_mark(), pytest.mark.slow]),
])
def test_adaptive_on_uncontended_trajectory_bit_equal(trainer_name, pipeline,
                                                      native, toy_dataset,
                                                      fresh_health):
    """One worker, no contention, no events: adaptive=True must be
    bit-identical to adaptive=False — the combiner's batch-of-one apply
    is the plain apply."""
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))

    def run(adaptive):
        health_mod.reset_default()
        cls = getattr(dk, trainer_name)
        trainer = cls(Model.init(spec, seed=0),
                      loss="categorical_crossentropy", batch_size=16,
                      num_epoch=2, num_workers=1, communication_window=4,
                      learning_rate=0.05, seed=0, pipeline=pipeline,
                      adaptive=adaptive, native_ps=native)
        model = trainer.train(toy_dataset)
        return trainer.history, jax.tree.leaves(model.params)

    hist_off, params_off = run(False)
    hist_on, params_on = run(True)
    assert hist_off == hist_on
    for a, b in zip(params_off, params_on):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_adaptive_trainer_guards(toy_dataset):
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec
    from distkeras_tpu.runtime.launcher import start_parameter_server

    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    # adaptive + native is SERVED since ISSUE 11: the trainer constructs,
    # and a standalone native adaptive hub starts and stops cleanly
    dk.AsyncADAG(Model.init(spec, seed=0),
                 loss="categorical_crossentropy", batch_size=16,
                 num_epoch=1, adaptive=True, native_ps=True)
    from distkeras_tpu.runtime.native import native_available

    if native_available():
        ps = start_parameter_server(Model.init(spec, seed=0), native=True,
                                    adaptive=True, idle_timeout=None)
        try:
            assert ps.adaptive and ps.port > 0
        finally:
            ps.stop()


def test_adaptive_trainer_end_to_end(toy_dataset, fresh_health):
    """adaptive=True trains end to end over sockets with real worker
    concurrency: commits flow through the combiner (clock == windows),
    trace contexts exist without telemetry, and the run still learns."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncADAG(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=2, num_workers=2,
                           communication_window=4, learning_rate=0.05,
                           seed=0, adaptive=True, health_interval_s=0.1)
    trainer.train(toy_dataset)
    assert trainer.history
    assert trainer.worker_errors == []
    ps = trainer.parameter_server
    assert ps.num_updates == len(trainer.history)
    # the hub bound the health plane and folded per-worker staleness
    # (trace contexts exist even with telemetry off)
    workers = health_mod.collector().workers()
    assert any(w in ("0", "1") for w in workers), workers


@pytest.mark.slow  # the inproc combiner path is tier-1-covered by the
# commit_direct tests; this full-trainer cell rides the slow suite
def test_adaptive_inproc_trainer_end_to_end(toy_dataset, fresh_health):
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model, ModelSpec

    spec = ModelSpec(name="mlp",
                     config={"hidden_sizes": (16,), "num_outputs": 2},
                     input_shape=(8,))
    trainer = dk.AsyncADAG(Model.init(spec, seed=0),
                           loss="categorical_crossentropy", batch_size=16,
                           num_epoch=1, num_workers=2,
                           communication_window=4, learning_rate=0.05,
                           seed=0, adaptive=True, transport="inproc")
    trainer.train(toy_dataset)
    assert trainer.history
    assert trainer.worker_errors == []


def test_distkeras_ps_adaptive_flag_composes_with_native():
    """--adaptive --native is no longer a parser error (ISSUE 11): the
    CLI reaches the model load (which fails on the nonexistent path,
    proving the flag combination passed validation)."""
    from distkeras_tpu.runtime.launcher import main

    with pytest.raises(FileNotFoundError):
        main(["--model", "/nonexistent", "--native", "--adaptive"])
