"""Algorithm-semantics tests (SURVEY §4.4): each commit rule verified
against its closed-form single-window expectation on an 8-replica mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distkeras_tpu.parallel.algorithms import (
    AdagAlgorithm,
    DownpourAlgorithm,
    DynSGDAlgorithm,
    ElasticAlgorithm,
    NoCommitAlgorithm,
)
from distkeras_tpu.parallel.mesh import create_mesh

R = 8


def run_commit(algo, center, local):
    """Run one window_commit under shard_map; center [D], local [R, D]."""
    mesh = create_mesh(R)

    def fn(center, local):
        l = local[0]
        c2, l2, _ = algo.window_commit(center, l, {}, "replica")
        return c2, l2[None]

    out = jax.shard_map(fn, mesh=mesh, in_specs=(P(), P("replica")), out_specs=(P(), P("replica")))(
        jnp.asarray(center), jnp.asarray(local)
    )
    return np.asarray(out[0]), np.asarray(out[1])


@pytest.fixture
def weights():
    rng = np.random.default_rng(42)
    center = rng.normal(size=(16,)).astype(np.float32)
    local = rng.normal(size=(R, 16)).astype(np.float32)
    return center, local


def test_adag_commit_is_mean_delta(weights):
    center, local = weights
    new_center, new_local = run_commit(AdagAlgorithm(), center, local)
    expected = center + (local - center).mean(axis=0)
    np.testing.assert_allclose(new_center, expected, rtol=1e-5)
    # post-commit pull: every local equals the new center
    for r in range(R):
        np.testing.assert_allclose(new_local[r], expected, rtol=1e-5)


def test_downpour_commit_is_sum_delta(weights):
    center, local = weights
    new_center, new_local = run_commit(DownpourAlgorithm(), center, local)
    expected = center + (local - center).sum(axis=0)
    np.testing.assert_allclose(new_center, expected, rtol=1e-4)
    np.testing.assert_allclose(new_local[0], expected, rtol=1e-4)


def test_elastic_commit_spring_forces(weights):
    center, local = weights
    rho, lr = 5.0, 0.01
    alpha = rho * lr
    new_center, new_local = run_commit(ElasticAlgorithm(rho=rho, learning_rate=lr), center, local)
    ediff = alpha * (local - center)
    np.testing.assert_allclose(new_center, center + ediff.sum(axis=0), rtol=1e-4)
    # locals pulled toward center but NOT reset: divergence preserved
    np.testing.assert_allclose(new_local, local - ediff, rtol=1e-4)
    assert not np.allclose(new_local[0], new_local[1])


def test_elastic_fixed_point(weights):
    """If all locals equal the center, elastic averaging is a no-op."""
    center, _ = weights
    local = np.stack([center] * R)
    new_center, new_local = run_commit(ElasticAlgorithm(rho=5.0, learning_rate=0.01), center, local)
    np.testing.assert_allclose(new_center, center, rtol=1e-5)
    np.testing.assert_allclose(new_local, local, rtol=1e-5)


def test_dynsgd_staleness_scaling(weights):
    center, local = weights
    new_center, new_local = run_commit(DynSGDAlgorithm(), center, local)
    # deterministic serialization: replica r has staleness r -> scale 1/(r+1)
    expected = center.copy()
    for r in range(R):
        expected = expected + (local[r] - center) / (r + 1)
    np.testing.assert_allclose(new_center, expected, rtol=1e-4)
    np.testing.assert_allclose(new_local[3], expected, rtol=1e-4)


def test_nocommit_is_identity(weights):
    center, local = weights
    new_center, new_local = run_commit(NoCommitAlgorithm(), center, local)
    np.testing.assert_allclose(new_center, center)
    np.testing.assert_allclose(new_local, local)


def test_dynsgd_sync_matches_async_hub(weights):
    """Cross-family consistency (round-1 verdict weak #5): the sync
    DynSGDAlgorithm must be the EXACT serialization of the async
    DynSGDParameterServer under the schedule it claims — all workers pull
    at window start, then commit in rank order."""
    from distkeras_tpu.runtime.parameter_server import DynSGDParameterServer, PSClient

    center, local = weights
    new_center_sync, _ = run_commit(DynSGDAlgorithm(), center, local)

    ps = DynSGDParameterServer([center], host="127.0.0.1")
    ps.start()
    try:
        clients = [PSClient("127.0.0.1", ps.port, templates=[center]) for _ in range(R)]
        pulled = [c.pull()[0] for c in clients]       # all pull before any commit
        for r in range(R):                            # rank-order commits
            clients[r].commit([local[r] - pulled[r]])
        final = ps.get_weights()[0]
        for c in clients:
            c.close()
    finally:
        ps.stop()
    np.testing.assert_allclose(final, new_center_sync, rtol=1e-4, atol=1e-5)
